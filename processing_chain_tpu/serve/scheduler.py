"""Queue-draining scheduler: weighted fairness, singleflight, waves.

Worker threads pull from the DurableQueue and execute through the
engine's JobRunner — so serve executions get exactly the batch chain's
crash sentinels, store commits, provenance and telemetry, not a
parallel implementation of them.

Scheduling policy, in order:

  1. **Fairness** — stride scheduling over (tenant × priority class)
     flows. Each flow carries a virtual `pass`; dispatching from a flow
     advances its pass by `SCALE / (tenant_weight × class_weight)`.
     The next seed job always comes from the flow with the smallest
     pass: an interactive flow (weight 16) drains ~16x the rate of a
     bulk flow (weight 1) under contention, yet every flow's pass
     eventually becomes the smallest — nothing starves. New flows join
     at the current minimum pass, so arriving tenants neither wait out
     history nor monopolize the near future.
  2. **Wave packing** — after the fairness pick chooses WHO goes next,
     the wave fills with other queued units sharing the seed's bucket
     key (parallel/p03_batch geometry semantics) regardless of tenant
     or request, up to `wave_width`: device sharing is free capacity,
     not a fairness question.
  3. **Singleflight** — `queue.claim` moves records queued→running
     under the queue lock; a plan hash can never be executing twice,
     and enqueue-time attachment (queue.py) means overlapping requests
     were already riding the one record.

Execution failures are CLASSIFIED before they are settled
(docs/SERVE.md "Failure taxonomy"): transient ones (disk pressure,
device unavailable, OOM) retry up to `max_attempts` with exponential
backoff + jitter — the record's `not_before` keeps a deterministic
failure from burning its whole attempts budget in milliseconds —
while permanent ones (bad params, corrupt SRC) QUARANTINE the plan
with forensics instead of retrying. The store stays the truth for what
actually completed: a commit that landed before a crash is a warm hit,
never a re-execution.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Optional

from .. import telemetry as tm
from ..engine.jobs import Job, JobRunner
from ..store import runtime as store_runtime
from ..utils import lockdebug
from ..utils.log import get_logger
from ..utils.runner import ChainError
from .api import PRIORITIES
from .executors import _unit_of
from .queue import DurableQueue, JobRecord

_INFLIGHT = tm.gauge(
    "chain_serve_inflight", "units currently executing in the serve scheduler"
)

#: exception types whose retry-worthiness is knowable without a
#: ChainError kind tag: environmental failures may succeed later;
#: programming/data errors will not.
_TRANSIENT_TYPES = (OSError, MemoryError, TimeoutError, ConnectionError)
_PERMANENT_TYPES = (ValueError, TypeError, KeyError, AssertionError)


#: the declared failure kinds (docs/SERVE.md "Failure taxonomy"):
#: `poison` is `permanent` plus a fleet-wide verdict about the SRC
#: BYTES — the settle path additionally quarantines the record's SRC
#: content digest so every plan referencing it fails fast.
FAILURE_KINDS = ("transient", "permanent", "poison")


def classify_failure(exc: BaseException) -> str:
    """'transient', 'permanent' or 'poison' for one execution failure.
    Walks the cause/context chain (the wave barrier and the runner both
    wrap the executor's exception): an explicit `kind` attribute
    anywhere wins — ChainError and io.medialib.MediaError both carry
    one — otherwise the first recognizably-environmental or
    recognizably-deterministic type decides. Unknown shapes default to
    transient — the attempts budget still bounds them, and retrying an
    unknown is cheaper than quarantining a recoverable plan."""
    seen: set = set()
    cursor: Optional[BaseException] = exc
    verdict: Optional[str] = None
    while cursor is not None and id(cursor) not in seen:
        seen.add(id(cursor))
        kind = getattr(cursor, "kind", None)
        if kind in FAILURE_KINDS:
            return kind
        if verdict is None:
            if isinstance(cursor, _TRANSIENT_TYPES):
                verdict = "transient"
            elif isinstance(cursor, _PERMANENT_TYPES):
                verdict = "permanent"
        cursor = cursor.__cause__ or cursor.__context__
    return verdict or "transient"


def extract_src_digest(exc: BaseException) -> Optional[str]:
    """The convicting SRC content digest a `poison` verdict carries
    (ChainError(src_digest=…), docs/ROBUSTNESS.md), walked through the
    cause/context chain like classify_failure. None = unattributed —
    the settle path then falls back to solo-wave blame."""
    seen: set = set()
    cursor: Optional[BaseException] = exc
    while cursor is not None and id(cursor) not in seen:
        seen.add(id(cursor))
        digest = getattr(cursor, "src_digest", None)
        if digest:
            return str(digest)
        cursor = cursor.__cause__ or cursor.__context__
    return None

#: stride virtual-time scale (anything ≫ max weight works; power of two
#: keeps the passes exact in floats far past any realistic uptime)
_SCALE = 1 << 20


class StridePicker:
    """Stride scheduling over (tenant, priority) flows. Not thread-safe
    by itself — the scheduler serializes picks under its own lock."""

    def __init__(self, tenant_weights: Optional[dict] = None) -> None:
        self._weights = dict(tenant_weights or {})
        self._pass: dict[tuple, float] = {}
        #: virtual time = the pass of the most recently dispatched flow.
        #: Joining/rejoining flows enter at vtime, not at the minimum
        #: over every flow EVER seen — a pass frozen while its flow sat
        #: idle would otherwise hand the next arrival (or the returning
        #: flow itself) a catch-up burst that starves every active
        #: tenant until the stale gap is consumed.
        self._vtime = 0.0

    def _stride(self, flow: tuple) -> float:
        tenant, priority = flow
        weight = max(float(self._weights.get(tenant, 1.0)), 1e-6)
        return _SCALE / (weight * PRIORITIES.get(priority, 1))

    def pick(self, queued: list[JobRecord]) -> JobRecord:
        """Choose the next seed among queued records (must be non-empty)
        and advance its flow's pass."""
        flows: dict[tuple, JobRecord] = {}
        for record in queued:  # queued is enqueue-ordered: first wins
            flow = (record.tenant, record.priority)
            if flow not in flows:
                flows[flow] = record
        for flow in flows:
            if flow not in self._pass or self._pass[flow] < self._vtime:
                # new flow, or one whose pass froze while it was idle
                # and vtime moved on: (re)join at NOW. For flows that
                # stayed active this is a no-op — vtime is the minimum
                # pass by construction, so active passes never trail it.
                self._pass[flow] = self._vtime
        chosen = min(
            flows,
            key=lambda f: (self._pass[f], -PRIORITIES.get(f[1], 1), f[0]),
        )
        self._vtime = self._pass[chosen]
        self._pass[chosen] += self._stride(chosen)
        return flows[chosen]


#: how long a wave member will wait for its siblings to ARRIVE at the
#: barrier. All members are submitted to a pool exactly as wide as the
#: wave, so arrival is thread-startup time (milliseconds) — a miss on
#: this timeout means a sibling job died before reaching its fn, and
#: waiting longer would deadlock the wave forever.
_ARRIVAL_TIMEOUT_S = 60.0


class _WaveBarrier:
    """One shared execution for a batch of engine Jobs: every planned
    job's fn arrives here; the LAST arrival (all sentinels down by then)
    runs the executor's batch once; everyone returns together. A batch
    failure surfaces in every member job, so the runner's fail-fast and
    the per-job telemetry stay truthful.

    Deadlock-proofing: waiters block UNBOUNDED only on the compute
    phase (which is genuinely unbounded — a device wave takes as long
    as it takes) but only BOUNDED on the arrival phase. If a sibling
    dies before reaching produce() (any unexpected pre-fn failure), the
    remaining members time out, fail their jobs, and the scheduler's
    settle path re-queues against the store instead of hanging the
    worker thread forever."""

    def __init__(self, executor, units: list, outputs: list) -> None:
        self._executor = executor
        self._units = units
        self._outputs = outputs
        self._lock = lockdebug.make_lock("serve_wave")
        self._expected: int = len(units)  # guarded-by: _lock
        self._arrived: int = 0            # guarded-by: _lock
        self._all_arrived = threading.Event()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None

    def produce(self) -> None:
        with self._lock:
            self._arrived += 1
            run_it = self._arrived == self._expected
        if run_it:
            self._all_arrived.set()
            try:
                self._executor.run_batch(self._units, self._outputs)
            except BaseException as exc:  # noqa: BLE001 - must release waiters
                self._error = exc
                raise
            finally:
                self._done.set()
        else:
            if not self._all_arrived.wait(timeout=_ARRIVAL_TIMEOUT_S):
                with self._lock:
                    arrived, expected = self._arrived, self._expected
                raise RuntimeError(
                    f"wave barrier: only {arrived}/{expected} members "
                    "arrived — a sibling job died before reaching its fn; "
                    "failing this member instead of deadlocking"
                )
            self._done.wait()
            if self._error is not None:
                raise RuntimeError(
                    f"wave execution failed: {self._error!r}"
                ) from self._error


#: worker idle-poll bounds: fast right after a dispatch (work begets
#: work), decaying when the queue stays empty (an idle fleet must not
#: hammer the shared queue lock)
_IDLE_MIN_S = 0.01
_IDLE_MAX_S = 0.25

#: how deep the same-bucket fill looks into the queued snapshot; with
#: cost budgets a skip no longer ends the scan, so the window must be
#: bounded — the packing pass runs under the scheduler lock
_FILL_SCAN_CAP = 256


class Scheduler:
    """Worker threads draining the queue (see module doc for policy)."""

    def __init__(
        self,
        queue: DurableQueue,
        executor,
        artifacts_root: str,
        workers: int = 2,
        wave_width: int = 4,
        tenant_weights: Optional[dict] = None,
        max_attempts: int = 2,
        retry_base_s: float = 0.25,
        retry_cap_s: float = 30.0,
        wave_budget_s: Optional[float] = None,
        on_done: Optional[Callable[[JobRecord], None]] = None,
        on_failed: Optional[Callable[[JobRecord], None]] = None,
    ) -> None:
        self.queue = queue
        self.executor = executor
        self.artifacts_root = artifacts_root
        self.workers = max(1, int(workers))
        self.wave_width = max(1, int(wave_width))
        #: cost-aware packing (serve/cost.py): fill waves until the
        #: members' PREDICTED seconds reach this budget instead of
        #: stopping at a unit count — None keeps count-based packing
        self.wave_budget_s = (
            float(wave_budget_s) if wave_budget_s else None
        )
        self.max_attempts = max(1, int(max_attempts))
        self.retry_base_s = max(0.0, float(retry_base_s))
        self.retry_cap_s = max(self.retry_base_s, float(retry_cap_s))
        self.on_done = on_done or (lambda record: None)
        self.on_failed = on_failed or (lambda record: None)
        self._picker = StridePicker(tenant_weights)
        self._lock = lockdebug.make_lock("serve_sched")
        self._wake = threading.Event()
        self._stop = threading.Event()
        #: drain gate (docs/SERVE.md "Draining a replica"): while set,
        #: _next_batch claims nothing — in-flight waves finish, queued
        #: work stays for peers or for resume()
        self._draining = threading.Event()
        self._threads: list[threading.Thread] = []

    # --------------------------------------------------------- lifecycle

    def start(self) -> "Scheduler":
        if not self._threads:
            for i in range(self.workers):
                t = threading.Thread(
                    target=self._worker, name=f"chain-serve-worker-{i}",
                    daemon=True,
                )
                t.start()
                self._threads.append(t)
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = []

    def notify(self) -> None:
        """New work arrived (submit path); wake idle workers now."""
        self._wake.set()

    def drain(self) -> None:
        """Stop claiming new work; waves already dispatched finish and
        settle normally (their leases stay live). Idempotent."""
        self._draining.set()

    def resume(self) -> None:
        """Leave draining: claiming resumes with the next wake."""
        self._draining.clear()
        self._wake.set()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # --------------------------------------------------------- main loop

    def _worker(self) -> None:
        log = get_logger()
        idle_wait = _IDLE_MIN_S
        while not self._stop.is_set():
            try:
                batch = self._next_batch()
                if not batch:
                    # idle backoff: stay responsive just after real work
                    # (a settling wave often unblocks more), decay to
                    # ~250 ms when the queue stays empty — an idle
                    # replica fleet must not spin N workers hot against
                    # the queue lock. A submit's notify() short-circuits
                    # the wait either way.
                    if self._wake.wait(timeout=idle_wait):
                        self._wake.clear()
                        idle_wait = _IDLE_MIN_S
                    else:
                        idle_wait = min(idle_wait * 2.0, _IDLE_MAX_S)
                    continue
                idle_wait = _IDLE_MIN_S
                self._dispatch(batch)
            except BaseException:  # noqa: BLE001 - a worker must survive anything
                # _next_batch is INSIDE the guard: a poisoned queue record
                # (e.g. unparseable params reaching bucket_key) must not
                # kill the worker. Back off briefly so a persistently bad
                # record cannot turn the loop into a log-spinning hot path.
                log.exception("serve scheduler: worker iteration crashed")
                self._stop.wait(timeout=0.5)

    def _next_batch(self) -> list[JobRecord]:
        """Fairness seed + same-bucket fill, all claimed atomically:
        the claimed batch is the seed plus up to `wave_width - 1` other
        queued records sharing its bucket key (p03_batch geometry
        semantics — same key ⟺ same compiled device step), in enqueue
        order. With `wave_budget_s` set, the fill also balances
        PREDICTED seconds (the records' `cost_s`, serve/cost.py): a
        member that would push the wave past the budget is skipped in
        favor of later, lighter same-bucket units — waves stop being
        "4 units" and start being "~budget seconds", which is what
        keeps one all-heavy wave from defining the e2e tail. The fill
        scans only a bounded window instead of packing the entire
        snapshot into waves to keep one — a deep queue must not cost
        O(queue) key calls under the scheduler lock per dispatch."""

        def safe_key(record: JobRecord):
            # totality guaranteed HERE, not re-audited per executor: one
            # record whose unit an executor's bucket_key cannot parse
            # must degrade to unbatchable (solo wave), never abort the
            # packing pass every worker runs over the queued snapshot
            try:
                return self.executor.bucket_key(record.unit)
            except Exception:  # noqa: BLE001 - any key failure = unbatchable
                return None

        if self._draining.is_set():
            # draining: never claim — queued work is for peers (or for
            # resume()); waves already in flight settle on their own
            return []
        with self._lock:
            queued = self.queue.queued_snapshot()
            if not queued:
                return []
            seed = self._picker.pick(queued)
            wave = [seed]
            wave_cost = seed.cost_s
            seed_key = safe_key(seed)
            if seed_key is not None:  # None = unbatchable: solo wave
                for record in queued[:_FILL_SCAN_CAP]:
                    if len(wave) >= self.wave_width:
                        break
                    if (record.job_id == seed.job_id
                            or safe_key(record) != seed_key):
                        continue
                    if (self.wave_budget_s is not None
                            and wave_cost + record.cost_s
                            > self.wave_budget_s):
                        continue  # too heavy for THIS wave; a lighter
                        # same-bucket unit further on may still fit
                    wave.append(record)
                    wave_cost += record.cost_s
            return self.queue.claim([r.job_id for r in wave])

    # --------------------------------------------------------- execution

    def _dispatch(self, batch: list[JobRecord]) -> None:
        """Execute one claimed batch. EVERY claimed record leaves this
        method settled — completed, requeued, or failed: an exception
        anywhere (planning, a mid-loop persist error, the runner itself)
        falls through to the settle path, because a claimed record left
        in state 'running' with no owner would hang its requests forever
        and soak up attaching newcomers."""
        settled: set[str] = set()
        _INFLIGHT.inc(len(batch))
        # the wave's predicted mass — what cost-aware packing balances;
        # the pack bench (tools serve-soak --pack-bench) grades packing
        # policies from exactly these records
        tm.emit("serve_wave", units=len(batch),
                predicted_s=round(sum(r.cost_s for r in batch), 4))
        try:
            os.makedirs(self.artifacts_root, exist_ok=True)
            runner = JobRunner(parallelism=len(batch), name="serve")
            # records per label — a LIST, not a single slot: the
            # cross-replica enqueue race can mint twin records for one
            # plan (docs/SERVE.md "eventual dedup"), and both twins can
            # be claimed into one wave (same plan ⟹ same bucket key).
            # They share one execution (JobRunner dedups the identical
            # job), but EVERY claimed record must settle — a twin left
            # in 'running' keeps its lease renewed forever and hangs
            # its requests. The trace-completeness chaos invariant is
            # what exposed this.
            by_label: dict[str, list[JobRecord]] = {}
            out_of: dict[str, str] = {}
            for record in batch:
                label = f"serve:{record.unit['pvs_id']}:{record.plan_hash[:8]}"
                by_label.setdefault(label, []).append(record)
                out_of[label] = os.path.join(
                    self.artifacts_root, record.output
                )
            for label, records in by_label.items():
                request_ids = list(dict.fromkeys(
                    r for rec in records for r in rec.requests))
                trace_ids = list(dict.fromkeys(
                    t for rec in records for t in rec.trace_ids))
                runner.add(Job(
                    label=label,
                    output_path=out_of[label],
                    fn=None,  # bound below, once planning has spoken
                    plan=records[0].plan,
                    provenance={
                        "tenant": records[0].tenant,
                        "priority": records[0].priority,
                        "executor": self.executor.kind,
                        "replica": self.queue.replica,
                    },
                    request_ids=tuple(request_ids),
                    trace_ids=tuple(trace_ids),
                ))
            planned = {job.label for job in runner.jobs}
            # store warm path: should_run already verified+materialized
            # the artifact for skipped jobs — complete them right now
            for label, records in by_label.items():
                if label not in planned:
                    for record in records:
                        self._complete(record, settled, warm=True)
            if not planned:
                return
            # the wave holds exactly the PLANNED members: a warm-skipped
            # unit must neither be recomputed nor waited for
            wave = _WaveBarrier(
                self.executor,
                [_unit_of(by_label[j.label][0].unit) for j in runner.jobs],
                [out_of[j.label] for j in runner.jobs],
            )
            for job in runner.jobs:
                job.fn = wave.produce
            runner.run()
            for label in planned:
                for record in by_label[label]:
                    self._complete(record, settled)
        except Exception as exc:
            self._settle_failure(batch, settled, exc)
        finally:
            _INFLIGHT.dec(len(batch))

    def _complete(self, record: JobRecord, settled: set,
                  warm: bool = False) -> None:
        done = self.queue.complete(record.job_id, warm=warm)
        settled.add(record.job_id)
        if done is not None:
            self.on_done(done)

    def _backoff_s(self, attempts: int) -> float:
        """Exponential retry backoff with ±25% jitter: attempt k waits
        ~base·2^k (capped). Without it a deterministic transient-looking
        failure is re-eligible instantly and burns its whole attempts
        budget in milliseconds; the jitter keeps a replica fleet from
        retrying a shared record in lockstep."""
        delay = min(self.retry_cap_s,
                    self.retry_base_s * (2.0 ** max(0, attempts)))
        return delay * (0.75 + 0.5 * random.random())

    def _settle_failure(self, batch: list[JobRecord], settled: set,
                        exc: Exception) -> None:
        """After a batch failure the STORE is the truth: members whose
        commit landed are done. The rest settle by failure CLASS
        (classify_failure): permanent failures quarantine the plan with
        forensics — retrying a determined outcome is waste — while
        transient ones retry under the attempts budget, re-eligible
        only after an exponential backoff (the record's not_before). A
        wave failure is collective, but completion is not — and neither
        is BLAME: a permanent verdict is applied only when exactly one
        unsettled member could have caused it, because quarantining a
        whole wave for one poisoned sibling would park healthy plans
        behind an operator re-arm. Ambiguous permanent failures retry
        like transients (jittered backoff desynchronizes the members,
        so a truly poisoned unit soon fails a wave it owns alone and
        quarantines then; the attempts budget terminates the rest).
        Per-record settling is itself fenced — one record's persist
        error must not strand its siblings in 'running'."""
        log = get_logger()
        store = store_runtime.active()
        kind = classify_failure(exc)
        # an attributed poison verdict names the convicting digest on
        # the exception — wave packing then never decides who parks
        poison_digest = extract_src_digest(exc) if kind == "poison" \
            else None
        suspects = sum(1 for r in batch if r.job_id not in settled)
        for record in batch:
            if record.job_id in settled:
                continue
            try:
                committed = False
                if store is not None:
                    try:
                        committed = store.lookup(record.plan_hash) is not None
                    except Exception:  # noqa: BLE001 - store probe is best-effort
                        committed = False
                if committed:
                    self._complete(record, settled)
                    continue
                # blame attribution: a deterministic verdict parks a
                # record when (a) it owned the failed wave alone, or
                # (b) the poison verdict NAMES this record's SRC digest
                # (extract_src_digest) — an attributed conviction from
                # any wave shape. A mis-attributed sibling keeps
                # retrying under backoff; a poison record whose budget
                # is spent quarantines anyway (terminal either way, and
                # 'failed' would hide it from the operator's quarantine
                # surface) but convicts NO digest — fleet-wide blame
                # needs solo ownership or an attributed verdict. An
                # EXONERATED record — the verdict names a different
                # digest — never rides that clause: it settles 'failed'
                # like any spent budget instead of parking a healthy
                # plan behind an operator re-arm.
                attributed = (
                    kind == "poison" and poison_digest is not None
                    and record.src_digest == poison_digest
                )
                exonerated = (
                    kind == "poison" and poison_digest is not None
                    and record.src_digest is not None
                    and record.src_digest != poison_digest
                )
                budget_spent = record.attempts + 1 >= self.max_attempts
                if kind in ("permanent", "poison") and \
                        (suspects == 1 or attributed or
                         (kind == "poison" and budget_spent
                          and not exonerated)):
                    quarantined = self.queue.quarantine(
                        record.job_id, error=repr(exc), kind=kind,
                    )
                    settled.add(record.job_id)
                    if quarantined is not None:
                        log.error("serve: job %s quarantined (%s "
                                  "failure): %r", record.job_id, kind, exc)
                        self.on_failed(quarantined)
                        if kind == "poison" and record.src_digest and \
                                ((suspects == 1 and not exonerated)
                                 or attributed):
                            # the verdict is about the SRC BYTES, not
                            # this one plan: quarantine the content
                            # digest fleet-wide and fail every queued
                            # sibling referencing it — one hostile
                            # upload must burn ONE attempts budget,
                            # not one per (HRC × tenant × replica)
                            swept = self.queue.poison_src(
                                record.src_digest,
                                src=record.unit.get("src"),
                                error=repr(exc), by_job=record.job_id,
                            )
                            for sibling in swept:
                                settled.add(sibling.job_id)
                                self.on_failed(sibling)
                    continue
                requeue = record.attempts + 1 < self.max_attempts
                failed = self.queue.fail(
                    record.job_id, error=repr(exc), requeue=requeue,
                    backoff_s=self._backoff_s(record.attempts) if requeue
                    else 0.0,
                    kind=kind,
                )
                settled.add(record.job_id)
                if failed is not None and not requeue:
                    log.error("serve: job %s failed permanently: %r",
                              record.job_id, exc)
                    self.on_failed(failed)
            except Exception:  # noqa: BLE001 - settle the rest regardless
                log.exception("serve: could not settle job %s",
                              record.job_id)
        self._wake.set()  # requeued members should not wait out the idle poll

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Test/soak helper: True once nothing is queued or running."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            counts = self.queue.counts()
            if not counts.get("queued") and not counts.get("running"):
                return True
            time.sleep(0.02)
        return False
