"""The chain-serve daemon: HTTP front door, queue, scheduler, store.

One `ChainServeService` owns the whole serving stack rooted at one
directory:

    root/
      queue/jobs/*.json        durable job records (+ .inprogress sentinels)
      requests/*.json          request records (atomic rewrites)
      artifacts/               materialized outputs (store-hardlinked)
      store/                   the content-addressed artifact store
      serve-info.json          {pid, port, url} for operators/scripts

HTTP surface — ONE LiveServer (telemetry/live.py route registry), so
the observability endpoints and the serving API share a port, a thread
pool and a shutdown story:

    GET  /healthz /metrics /status     the PR 3 observability triple
         (/status?request=<id> scopes the serve section to one request)
    POST /v1/requests                  submit a processing request
    GET  /v1/requests                  list requests
    GET  /v1/requests/<id>             one request with per-unit states
    GET  /v1/artifacts/<plan_hash>     artifact bytes from the store

Identity and dedup: a unit's plan hash (store/keys) is its name
everywhere — queue dedup key, store commit key, artifact URL. Request
overlap therefore collapses BEFORE execution: a unit already in the
store answers warm in milliseconds; one queued or running attaches; and
only genuinely novel plans execute, exactly once (docs/SERVE.md).

The engine's global store slot (store/runtime) is configured to the
serve store at construction: one service per process at a time — or
several REPLICAS of one root in one process (the fleet-shaped tests),
which share the same store root and so agree on the slot.

Multi-replica: any number of services (in any number of processes) may
share one root. Queue ownership is lease-fenced (serve/queue.py), and
the maintenance tick propagates peer executions into this replica's
request bookkeeping (docs/SERVE.md "Running multiple replicas").
"""

from __future__ import annotations

import json
import os
import re
import secrets
import threading
import time
from typing import NamedTuple, Optional

from .. import telemetry as tm
from ..telemetry import alerts as alerts_mod
from ..telemetry import catalog as tm_catalog
from ..telemetry import watchdog as tm_watchdog
from ..store import heat as store_heat
from ..store import runtime as store_runtime
from ..store.store import StoreCorruption
from ..telemetry import live
from ..utils import lockdebug
from ..utils.fsio import atomic_write_json
from ..utils.log import get_logger
from . import api, autoscale, cost
from .executors import make_executor
from .pressure import StorePressure
from .queue import DurableQueue, owner_process_dead, owner_stamp
from .scheduler import Scheduler

_REQ_TOTAL = tm.counter(
    "chain_serve_requests_total", "serve requests by terminal disposition",
    ("state",),
)
_UNITS = tm.counter(
    "chain_serve_units_total", "per-PVS units by enqueue outcome",
    ("outcome",),
)
_REQ_SECONDS = tm.histogram(
    "chain_serve_request_seconds", "request accept-to-complete latency"
)
_WARM_REQ_SECONDS = tm.histogram(
    "chain_serve_warm_request_seconds",
    "latency of requests answered entirely from the store",
)
_E2E_SECONDS = tm.histogram(
    "chain_serve_e2e_seconds",
    "request end-to-end latency (submit to done), per tenant/priority "
    "— the SLO layer's third phase next to queue-wait and execution",
    ("tenant", "priority"),
    buckets=tm_catalog.SLO_LATENCY_BUCKETS,
)
_READ_TTFB_SECONDS = tm.histogram(
    "chain_serve_read_ttfb_seconds",
    "artifact read time-to-first-byte (request to headers+first chunk "
    "on the wire; a 304 observes here only), per tenant/size class — "
    "graded against READ_SLO_BANDS by the fleet view",
    ("tenant", "size_class"),
    buckets=tm_catalog.READ_LATENCY_BUCKETS,
)
_READ_SECONDS = tm.histogram(
    "chain_serve_read_seconds",
    "artifact full-stream read latency (request to last byte), per "
    "tenant/size class",
    ("tenant", "size_class"),
    buckets=tm_catalog.READ_LATENCY_BUCKETS,
)

_HASH_LEN = 64  # sha256 hex


class _DoneState:
    """Stand-in for a queue record the queue no longer tracks: settled."""

    state = "done"


_DONE_SENTINEL = _DoneState()


class _PlanSettled(NamedTuple):
    """Record stand-in for cross-replica completion sweeps: all the
    waiter bookkeeping needs is the plan hash (and, for failures, the
    error text)."""

    plan_hash: str
    error: Optional[str] = None


class ChainServeService:
    """Composition root of the serve daemon (see module doc)."""

    def __init__(
        self,
        root: str,
        port: int = 0,
        host: Optional[str] = None,
        executor: str = "synthetic",
        workers: int = 2,
        wave_width: int = 4,
        store_root: Optional[str] = None,
        store_budget_bytes: Optional[int] = None,
        store_tiers: Optional[str] = None,
        tenant_weights: Optional[dict] = None,
        max_attempts: int = 2,
        request_retention: int = 10_000,
        replica: Optional[str] = None,
        lease_s: float = 15.0,
        poll_s: float = 1.0,
        info_path: Optional[str] = None,
        wave_budget_s: Optional[float] = None,
        admission_budget_s: Optional[float] = None,
        tenant_budget_s: Optional[float] = None,
        cost_calibrate: bool = False,
        control_interval_s: float = 10.0,
        alert_window_scale: float = 1.0,
    ) -> None:
        self.root = os.path.abspath(root)
        self.artifacts_root = os.path.join(self.root, "artifacts")
        self.requests_dir = os.path.join(self.root, "requests")
        for d in (self.root, self.artifacts_root, self.requests_dir):
            os.makedirs(d, exist_ok=True)
        # the serve surface IS telemetry: /metrics must render, job
        # accounting must count — enable before anything registers
        tm.enable()
        self.executor = make_executor(executor)
        self.store = store_runtime.configure(
            store_root or os.path.join(self.root, "store"),
            tiers=store_tiers,
        )
        self.queue = DurableQueue(
            os.path.join(self.root, "queue"),
            replica=replica, lease_s=lease_s,
        )
        self.replica = self.queue.replica
        #: the read-path flight recorder (store/heat.py): per-replica
        #: access journal + eviction-regret detector, shared with the
        #: GC pressure hook so evictions land with forensics
        self.heat = store_heat.HeatLedger(
            self.store.root, replica=self.replica
        )
        # the device-plane flight recorder (parallel/meshobs.py): the
        # wave executors record into this root's journal under this
        # replica's name — /fleet merges the per-replica files.
        # Imported lazily: the parallel package pulls in jax, which a
        # synthetic-only service must not pay at module import.
        from ..parallel import meshobs

        meshobs.attach_journal(
            meshobs.mesh_dir(self.root), replica=self.replica
        )
        self.poll_s = max(0.05, float(poll_s))
        self.info_path = info_path or os.path.join(
            self.root, "serve-info.json"
        )
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        #: request-doc stat signatures for the orphan sweep; touched
        #: only by the maintenance thread
        self._req_stat: dict[str, tuple] = {}
        self.request_retention = max(1, int(request_retention))
        self._lock = lockdebug.make_lock("serve_service")
        #: request docs; each active one carries a non-persisted
        #: "_pending" set of plan hashes still outstanding, maintained by
        #: submit/_on_job_done so completion checks never re-verify the
        #: store under this lock
        self._requests: dict[str, dict] = {}   # guarded-by: _lock
        #: plan hash -> request ids still waiting on it
        self._plan_waiters: dict[str, set] = {}  # guarded-by: _lock
        self.pressure = StorePressure(
            self.store, store_budget_bytes, self.active_plans,
            heat=self.heat,
        )
        #: cost-aware serving knobs (docs/SERVE.md "Cost-aware
        #: scheduling & admission"); budgets of None disable each gate
        self.admission_budget_s = (
            float(admission_budget_s) if admission_budget_s else None
        )
        self.tenant_budget_s = (
            float(tenant_budget_s) if tenant_budget_s else None
        )
        self.cost_ledger = cost.CostLedger()
        #: periodic per-host refit of the cost-prediction scale from
        #: the ledger's observed/predicted ratio ring (maintenance
        #: tick; docs/SERVE.md "Cost-aware scheduling & admission")
        self.cost_calibrate = bool(cost_calibrate)
        # ------ the SLO control loop (docs/TELEMETRY.md "Alerting &
        # the scale signal"): one shared per-replica alert journal
        # carries both alert lifecycle records and scale-signal
        # records, so fleet-doctor reads one plane and scale decisions
        # sit next to the alerts that motivated them. The engine and
        # the advisor are re-graded by the maintenance tick, throttled
        # to control_interval_s; window_scale compresses every burn
        # window/hold uniformly (the soak harness squeezes hours into
        # seconds without forking the rule declarations).
        self.control_interval_s = max(0.05, float(control_interval_s))
        self.alert_journal = alerts_mod.AlertJournal(
            alerts_mod.alerts_dir(self.root), self.replica
        )
        self.alert_engine = alerts_mod.AlertEngine(
            self.root, self.replica, journal=self.alert_journal,
            window_scale=alert_window_scale,
        )
        self.autoscale = autoscale.AutoscaleAdvisor(
            self.alert_journal, self.replica, workers=workers,
            window_scale=alert_window_scale,
        )
        self._next_control = 0.0  # monotonic deadline; maintenance thread
        self.scheduler = Scheduler(
            self.queue, self.executor, self.artifacts_root,
            workers=workers, wave_width=wave_width,
            tenant_weights=tenant_weights, max_attempts=max_attempts,
            wave_budget_s=wave_budget_s,
            on_done=self._on_job_done, on_failed=self._on_job_failed,
        )
        #: graceful drain (docs/SERVE.md "Draining a replica"): while
        #: True the scheduler claims nothing; flipped by POST /v1/drain
        #: or SIGUSR1, reported by /healthz and serve-info
        self._draining = False               # guarded-by: _lock
        self._t0 = time.monotonic()
        routes = live.default_routes()
        routes.add("/v1/requests", self._h_requests, methods=("GET", "POST"))
        routes.add_prefix("/v1/requests/", self._h_request)
        routes.add_prefix("/v1/artifacts/", self._h_artifact)
        routes.add("/v1/drain", self._h_drain, methods=("POST",))
        # replaces the default liveness route: same shape, plus the
        # replica's drain state — a draining replica is still HEALTHY
        # (200), it is just not claiming work
        routes.add("/healthz", self._h_healthz)
        routes.add("/fleet", self._h_fleet)
        routes.add("/fleet/alerts", self._h_fleet_alerts)
        routes.add("/fleet/scale-signal", self._h_scale_signal)
        self.server = live.LiveServer(port, host=host, routes=routes)
        self._recover_requests()

    # --------------------------------------------------------- lifecycle

    def start(self) -> "ChainServeService":
        live.STATUS_PROVIDERS["serve"] = self._status_section
        self.server.start()
        self.queue.start_heartbeat()
        self.scheduler.start()
        self._poll_stop.clear()
        self._poll_thread = threading.Thread(
            target=self._maintenance_loop,
            name="chain-serve-maintenance", daemon=True,
        )
        self._poll_thread.start()
        self._write_info()
        get_logger().info(
            "chain-serve: %s (root %s, replica %s, executor %s, queue: %s)",
            self.server.url, self.root, self.replica, self.executor.kind,
            self.queue.recovery,
        )
        return self

    def _write_info(self) -> None:
        with self._lock:
            state = "draining" if self._draining else "ok"
        atomic_write_json(self.info_path, {
            "pid": os.getpid(),
            "port": self.server.port,
            "url": self.server.url,
            "root": self.root,
            "executor": self.executor.kind,
            "replica": self.replica,
            "replica_epoch": self.queue.replica_epoch,
            "store": self.store.root,
            "state": state,
        })

    def drain(self) -> dict:
        """Flip this replica to draining (docs/SERVE.md "Draining a
        replica"): the scheduler stops claiming, in-flight waves finish
        and settle normally, queued work stays for peers (or for
        resume()). Idempotent; reported by /healthz and serve-info."""
        with self._lock:
            was = self._draining
            self._draining = True
        if not was:
            self.scheduler.drain()
            self._write_info()
            tm.emit("serve_drain", replica=self.replica,
                    state="draining")
            get_logger().info("chain-serve: replica %s draining",
                              self.replica)
        return {"replica": self.replica, "state": "draining"}

    def resume(self) -> dict:
        """Rejoin after a drain: the scheduler claims again with its
        next wake. Idempotent."""
        with self._lock:
            was = self._draining
            self._draining = False
        if was:
            self.scheduler.resume()
            self._write_info()
            tm.emit("serve_drain", replica=self.replica, state="ok")
            get_logger().info("chain-serve: replica %s resumed",
                              self.replica)
        return {"replica": self.replica, "state": "ok"}

    def stop(self) -> None:
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=10.0)
            self._poll_thread = None
        self.scheduler.stop()
        self.server.stop()
        live.STATUS_PROVIDERS.pop("serve", None)
        # releases this replica's leases/liveness so a successor (or a
        # peer) can reclaim any still-running work immediately
        self.queue.close()
        # resolve-on-shutdown is wrong (the condition may persist);
        # close() just seals the journal handle
        self.alert_engine.close()
        self.heat.close()
        if self.store is not None:
            self.store.digests.save()

    # ------------------------------------------------------- maintenance

    def _maintenance_loop(self) -> None:
        """The replica-fleet tick: merge peer queue changes, steal dead
        leases (waking our scheduler for the reclaimed work), and settle
        requests whose plans a PEER replica finished — this replica's
        scheduler callbacks only fire for its own executions, so
        cross-replica completions propagate here."""
        while not self._poll_stop.wait(timeout=self.poll_s):
            try:
                result = self.queue.poll()
                if result.get("stolen") or result.get("changed"):
                    self.scheduler.notify()
                self._sweep_remote_settlements()
                self._adopt_orphan_requests()
                if self.cost_calibrate:
                    # cheap (a sorted copy of a bounded ring); a thin
                    # ring returns None and the scale stays put
                    self.cost_ledger.calibrate()
            except Exception:  # noqa: BLE001 - the tick must survive disk hiccups
                get_logger().exception(
                    "chain-serve: maintenance tick failed")
            try:
                # the SLO control loop rides the same tick but in its
                # own try: an alert-grading failure must not starve
                # lease stealing (and vice versa)
                self._control_tick()
            except Exception:  # noqa: BLE001 - grading must never kill the tick
                get_logger().exception(
                    "chain-serve: control tick failed")

    def _control_tick(self, force: bool = False) -> Optional[dict]:
        """Grade the alert rules and the scale signal against the
        current fleet view. Throttled to `control_interval_s` (the
        fleet scrape stats every replica's journals); `force=True`
        (the /fleet/scale-signal cold path) grades immediately."""
        now = time.monotonic()
        if not force and now < self._next_control:
            return None
        self._next_control = now + self.control_interval_s
        from ..telemetry import fleet as fleet_mod

        view = fleet_mod.fleet_view(self.root, timeout_s=2.0)
        result = self.alert_engine.evaluate(view)
        calibrated = int(cost.calibration().get("n", 0)) > 0
        return self.autoscale.evaluate(
            current_replicas=max(1, int(view.get("alive") or 0)),
            backlog=self.queue.backlog(),
            outstanding_s=self.queue.outstanding_cost(),
            active_alerts=result["active"],
            calibrated=calibrated,
        )

    def _sweep_remote_settlements(self) -> None:
        with self._lock:
            waited = list(self._plan_waiters)
        for plan_hash in waited:
            record = self.queue.by_plan(plan_hash)
            if record is not None and record.state == "done":
                self._on_job_done(record)
            elif record is not None and record.state in (
                    "failed", "quarantined"):
                self._on_job_failed(record)
            elif record is None and self._plan_is_done(plan_hash):
                # no queue record but the store holds verified bytes: a
                # peer executed and its record left our view
                self._on_job_done(_PlanSettled(plan_hash))

    def _adopt_orphan_requests(self) -> None:
        """An active request whose owning replica died UN-restarted
        would otherwise wait for some replica's next startup rescan to
        be adopted; the tick adopts it directly. Terminal docs are
        stat-cached (they cannot regress), active docs of LIVE owners
        are re-probed each tick — the probe is one os.kill(pid, 0)."""
        try:
            names = os.listdir(self.requests_dir)
        except OSError:
            return
        seen: set = set()
        for name in names:
            if not name.endswith(".json"):
                continue
            req_id = name[:-5]
            seen.add(req_id)
            with self._lock:
                if req_id in self._requests:
                    continue
            path = os.path.join(self.requests_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            sig = (st.st_mtime_ns, st.st_size)
            if self._req_stat.get(req_id) == sig:
                continue
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue  # mid-replace or poisoned: next tick retries
            if doc.get("state") != "active":
                self._req_stat[req_id] = sig  # terminal: never re-read
                continue
            if not owner_process_dead(doc.get("owner")):
                continue  # owner lives (or is unknowable): theirs
            # claim the doc under the fleet fence: re-check and restamp
            # in one exclusive section so two surviving replicas cannot
            # both adopt the same orphan off simultaneous ticks
            claimed = False
            try:
                with self.queue.exclusive():
                    with open(path) as f:
                        doc = json.load(f)
                    if doc.get("state") == "active" and \
                            owner_process_dead(doc.get("owner")):
                        prev = (doc.get("owner") or {}).get("replica")
                        doc["owner"] = owner_stamp(self.replica)
                        atomic_write_json(path, doc, durable=True,
                                          sort_keys=True)
                        claimed = True
            except (OSError, ValueError):
                continue
            if not claimed:
                continue
            get_logger().warning(
                "chain-serve: adopting orphaned request %s from dead "
                "replica %r", req_id, prev)
            self._adopt_active(doc)
        # retention pruning deletes docs from disk; their stat entries
        # must not outlive them (an always-on daemon leaks otherwise)
        for req_id in list(self._req_stat):
            if req_id not in seen:
                self._req_stat.pop(req_id, None)

    def __enter__(self) -> "ChainServeService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------------------------------------------------- recovery

    def _recover_requests(self) -> None:
        """Reload persisted request records. Finished ones are indexed;
        every active one is ADOPTED (`_adopt_active`): waiters re-armed,
        units whose job record vanished (a crash between request
        persist and unit enqueue) re-enqueued, requests against
        quarantined plans failed with the forensics, and requests whose
        every unit meanwhile completed finalized now."""
        try:
            names = sorted(os.listdir(self.requests_dir))
        except OSError:
            names = []
        recovered_active = []
        with self._lock:
            for name in names:
                if not name.endswith(".json"):
                    continue
                path = os.path.join(self.requests_dir, name)
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    get_logger().warning(
                        "serve: unreadable request record %s; skipping", path
                    )
                    continue
                if doc.get("state") == "active":
                    recovered_active.append(doc)  # adopted below
                else:
                    self._requests[doc["request"]] = doc
        for doc in recovered_active:
            self._adopt_active(doc)

    def _adopt_active(self, doc: dict) -> None:
        """Take responsibility for one active request record: re-arm
        its plan waiters, re-create lost enqueues, fail it against
        quarantined plans, finalize it if everything already settled.
        Restamps the ownership so peers stop probing it. Called at
        recovery (every active doc on disk) and from the maintenance
        tick (docs whose owning replica process died un-restarted)."""
        req_id = doc["request"]
        quarantine_error: Optional[str] = None
        with self._lock:
            if req_id in self._requests:
                return
            doc["owner"] = owner_stamp(self.replica)
            doc["_pending"] = set()
            self._requests[req_id] = doc
            for unit_doc in doc["units"].values():
                plan_hash = unit_doc["plan"]
                if self._plan_is_done(plan_hash):
                    continue
                doc["_pending"].add(plan_hash)
                self._plan_waiters.setdefault(plan_hash, set()).add(req_id)
                record = self.queue.by_plan(plan_hash)
                if record is None:
                    # enqueue lost to the crash: re-create it from the
                    # request record (it carries the full unit payload)
                    self.queue.enqueue(
                        plan_hash,
                        unit_doc["planPayload"],
                        unit_doc["unit"],
                        doc["tenant"], doc["priority"], req_id,
                        unit_doc["output"], trace_id=doc.get("trace"),
                        cost_s=float(unit_doc.get("cost_s", 0.0) or 0.0),
                        src_digest=unit_doc.get("src_digest")
                        or self.executor.src_digest(unit_doc["unit"]),
                    )
                elif record.state == "quarantined":
                    # the plan failed PERMANENTLY while the request
                    # never saw the verdict: deliver it now instead of
                    # re-arming work whose outcome is determined
                    # (docs/SERVE.md "Failure taxonomy")
                    quarantine_error = (record.error or
                                       "plan quarantined after permanent "
                                       "failure")
                else:
                    # the record may be 'failed' (crash before the
                    # request saw the failure) or 'done' with the
                    # artifact since evicted (the store check above said
                    # not-done): re-arm it, mirroring submit — otherwise
                    # nothing ever runs this plan and the adopted
                    # request pins it in 'active' forever. rearm is a
                    # no-op on queued/running records.
                    self.queue.rearm(record.job_id)
        if quarantine_error is not None:
            with self._lock:
                if doc["state"] == "active":
                    doc["state"] = "failed"
                    doc["done_at"] = time.time()
                    doc["error"] = quarantine_error
            self._persist_request(doc)
            _REQ_TOTAL.labels(state="failed").inc()
            tm.emit("serve_request_done", request=req_id,
                    trace_id=doc.get("trace"), status="failed",
                    error=quarantine_error)
            return
        self._persist_request(doc)  # the new owner stamp, durably
        self._check_request_done(req_id)

    # ------------------------------------------------------- submissions

    def submit(self, payload: object) -> dict:
        """Validate + enqueue one request; returns the acceptance doc.
        Raises api.RequestError on a bad document (handler → 400)."""
        t0 = time.perf_counter()
        try:
            normalized = api.validate_request(payload)
            # executor-specific params validate at the front door too: a
            # unit the executor cannot parse must 400 here, not become a
            # durable queue record that poisons the scheduler's packing
            # pass on every restart
            self.executor.validate_params(normalized["params"])
        except api.RequestError:
            _REQ_TOTAL.labels(state="rejected").inc()
            raise
        except ValueError as exc:
            _REQ_TOTAL.labels(state="rejected").inc()
            raise api.RequestError(str(exc)) from exc
        units = api.expand_units(normalized)
        req_id = "req-" + secrets.token_hex(5)
        # every request gets a trace id (client-supplied context wins):
        # the thread that ties request docs, queue records, span journal
        # and job events into one cross-replica timeline
        trace_id = normalized.get("trace") or api.new_trace_id()
        unit_docs: dict[str, dict] = {}
        plans: dict[str, dict] = {}
        try:
            for unit in units:
                # plan construction is part of the front door: the chain
                # executor resolves the grid against the database config
                # here, so a cell the database does not define is a 400,
                # never a durable record
                plan = self.executor.plan(unit)
                plan_hash = self.store.plan_hash(plan)
                record_unit = {
                    "database": unit.database, "src": unit.src,
                    "hrc": unit.hrc, "params": unit.params,
                    "pvs_id": unit.pvs_id,
                }
                unit_docs[unit.pvs_id] = {
                    "plan": plan_hash,
                    "planPayload": plan,
                    "output": self.executor.output_name(unit, plan_hash),
                    "cost_s": round(cost.predict_unit_cost(
                        self.executor, record_unit), 4),
                    # the poison-quarantine key (docs/ROBUSTNESS.md):
                    # stamped at the front door so the queue record can
                    # fail fast against the digest registry at enqueue
                    "src_digest": self.executor.src_digest(record_unit),
                    "unit": record_unit,
                }
                plans[plan_hash] = unit_docs[unit.pvs_id]
        except api.RequestError:
            _REQ_TOTAL.labels(state="rejected").inc()
            raise
        # admission control (docs/SERVE.md "Cost-aware scheduling &
        # admission"): COLD units' predicted seconds against the
        # per-request and per-tenant budgets, refused at POST time with
        # a 429 forensic body — before any durable state exists. The
        # warm set is computed once and reused by the enqueue loop.
        # Units whose plan is already queued/running cost nothing
        # either: they ATTACH to the in-flight record (singleflight),
        # whose prediction is already in the tenant's outstanding sum —
        # charging them again would refuse exactly the overlapping-grid
        # workload the serve layer exists to dedup, and double-count
        # the predicted ledger.
        warm_plans = {ph for ph in plans if self._plan_is_done(ph)}

        def _in_flight(plan_hash: str) -> bool:
            record = self.queue.by_plan(plan_hash)
            return record is not None and record.state in (
                "queued", "running")

        try:
            predicted_s = cost.check_admission(
                normalized["tenant"],
                [(ud["unit"]["pvs_id"], ud["cost_s"])
                 for ph, ud in plans.items()
                 if ph not in warm_plans and not _in_flight(ph)],
                self.admission_budget_s,
                self.tenant_budget_s,
                self.queue.outstanding_cost(normalized["tenant"]),
            )
        except cost.AdmissionError:
            _REQ_TOTAL.labels(state="rejected").inc()
            raise
        doc = {
            "request": req_id,
            "trace": trace_id,
            "tenant": normalized["tenant"],
            "priority": normalized["priority"],
            "database": normalized["database"],
            "created_at": time.time(),
            "units": unit_docs,
            "state": "active",
            "done_at": None,
            "latency_ms": None,
            "warm": False,
            #: the admission decision's evidence, kept on the record
            "predicted_cost_s": round(predicted_s, 3),
            # liveness stamp: peers adopt this request if our process
            # dies before finalizing it (maintenance orphan sweep)
            "owner": owner_stamp(self.replica),
        }
        # the request must be discoverable BEFORE its first unit can
        # complete, or a fast job's on_done would miss the waiter
        with self._lock:
            doc["_pending"] = set(plans)
            self._requests[req_id] = doc
            for plan_hash in plans:
                self._plan_waiters.setdefault(plan_hash, set()).add(req_id)
        self._persist_request(doc)
        self.cost_ledger.admitted(normalized["tenant"], predicted_s)
        outcomes = {"warm": 0, "enqueued": 0, "attached": 0,
                    "quarantined": 0}
        quarantine_error: Optional[str] = None
        for plan_hash, unit_doc in plans.items():
            if plan_hash in warm_plans:
                _UNITS.labels(outcome="warm").inc()
                outcomes["warm"] += 1
                self.cost_ledger.warm(normalized["tenant"])
                with self._lock:
                    doc["_pending"].discard(plan_hash)
                    waiters = self._plan_waiters.get(plan_hash)
                    if waiters is not None:
                        waiters.discard(req_id)
                        if not waiters:
                            self._plan_waiters.pop(plan_hash, None)
                continue
            record, outcome = self.queue.enqueue(
                plan_hash, unit_doc["planPayload"], unit_doc["unit"],
                normalized["tenant"], normalized["priority"], req_id,
                unit_doc["output"], trace_id=trace_id,
                cost_s=unit_doc["cost_s"],
                src_digest=unit_doc.get("src_digest"),
            )
            if outcome == "done":
                # the queue remembers a completion the store no longer
                # holds (evicted): re-arm the same record. If the
                # eviction was recent, this rebuild is eviction REGRET —
                # the budget forced recomputation of bytes we had.
                self.queue.rearm(record.job_id)
                self.heat.note_read_or_rebuild(plan_hash, via="rebuild")
                outcome = "new"
            if outcome == "quarantined":
                # permanent failure on record: the request fails NOW
                # instead of waiting on work nothing will run — an
                # operator re-arms the plan (docs/SERVE.md), a re-POST
                # then retries it
                _UNITS.labels(outcome="quarantined").inc()
                outcomes["quarantined"] += 1
                quarantine_error = record.error or "plan quarantined"
                continue
            key = "enqueued" if outcome == "new" else "attached"
            _UNITS.labels(outcome=key).inc()
            outcomes[key] += 1
        # under the lock: `doc` is shared with worker callbacks the
        # moment it entered self._requests above, and _persist_request
        # snapshots it under this same lock — a bare mutation here would
        # race that snapshot's iteration (snapshot-under-lock audit)
        with self._lock:
            doc["warm"] = outcomes["warm"] == len(plans)
            if quarantine_error is not None and doc["state"] == "active":
                doc["state"] = "failed"
                doc["done_at"] = time.time()
                doc["error"] = quarantine_error
        _REQ_TOTAL.labels(state="accepted").inc()
        tm.emit("serve_request", request=req_id, trace_id=trace_id,
                tenant=normalized["tenant"],
                priority=normalized["priority"], units=len(unit_docs),
                **outcomes)
        if quarantine_error is not None:
            self._persist_request(doc)
            _REQ_TOTAL.labels(state="failed").inc()
            tm.emit("serve_request_done", request=req_id,
                    trace_id=trace_id, status="failed",
                    error=quarantine_error)
        self.scheduler.notify()
        self._check_request_done(req_id, submit_t0=t0)
        with self._lock:
            state = self._requests[req_id]["state"]
            latency_ms = self._requests[req_id]["latency_ms"]
        return {
            "request": req_id,
            "trace": trace_id,
            "state": state,
            "units": len(unit_docs),
            "outcomes": outcomes,
            "latency_ms": latency_ms,
            "url": f"/v1/requests/{req_id}",
        }

    # ------------------------------------------------------- completion

    def _plan_is_done(self, plan_hash: str) -> bool:
        """The store is the truth for artifact existence; a verified
        manifest = warm. Corruption counts as a miss (the rebuild
        path will re-execute)."""
        if self.store is None:
            return False
        manifest = self.store.lookup(plan_hash)
        if manifest is None:
            return False
        try:
            self.store.verify_object(manifest.object)
        except StoreCorruption:
            return False
        self.store.touch(manifest)
        return True

    def _on_job_done(self, record) -> None:
        self._settle_cost(record)
        with self._lock:
            waiters = self._plan_waiters.pop(record.plan_hash, set())
            for req_id in waiters:
                doc = self._requests.get(req_id)
                if doc is not None:
                    doc.get("_pending", set()).discard(record.plan_hash)
        for req_id in sorted(waiters):
            self._check_request_done(req_id)
        self.pressure.maybe_collect()

    def _settle_cost(self, record) -> None:
        """The cost model's feedback loop (docs/SERVE.md): grade the
        record's predicted seconds against what execution really took.
        Only for executions THIS replica owned — a peer's completion
        already landed in its own ledger/metrics, and the fleet view
        merges the replicas' counters (double-observing here would
        double-count fleet-wide)."""
        if getattr(record, "owner", None) != self.replica:
            return
        tenant = getattr(record, "tenant", "") or ""
        if getattr(record, "warm", False):
            self.cost_ledger.warm(tenant)
            return
        claimed_at = getattr(record, "claimed_at", None)
        done_at = getattr(record, "done_at", None)
        if claimed_at and done_at:
            self.cost_ledger.observed(
                tenant, getattr(record, "cost_s", 0.0),
                max(0.0, done_at - claimed_at),
            )

    def _on_job_failed(self, record) -> None:
        with self._lock:
            waiters = self._plan_waiters.pop(record.plan_hash, set())
            docs = []
            for req_id in sorted(waiters):
                doc = self._requests.get(req_id)
                if doc is None or doc["state"] != "active":
                    continue
                doc["state"] = "failed"
                doc["done_at"] = time.time()
                doc["error"] = record.error
                # same visibility contract as _check_request_done: the
                # terminal event is published before the lock drops
                _REQ_TOTAL.labels(state="failed").inc()
                tm.emit("serve_request_done", request=doc["request"],
                        trace_id=doc.get("trace"), status="failed",
                        error=record.error)
                docs.append(doc)
        for doc in docs:
            self._persist_request(doc)

    def _check_request_done(self, req_id: str,
                            submit_t0: Optional[float] = None) -> None:
        """Finalize a request whose pending set drained. The set is
        maintained incrementally (submit warm hits, _on_job_done), so
        this is a dict lookup under the lock — NOT a per-unit store
        re-verification, which on a mostly-warm many-unit request would
        serialize submit and the whole observability surface behind
        file I/O."""
        with self._lock:
            doc = self._requests.get(req_id)
            if doc is None or doc["state"] != "active":
                return
            if doc.get("_pending"):
                return
            doc["state"] = "done"
            doc["done_at"] = time.time()
            if submit_t0 is not None:
                doc["latency_ms"] = round(
                    (time.perf_counter() - submit_t0) * 1e3, 3
                )
            else:
                doc["latency_ms"] = round(
                    (doc["done_at"] - doc["created_at"]) * 1e3, 3
                )
            warm = doc.get("warm", False)
            latency_s = (doc["done_at"] - doc["created_at"])
            # counters + the terminal event fire INSIDE the lock that
            # makes the state flip visible: a waiter that observes
            # 'done' must also find serve_request_done in the event log
            # — emitting after the (fsynced) persist below left a
            # window a loaded suite actually hit
            _REQ_TOTAL.labels(state="completed").inc()
            _REQ_SECONDS.observe(max(0.0, latency_s))
            _E2E_SECONDS.labels(tenant=doc["tenant"],
                                priority=doc["priority"]) \
                .observe(max(0.0, latency_s))
            if warm:
                _WARM_REQ_SECONDS.observe(max(0.0, latency_s))
            tm.emit("serve_request_done", request=req_id,
                    trace_id=doc.get("trace"), status="done",
                    duration_s=round(max(0.0, latency_s), 4), warm=warm)
        self._persist_request(doc)
        self._prune_finished()

    def _persist_request(self, doc: dict) -> None:
        # snapshot AND write under the lock (the queue's own discipline:
        # the files are small, one atomic replace each). The lock stops
        # two races at once: _on_job_failed inserting doc["error"] while
        # the comprehension iterates (RuntimeError), and a stale snapshot
        # from the submit thread landing AFTER a worker persisted the
        # terminal state, reverting the on-disk record to 'active'.
        # "_pending" (a set) is in-memory bookkeeping, rebuilt at
        # recovery from the store + queue — never persisted.
        with self._lock:
            snapshot = {
                k: v for k, v in doc.items() if not k.startswith("_")
            }
            atomic_write_json(
                os.path.join(
                    self.requests_dir, snapshot["request"] + ".json"
                ),
                snapshot,
                durable=True,  # request docs claim SIGKILL/power-loss proofness
                sort_keys=True,
            )

    def _prune_finished(self) -> None:
        """Retention for an always-on daemon: keep the most recent
        `request_retention` finished requests (memory AND disk); the
        artifacts themselves live in the store under GC/budget rules."""
        with self._lock:
            finished = [
                doc for doc in self._requests.values()
                if doc["state"] != "active"
            ]
            excess = len(finished) - self.request_retention
            victims = []
            if excess > 0:
                finished.sort(key=lambda d: d.get("done_at") or 0.0)
                victims = finished[:excess]
                for doc in victims:
                    self._requests.pop(doc["request"], None)
        for doc in victims:
            try:
                os.unlink(os.path.join(
                    self.requests_dir, doc["request"] + ".json"
                ))
            except OSError:
                pass

    def active_plans(self) -> set:
        """Plan hashes unfinished requests still need — the GC pressure
        hook's ephemeral pins."""
        with self._lock:
            plans: set = set()
            for doc in self._requests.values():
                if doc["state"] != "active":
                    continue
                plans.update(u["plan"] for u in doc["units"].values())
            return plans

    # ------------------------------------------------------------- views

    def request_status(self, req_id: str) -> Optional[dict]:
        with self._lock:
            doc = self._requests.get(req_id)
            if doc is None:
                return None
            out = {
                "request": doc["request"],
                "trace": doc.get("trace"),
                "tenant": doc["tenant"],
                "priority": doc["priority"],
                "state": doc["state"],
                "created_at": doc["created_at"],
                "done_at": doc["done_at"],
                "latency_ms": doc["latency_ms"],
                "warm": doc.get("warm", False),
                "predicted_cost_s": doc.get("predicted_cost_s"),
                "units": {},
            }
            if "error" in doc:
                out["error"] = doc["error"]
            pending = doc.get("_pending")
            if pending is None:
                # recovered finished request (no live bookkeeping): any
                # unit the queue still knows as unfinished reports that
                # state; the rest are settled
                pending = {
                    u["plan"] for u in doc["units"].values()
                    if (self.queue.by_plan(u["plan"]) or
                        _DONE_SENTINEL).state != "done"
                }
            for pvs_id, unit_doc in doc["units"].items():
                if unit_doc["plan"] not in pending:
                    # settled when it drained from the pending set — no
                    # store re-verification per GET (eviction later just
                    # 404s the artifact URL, by design)
                    entry = {
                        "plan": unit_doc["plan"], "state": "done",
                        "artifact": f"/v1/artifacts/{unit_doc['plan']}",
                    }
                else:
                    record = self.queue.by_plan(unit_doc["plan"])
                    state = record.state if record is not None else "queued"
                    entry = {"plan": unit_doc["plan"], "state": state}
                    if record is not None and record.error:
                        entry["error"] = record.error
                out["units"][pvs_id] = entry
            return out

    def _request_summaries(self) -> list[dict]:
        with self._lock:
            return [
                {
                    "request": doc["request"],
                    "tenant": doc["tenant"],
                    "priority": doc["priority"],
                    "state": doc["state"],
                    "units": len(doc["units"]),
                    "created_at": doc["created_at"],
                }
                for doc in sorted(
                    self._requests.values(),
                    key=lambda d: d["created_at"],
                )[-1000:]  # most recent; full history is on disk
            ]

    def _status_section(self, query: dict) -> dict:
        section = {
            "executor": self.executor.kind,
            # replica identity: multi-replica runs must be tellable
            # apart at a glance (/status, chain-top, the fleet view)
            "replica": self.replica,
            "replica_epoch": self.queue.replica_epoch,
            "pid": os.getpid(),
            "queue": self.queue.counts(),
            "requests": {},
            # per-tenant predicted/observed accounting + model error
            # (docs/SERVE.md "Cost-aware scheduling & admission")
            "cost": {
                **self.cost_ledger.report(),
                "outstanding_s": round(self.queue.outstanding_cost(), 3),
                # the per-host prediction multiplier in force (1.0 =
                # base coefficients); refit when --cost-calibrate is on
                "calibration": {
                    **cost.calibration(),
                    "enabled": self.cost_calibrate,
                },
            },
            # live stall/hard-timeout episodes from the heartbeat
            # registry — the fleet view re-labels these per replica so
            # a stalled replica is visible fleet-wide (fleet-top's
            # active-stalls line)
            "stalls": tm_watchdog.active_stalls(),
        }
        with self._lock:
            for doc in self._requests.values():
                state = doc["state"]
                section["requests"][state] = (
                    section["requests"].get(state, 0) + 1
                )
        req_id = query.get("request")
        if req_id:
            section["request"] = (
                self.request_status(req_id) or {"error": "unknown request"}
            )
        return section

    # ------------------------------------------------------------- HTTP

    @staticmethod
    def _json(code: int, doc: object):
        return code, "application/json", json.dumps(doc)

    def _h_requests(self, req: live.WebRequest):
        if req.method == "GET":
            return self._json(200, {"requests": self._request_summaries()})
        try:
            payload = json.loads(req.body.decode() or "null")
        except (ValueError, UnicodeDecodeError):
            _REQ_TOTAL.labels(state="rejected").inc()
            return self._json(400, {"error": "body is not valid JSON"})
        try:
            return self._json(202, self.submit(payload))
        except cost.AdmissionError as exc:
            # 429 with the full forensic body: what was predicted,
            # against which budget, and which units are the heaviest —
            # the client can split the grid or retry as work settles
            return self._json(429, exc.doc)
        except api.RequestError as exc:
            return self._json(400, {"error": str(exc)})

    def _h_healthz(self, req: live.WebRequest):
        """Liveness plus drain state. A draining replica answers 200 —
        it is healthy, it is just not claiming work — so probes keep
        passing while `tools serve-chaos` cycles a drain/join."""
        with self._lock:
            state = "draining" if self._draining else "ok"
        return 200, "application/json", json.dumps({
            "status": state,
            "pid": os.getpid(),
            "replica": self.replica,
            "uptime_s": round(time.monotonic() - self._t0, 3),
        })

    def _h_drain(self, req: live.WebRequest):
        """POST /v1/drain: body `{}` (or empty) drains; `{"resume":
        true}` rejoins. SIGUSR1 on the daemon is the signal-shaped
        equivalent of the drain half (tools/chain_serve.py)."""
        try:
            payload = json.loads(req.body.decode() or "{}")
        except (ValueError, UnicodeDecodeError):
            return self._json(400, {"error": "body is not valid JSON"})
        if not isinstance(payload, dict):
            return self._json(400, {"error": "body must be a JSON object"})
        if payload.get("resume"):
            return self._json(200, self.resume())
        return self._json(200, self.drain())

    def _h_fleet(self, req: live.WebRequest):
        """The merged fleet view (telemetry/fleet.py): every replica
        over this root — discovered via their serve-info files — plus
        the shared queue/request truth from disk and the SLO layer's
        merged per-(tenant × priority) histograms."""
        from ..telemetry import fleet

        return self._json(200, fleet.fleet_view(self.root))

    def _h_fleet_alerts(self, req: live.WebRequest):
        """GET /fleet/alerts: the fleet-merged alert plane — active
        alerts, recently-resolved ones, and journal stats — folded from
        every replica's alert journal (telemetry/alerts.py)."""
        return self._json(200, alerts_mod.alerts_report(self.root))

    def _h_scale_signal(self, req: live.WebRequest):
        """GET /fleet/scale-signal: the autoscale recommendation
        (serve/autoscale.py) — current vs desired replicas, confidence,
        reason codes. Served from the last maintenance-tick grading;
        a cold replica grades synchronously once."""
        signal = self.autoscale.latest()
        if signal is None:
            try:
                signal = self._control_tick(force=True)
            except Exception:  # noqa: BLE001 - degrade to 503, not a 500
                get_logger().exception(
                    "chain-serve: cold scale-signal grading failed")
        if signal is None:  # grading itself failed; say so, don't 500
            return self._json(503, {"error": "scale signal unavailable"})
        return self._json(200, signal)

    def _h_request(self, req: live.WebRequest):
        req_id = req.path[len("/v1/requests/"):]
        doc = self.request_status(req_id)
        if doc is None:
            return self._json(404, {"error": f"unknown request {req_id!r}"})
        return self._json(200, doc)

    @staticmethod
    def _etag_matches(header: str, etag: str) -> bool:
        """Strong If-None-Match comparison (RFC 9110 §13.1.2): the plan
        hash IS the content address, so weak tags (`W/"…"`) never
        match — a weak validator on a CAS key is a client bug."""
        if header.strip() == "*":
            return True
        return any(c.strip() == etag for c in header.split(","))

    @staticmethod
    def _parse_range(header: Optional[str], size: int):
        """RFC 9110 §14.2 single-range parse against a known size.
        Returns `(start, length)`, the string `"unsatisfiable"` (→ 416
        with `Content-Range: bytes */size`), or None when there is no
        range to honor — absent header, other units, multi-range, and
        malformed specs all serve the full body, as the spec allows."""
        if not header:
            return None
        m = re.fullmatch(r"bytes=(\d*)-(\d*)", header.strip())
        if m is None:
            return None
        first, last = m.group(1), m.group(2)
        if not first and not last:
            return None
        if not first:
            # suffix range: the final N bytes
            n = int(last)
            if n == 0 or size == 0:
                return "unsatisfiable"
            n = min(n, size)
            return size - n, n
        start = int(first)
        if start >= size:
            return "unsatisfiable"
        if not last:
            return start, size - start
        end = int(last)
        if end < start:
            return None
        return start, min(end, size - 1) - start + 1

    def _h_artifact(self, req: live.WebRequest):
        t0 = time.perf_counter()
        key = req.path[len("/v1/artifacts/"):]
        if len(key) != _HASH_LEN or any(
            c not in "0123456789abcdef" for c in key
        ):
            return self._json(400, {"error": "artifact key must be a "
                                             "64-hex plan hash"})
        if self.store is None:
            return self._json(404, {"error": "no store configured"})
        manifest = self.store.lookup(key)
        if manifest is None:
            # a recently-evicted plan re-requested = eviction regret
            self.heat.note_read_or_rebuild(key, via="read")
            return self._json(404, {"error": "unknown artifact (expired "
                                             "or never built; re-POST the "
                                             "request to rebuild)"})
        try:
            self.store.verify_object(manifest.object)
        except StoreCorruption:
            return self._json(404, {"error": "artifact failed verification; "
                                             "re-POST the request to rebuild"})
        self.store.touch(manifest)
        size = int(manifest.object.get("size", 0))
        size_class = tm_catalog.read_size_class(size)
        tenant = req.query.get("tenant", "")
        # the plan hash is a content address: it IS the strong ETag, and
        # the bytes behind it are immutable — cache forever
        etag = f'"{key}"'
        extra = {"ETag": etag,
                 "Accept-Ranges": "bytes",
                 "Cache-Control": "public, max-age=31536000, immutable"}
        inm = req.headers.get("if-none-match")
        if inm and self._etag_matches(inm, etag):
            # conditional GET hit: no body, fd never opened — the
            # cheapest read the plane can serve. An edge-class hit in
            # the heat ledger (mode=not_modified), TTFB-only in the SLO
            # layer (there is no stream to time).
            ttfb = time.perf_counter() - t0
            _READ_TTFB_SECONDS.labels(
                tenant=tenant, size_class=size_class).observe(ttfb)
            self.heat.record_read(
                key, 0, mode="not_modified", size=size,
                size_class=size_class, tenant=tenant, ttfb_s=ttfb,
            )
            return 304, "application/octet-stream", b"", extra
        # RFC 9110 single-range parse against the manifest's size —
        # BEFORE any fd opens, so an unsatisfiable range costs nothing.
        # An If-Range validator that fails the strong compare drops the
        # range (full 200), per §13.1.5.
        rng = self._parse_range(req.headers.get("range"), size)
        if rng == "unsatisfiable":
            extra416 = dict(extra)
            extra416["Content-Range"] = f"bytes */{size}"
            return (416, "application/json",
                    json.dumps({"error": "requested range not "
                                         "satisfiable", "size": size}),
                    extra416)
        if rng is not None:
            if_range = req.headers.get("if-range")
            if if_range and if_range.strip() != etag:
                rng = None
        # streamed from disk (live.FileBody): artifacts are video-scale.
        # Open the fd HERE, not in the reply: the GC pressure hook can
        # evict the object between this check and the streaming loop,
        # and an open descriptor keeps the bytes alive for this response
        # (a post-eviction GET is an honest 404, never a truncated 200).
        # The open is tier-routed (store/tiers.py): a warm/cold hit is
        # promoted read-through, and the tier the bytes were FOUND in
        # lands in the heat journal with the read.
        try:
            hit_tier, path, fileobj, _ = self.store.open_object_read(
                manifest.object["sha256"], plan=key, heat=self.heat,
            )
        except FileNotFoundError:
            self.heat.note_read_or_rebuild(key, via="read")
            return self._json(404, {"error": "artifact evicted; re-POST "
                                             "the request to rebuild"})
        except OSError as exc:
            # NOT eviction (EMFILE under fd pressure, EACCES, …): a 404
            # here would tell clients to re-POST and recompute bytes that
            # are sitting in the store — say 500 so they retry the GET
            get_logger().warning("serve: artifact open failed: %r", exc)
            return self._json(500, {"error": "artifact temporarily "
                                             "unavailable; retry"})

        status = 200
        mode = "full"
        offset = 0
        length = None
        if rng is not None:
            offset, length = rng
            status = 206
            mode = "range"
            extra["Content-Range"] = (
                f"bytes {offset}-{offset + length - 1}/{size}")

        ttfb_box: list = []

        def _on_first_byte() -> None:
            ttfb_box.append(time.perf_counter() - t0)
            _READ_TTFB_SECONDS.labels(
                tenant=tenant, size_class=size_class
            ).observe(ttfb_box[0])

        def _on_complete(sent: int, ok: bool) -> None:
            dur = time.perf_counter() - t0
            if ok:
                _READ_SECONDS.labels(
                    tenant=tenant, size_class=size_class).observe(dur)
            # the ledger records every stream, aborted ones included —
            # bytes left the disk either way. Ranged reads are their
            # own mode so hot-set accounting can tell a sampler from a
            # full consumer.
            self.heat.record_read(
                key, sent, mode=mode, size=size, size_class=size_class,
                tenant=tenant, tier=hit_tier,
                ttfb_s=ttfb_box[0] if ttfb_box else None, dur_s=dur,
            )

        return status, "application/octet-stream", live.FileBody(
            path or "", fileobj=fileobj, offset=offset, length=length,
            on_first_byte=_on_first_byte, on_complete=_on_complete,
        ), extra

    # ------------------------------------------------------ test helpers

    def wait_request(self, req_id: str, timeout: float = 30.0) -> str:
        """Block until the request leaves 'active' (or timeout); returns
        its final (or current) state."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                doc = self._requests.get(req_id)
                state = doc["state"] if doc else "unknown"
            if state != "active":
                return state
            time.sleep(0.02)
        return "active"
