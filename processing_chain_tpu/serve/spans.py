"""Durable request-trace span journal: the queue's causal skeleton, surfaced.

PRs 7/9 made a request's life span processes — submit → queue → claim →
steal → wave → settle — and survive SIGKILL, but nothing could
reconstruct it afterwards: the durable queue records hold only the
LATEST state, not the path that led there. This module turns every
ownership/state transition into one append-only, epoch-stamped span
record so the whole fleet's history is replayable from disk:

  * **One journal file per replica** (`spans/<replica>.jsonl` under the
    queue root): appends never contend across processes, a SIGKILLed
    replica's journal survives it, and `read_journals` merges the fleet
    back into one timeline (sorted by wall clock, then per-journal
    sequence — the only ordering that exists across processes).
  * **Span-before-persist discipline**: the writer appends (flushed to
    the kernel) BEFORE the queue persists the transition it describes,
    so a crash between the two leaves an *extra* span (an attempt whose
    record never landed — honest forensics), never a *missing* one. The
    gapless-chain invariant below depends on exactly this ordering.
    Flush, not fsync: a SIGKILLed process cannot take flushed bytes
    with it (they are the kernel's), and that is the death mode the
    fleet contract covers — power-loss durability stays the QUEUE
    records' claim (their rewrites fsync), the journal deliberately
    does not pay ~ms-per-span for it inside the queue's critical
    sections.
  * **Trace ids ride along**: each span carries the record's request
    ids and trace ids at the moment of the transition, so `tools trace
    show` can filter the fleet journal down to one request without a
    secondary index.

The **gapless-chain invariant** (`verify_chain`, checked per terminal
record by `tools serve-chaos`): every epoch a record ever held was
introduced by exactly one claim/steal/requeue transition, and each of
those writes a span — so for a terminal record the journal must show an
`enqueue`, every epoch in `1..settled_epoch`, and a terminal span
matching the record's final state. A SIGKILLed owner cannot break this:
its own claim span was already flushed to the kernel before the claim
persisted — a process death cannot take those bytes with it (power
loss can; that durability is the queue records' fsynced claim,
deliberately not the journal's) — and the steal/recovery that took
the work over is written by a live peer.

Readers tolerate a torn final line (the one write a crash can
interrupt), mirroring telemetry/events.read_jsonl.

Retention: journals are append-only per-root history with NO rotation
— pruning old spans would break the gapless chains of the records
that outlive them, so a journal lives exactly as long as its serve
root. The hot path (/fleet, refreshed every few seconds) therefore
reads only tail-sampled stats (`journal_stats`); the full-history
readers (`tools trace show`, the chaos completeness check, soak
percentiles) are operator-invoked and bounded by the root's lifetime.
Journal rotation keyed to request retention is future work.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Iterable, Optional

from ..utils import lockdebug
from ..utils.log import get_logger

#: transition vocabulary (the `phase` field); terminal phases settle a
#: record, ownership phases introduce a fresh epoch
PHASES = (
    "enqueue",    # record minted (or re-armed for a fresh life)
    "attach",     # an overlapping request joined the existing record
    "claim",      # queued -> running: this replica owns the execution
    "revert",     # mid-claim disk failure undone: back to queued
    "steal",      # a live replica reclaimed a dead/expired lease
    "requeue",    # retry (attempts budget) or crash-recovery re-arm
    "complete",   # running -> done
    "fail",       # running -> failed
    "quarantine", # running -> quarantined
    "fenced",     # a stale-epoch settle was refused (forensics only)
)

#: phases that introduce the epoch they carry (the gapless-chain check
#: demands every epoch in 1..settled_epoch appear on one of these)
EPOCH_PHASES = ("claim", "steal", "requeue", "revert")

#: phases that settle a record; the last span of a terminal record's
#: chain must be one of these and agree with the record's state
TERMINAL_PHASES = {"complete": "done", "fail": "failed",
                   "quarantine": "quarantined"}

_SAFE_NAME = re.compile(r"[^A-Za-z0-9._-]+")


def safe_replica_name(replica: str) -> str:
    """Replica id as a filesystem-safe basename (journal + epoch files)."""
    return _SAFE_NAME.sub("_", replica)


def _journal_name(replica: str) -> str:
    return safe_replica_name(replica) + ".jsonl"


class SpanJournal:
    """Append-only per-replica span writer (see module doc).

    Thread-safe: the scheduler workers, the maintenance tick and the
    HTTP submit path all transition records. Appends are flushed per
    record (SIGKILL-proof: flushed bytes belong to the kernel, not the
    process — see the module doc for why fsync is deliberately NOT
    paid here), and any disk failure degrades to a logged warning: the
    journal is observability, it must never break the queue it
    observes."""

    def __init__(self, root: str, replica: str,
                 replica_epoch: int = 0) -> None:
        self.root = os.path.abspath(root)
        self.replica = replica
        self.replica_epoch = int(replica_epoch)
        self.path = os.path.join(self.root, _journal_name(replica))
        self._lock = lockdebug.make_lock("serve_spans")
        self._f = None    # guarded-by: _lock
        self._seq = 0     # guarded-by: _lock

    def append(self, phase: str, *, job: str, plan: str, state: str,
               epoch: int, requests: Iterable[str] = (),
               traces: Iterable[str] = (), **extra) -> None:
        """Record one transition. Never raises (see class doc)."""
        record = {
            "ts": round(time.time(), 6),
            "phase": phase,
            "job": job,
            "plan": plan,
            "state": state,
            "epoch": int(epoch),
            "replica": self.replica,
            "replica_epoch": self.replica_epoch,
            "pid": os.getpid(),
            "requests": list(requests),
            "traces": [t for t in traces if t],
        }
        record.update(extra)
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            try:
                if self._f is None:
                    os.makedirs(self.root, exist_ok=True)
                    # append-only stream: torn tails are tolerated by
                    # read_journals, and O_APPEND keeps concurrent
                    # incarnations (a restart racing its predecessor's
                    # last flush) from interleaving mid-line
                    self._f = open(self.path, "a")
                self._f.write(json.dumps(record, sort_keys=True) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                get_logger().warning(
                    "serve spans: could not append %s span for %s",
                    phase, job, exc_info=True)
                try:
                    if self._f is not None:
                        self._f.close()
                except OSError:
                    pass
                self._f = None

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


# ---------------------------------------------------------------- readers


def read_journal(path: str) -> list[dict]:
    """One journal file; tolerates a torn final line."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    break  # torn tail: everything before it stands
                if isinstance(record, dict):
                    out.append(record)
    except OSError:
        return []
    return out


def read_journals(root: str) -> list[dict]:
    """Every replica's journal under `root`, merged into one fleet
    timeline ordered by (ts, replica, seq) — wall clock across
    processes, per-journal sequence within one."""
    spans: list[dict] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        if name.endswith(".jsonl"):
            spans.extend(read_journal(os.path.join(root, name)))
    spans.sort(key=lambda s: (s.get("ts", 0.0), s.get("replica", ""),
                              s.get("seq", 0)))
    return spans


def journal_stats(root: str, tail_bytes: int = 1 << 19) -> dict:
    """Cheap fleet-view summary of the journals: total size from stat,
    per-phase counts parsed from each journal's TAIL (last
    `tail_bytes`). An always-on fleet appends spans forever, and
    /fleet refreshes every few seconds — it must not reparse an
    unbounded history per refresh. `sampled: true` flags that some
    journal exceeded the tail window, i.e. the counts cover the recent
    window rather than all time (no silent cap)."""
    stats = {"files": 0, "bytes": 0, "total": 0,
             "by_phase": {}, "sampled": False}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return stats
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(root, name)
        try:
            size = os.stat(path).st_size
            with open(path) as f:
                if size > tail_bytes:
                    stats["sampled"] = True
                    f.seek(size - tail_bytes)
                    f.readline()  # discard the mid-record partial
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        continue  # torn tail (or mid-window garbage)
                    phase = record.get("phase", "?")
                    stats["by_phase"][phase] = \
                        stats["by_phase"].get(phase, 0) + 1
                    stats["total"] += 1
        except OSError:
            continue
        stats["files"] += 1
        stats["bytes"] += size
    return stats


def spans_for_request(spans: Iterable[dict], request_id: str) -> list[dict]:
    return [s for s in spans if request_id in (s.get("requests") or ())]


def spans_for_job(spans: Iterable[dict], job_id: str) -> list[dict]:
    return [s for s in spans if s.get("job") == job_id]


# ------------------------------------------------------- gapless chains


def verify_chain(job_spans: list[dict], record: dict) -> list[str]:
    """The gapless-chain invariant for ONE terminal queue record
    (chaos-harness vocabulary: `record` is the on-disk JSON dict).
    Returns violations; empty = the journal fully explains how this
    record reached its terminal state, across any number of replica
    deaths. Non-terminal records are not checked (their chain is still
    being written)."""
    job_id = record.get("job", "?")
    state = record.get("state")
    if state not in ("done", "failed", "quarantined"):
        return []
    violations: list[str] = []
    if not job_spans:
        return [f"record {job_id} is terminal but has no spans at all"]
    if job_spans[0].get("phase") != "enqueue":
        violations.append(
            f"record {job_id}: chain starts with "
            f"{job_spans[0].get('phase')!r}, not 'enqueue'")
    settled_epoch = record.get("settledEpoch")
    final_epoch = settled_epoch if settled_epoch is not None \
        else record.get("epoch", 0)
    seen_epochs = {int(s.get("epoch", 0)) for s in job_spans
                   if s.get("phase") in EPOCH_PHASES}
    missing = sorted(set(range(1, int(final_epoch) + 1)) - seen_epochs)
    if missing:
        violations.append(
            f"record {job_id}: no ownership span introduced epoch(s) "
            f"{missing} — the chain has a gap")
    terminal = [s for s in job_spans if s.get("phase") in TERMINAL_PHASES]
    if not terminal:
        violations.append(
            f"record {job_id} is {state!r} but the journal holds no "
            "terminal span")
    else:
        last = terminal[-1]
        if TERMINAL_PHASES.get(last.get("phase")) != state:
            violations.append(
                f"record {job_id}: last terminal span is "
                f"{last.get('phase')!r} but the record is {state!r}")
        if settled_epoch is not None and \
                int(last.get("epoch", -1)) != int(settled_epoch):
            violations.append(
                f"record {job_id}: terminal span carries epoch "
                f"{last.get('epoch')} but the record settled under "
                f"{settled_epoch}")
    return violations


def verify_completeness(serve_root: str,
                        records: Optional[dict] = None) -> list[str]:
    """The fleet-wide trace-completeness check `tools serve-chaos` runs
    as an invariant: every terminal record under `serve_root` has a
    gapless span chain. `records` (job_id -> record dict) can be
    injected by callers that already loaded them."""
    jobs_dir = os.path.join(serve_root, "queue", "jobs")
    if records is None:
        records = {}
        try:
            names = os.listdir(jobs_dir)
        except OSError:
            names = []
        for name in names:
            if not name.endswith(".json"):
                continue  # lease sentinels (*.json.inprogress) included
            try:
                with open(os.path.join(jobs_dir, name)) as f:
                    doc = json.load(f)
                records[doc["job"]] = doc
            except (OSError, ValueError, KeyError):
                continue
    spans = read_journals(os.path.join(serve_root, "queue", "spans"))
    by_job: dict[str, list] = {}
    for span in spans:
        by_job.setdefault(span.get("job", ""), []).append(span)
    violations: list[str] = []
    for job_id, record in sorted(records.items()):
        violations.extend(verify_chain(by_job.get(job_id, []), record))
    return violations
