"""Host-side online services (reference lib/downloader.py).

Cloud encode/download paths never touch the TPU: they produce encoded
segment files behind the same Segment interface p01 consumes
(SURVEY.md §2.3 "Cloud offload").
"""

from .downloader import Downloader, select_format  # noqa: F401
