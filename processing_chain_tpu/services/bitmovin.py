"""Bitmovin cloud-encode submission: plan builder + injected API client.

Port of the reference's level-0 `encode_bitmovin` workflow (reference
lib/downloader.py:387-744): create input (https/http/sftp :446-472),
output (sftp/azure :500-519), codec configuration (H264/H265/VP9
:593-672), streams + muxings (MP4 for H.26x, WebM + FMP4-audio for VP9
:689-732), then start and wait-until-finished (:734-740). Reassembly of
the resulting chunks is the downloader's existing resume path.

Split in two so the cloud semantics are offline-testable:

- `plan_encoding(seg, settings)` is PURE: it maps the segment's quality
  level / video coding onto a `BitmovinPlan` (codec config dict, muxing
  specs, input/output specs) with the reference's pixel-format, profile,
  rate-control-factor, and audio rules.
- `submit_encoding(api, plan)` drives any `BitmovinApi` implementation
  (the real SDK wrapped thin, or a fake in tests) through the same call
  sequence the reference makes.

Reference bugs deliberately not replicated:
- double MP4-muxing create when audio is present (:698-711 creates a
  video-only muxing, then a second mp4 muxing for the same output file)
  — here one muxing carries both streams;
- the fps grammar mix-up (:568-575 compares the SRC fps against the
  DENOMINATOR of a fractional spec and then returns the numerator) —
  here the spec resolves through ops.fps.resolve_fps_spec;
- `download_from_azure` called but never defined (:439).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional, Protocol

from ..utils.log import get_logger

#: audio is always AAC@48kHz, capped at Bitmovin's 256 kbit/s (reference
#: :405-412, :492-496)
AUDIO_MAX_KBPS = 256
AUDIO_RATE_HZ = 48000
#: VP9 chunked-muxing layout (reference :713-732)
SEGMENT_LENGTH_S = 4


class BitmovinApi(Protocol):
    """Thin, SDK-shaped surface the submission drives. Every method
    returns the created resource id (a string)."""

    def create_input(self, kind: str, spec: dict) -> str: ...

    def create_output(self, kind: str, spec: dict) -> str: ...

    def create_codec_config(self, codec: str, spec: dict) -> str: ...

    def create_encoding(self, name: str) -> str: ...

    def create_stream(
        self, encoding_id: str, codec_config_id: str, input_id: str,
        input_path: str, name: str,
    ) -> str: ...

    def create_muxing(self, encoding_id: str, kind: str, spec: dict) -> str: ...

    def start(self, encoding_id: str) -> None: ...

    def wait_until_finished(self, encoding_id: str) -> None:
        """Block until the cloud encode completes. MUST raise on a
        terminal failure state (ERROR/CANCELED) and MUST NOT block
        forever on a hung encode (deadline -> TimeoutError): p01 runs
        online jobs pool-wide and a silently wedged encode would stall
        the whole stage with no diagnostic (the reference exits the
        process on BitmovinError, downloader.py:736-740)."""
        ...


@dataclass
class BitmovinPlan:
    """Everything `submit_encoding` needs, precomputed and assertable."""

    name: str                       # basename without extension
    input_kind: str                 # https | http | sftp
    input_spec: dict
    input_path: str                 # SRC path as the cloud sees it
    output_kind: str                # sftp | azure
    output_spec: dict
    output_path: str
    codec: str                      # h264 | h265 | vp9
    codec_config: dict
    muxings: list[dict] = field(default_factory=list)
    audio_config: Optional[dict] = None


class BitmovinPlanError(ValueError):
    """A segment that cannot be expressed as a Bitmovin encoding."""


def _pixel_format(codec: str, target_pix_fmt: Optional[str]) -> Optional[str]:
    """Reference :541-566: hevc supports 8/10-bit 420/422; other codecs
    are 8-bit only (warn on 10-bit) and 422 is broken for h264."""
    log = get_logger()
    pf = target_pix_fmt or ""
    if codec in ("h265", "hevc"):
        return {
            "yuv420p": "YUV420P",
            "yuv420p10le": "YUV420P10LE",
            "yuv422p": "YUV422P",
            "yuv422p10le": "YUV422P10LE",
        }.get(pf)
    if "10" in pf:
        log.warning("10bit is only supported by hevc for bitmovin!")
    if "yuv420p" in pf:
        return "YUV420P"
    if "yuv422p" in pf:
        if codec in ("h264", "avc"):
            log.warning("pix_fmt yuv422p is currently broken for bitmovin")
            return None
        return "YUV422P"
    return None


def _rate(quality_level, src) -> Optional[float]:
    """QL fps spec → encoder rate. 'original'/'auto' follow the SRC
    (reference :568-570). The reference's fractional-spec handling
    (:571-575) is a known bug (see module docstring); specs resolve
    through the chain's exact fps grammar instead."""
    spec = str(quality_level.fps)
    if spec.casefold() in ("original", "auto"):
        return None
    from ..ops.fps import resolve_fps_spec

    fps = resolve_fps_spec(spec, src.get_fps())
    return fps


def plan_encoding(seg, settings) -> BitmovinPlan:
    """Map one segment onto a Bitmovin submission plan (pure).

    `settings` is a `downloader.BitmovinSettings`; `seg` a domain Segment.
    """
    ql = seg.quality_level
    vc = seg.video_coding
    codec = str(ql.video_codec).casefold()
    if codec == "avc":
        codec = "h264"
    if codec == "hevc":
        codec = "h265"
    if codec not in ("h264", "h265", "vp9"):
        raise BitmovinPlanError(f"codec {ql.video_codec!r} not encodable via Bitmovin")
    name = os.path.splitext(seg.filename)[0]

    audio = ql.audio_bitrate is not None
    audio_config = None
    if audio:
        if str(ql.audio_codec or "").casefold() != "aac":
            raise BitmovinPlanError("Audio_codec has to be 'aac' (reference :409-411)")
        kbps = int(ql.audio_bitrate)
        if kbps > AUDIO_MAX_KBPS:
            get_logger().warning(
                "audio_bitrate too high. Bitmovin only supports bitrates "
                "up to 256kbit/s."
            )
        audio_config = {
            "name": f"{name}_audio_configuration",
            "bitrate": min(kbps, AUDIO_MAX_KBPS),
            "rate": AUDIO_RATE_HZ,
        }

    inp = dict(settings.input_details)
    input_kind = str(inp.pop("type", "")).casefold()
    if input_kind not in ("https", "http", "sftp"):
        raise BitmovinPlanError(f"input type {input_kind!r} not supported")
    in_root = inp.pop("path", None)
    input_path = (
        os.path.join(in_root, seg.src.filename)
        if in_root and in_root != "."
        else seg.src.filename
    )

    out = dict(settings.output_details)
    output_kind = str(out.pop("type", "")).casefold()
    if output_kind not in ("sftp", "azure"):
        raise BitmovinPlanError(f"output type {output_kind!r} not supported")
    out_root = out.pop("root", None)
    if out_root is None:  # only fall back to (and consume) path without root
        out_root = out.pop("path", "")
    out_root = out_root or ""
    output_path = os.path.join(out_root, name) if out_root else name

    bitrate = int(ql.video_bitrate * 1000)
    ten_bit = "10" in (seg.target_pix_fmt or "")
    pix_fmt = _pixel_format(codec, seg.target_pix_fmt)
    rate = _rate(ql, seg.src)

    cfg: dict = {
        "name": f"{codec}_{name}",
        "bitrate": bitrate,
        "rate": rate,
        "width": ql.width,
        "height": ql.height,
        "pixel_format": pix_fmt,
    }
    if codec in ("h264", "h265"):
        # rate-control factors scale the target bitrate (reference :578-588)
        cfg["min_bitrate"] = (
            int(vc.minrate_factor * bitrate) if vc.minrate_factor else None
        )
        cfg["max_bitrate"] = (
            int(vc.maxrate_factor * bitrate) if vc.maxrate_factor else None
        )
        cfg["bufsize"] = (
            int(vc.bufsize_factor * bitrate) if vc.bufsize_factor else None
        )
        cfg["bframes"] = vc.bframes
        # gop bounds live on the Coding (domain.py Coding.max_gop/min_gop,
        # reference seg.video_coding.max_gop :614-615), not the quality level
        cfg["max_gop"] = vc.max_gop
        cfg["min_gop"] = vc.min_gop
        if codec == "h264":
            cfg["profile"] = "MAIN"  # repo config drops `profile` (domain.py)
        else:
            cfg["profile"] = "main10" if ten_bit else "main"
    else:  # vp9: percent under/overshoot instead of absolute bounds
        cfg["quality"] = str(getattr(vc, "quality", "good")).upper()
        cfg["rate_undershoot_pct"] = (
            int(vc.minrate_factor * 100) if vc.minrate_factor else None
        )
        cfg["rate_overshoot_pct"] = (
            int(vc.maxrate_factor * 100) if vc.maxrate_factor else None
        )

    plan = BitmovinPlan(
        name=name,
        input_kind=input_kind,
        input_spec=inp,
        input_path=input_path,
        output_kind=output_kind,
        output_spec=out,
        output_path=output_path,
        codec=codec,
        codec_config=cfg,
        audio_config=audio_config,
    )
    if codec in ("h264", "h265"):
        streams = ["video"] + (["audio"] if audio else [])
        plan.muxings.append({
            "kind": "mp4",
            "streams": streams,
            "filename": f"{name}.mp4",
            "output_path": output_path,
            "acl": "PUBLIC_READ",
        })
    else:
        plan.muxings.append({
            "kind": "webm",
            "streams": ["video"],
            "segment_length": SEGMENT_LENGTH_S,
            "segment_naming": f"{name}_%number%.chk",
            "init_segment_name": f"{name}_init.hdr",
            "output_path": output_path,
            "acl": "PUBLIC_READ",
        })
        if audio:
            plan.muxings.append({
                "kind": "fmp4",
                "streams": ["audio"],
                "segment_length": SEGMENT_LENGTH_S,
                "segment_naming": f"{name}_%number%.chk",
                "init_segment_name": f"{name}_init.hdr",
                "output_path": os.path.join(output_path, "audio"),
                "acl": "PUBLIC_READ",
            })
    return plan


class SdkBitmovinApi:
    """`BitmovinApi` backed by the `bitmovin-api-sdk` package (the
    reference's dependency, requirements.txt). Construction fails with an
    actionable error when the SDK is absent, so `Downloader.from_settings`
    can degrade to resume-levels-only and offline tests can always run
    against fakes instead."""

    def __init__(self, api_key: str) -> None:
        try:
            import bitmovin_api_sdk  # type: ignore
        except ImportError as exc:
            raise RuntimeError(
                "bitmovin-api-sdk is not installed; cloud submission "
                "unavailable (resume levels 1-3 still work)"
            ) from exc
        self._sdk = bitmovin_api_sdk
        self._api = bitmovin_api_sdk.BitmovinApi(api_key=api_key)

    def create_input(self, kind: str, spec: dict) -> str:
        sdk, enc = self._sdk, self._api.encoding
        if kind == "sftp":
            return enc.inputs.sftp.create(sdk.SftpInput(
                host=spec["host"], username=spec.get("user"),
                password=spec.get("password"), port=spec.get("port", 22),
            )).id
        cls = sdk.HttpsInput if kind == "https" else sdk.HttpInput
        ep = enc.inputs.https if kind == "https" else enc.inputs.http
        return ep.create(cls(
            host=spec["host"], username=spec.get("user"),
            password=spec.get("password"),
        )).id

    def create_output(self, kind: str, spec: dict) -> str:
        sdk, enc = self._sdk, self._api.encoding
        if kind == "azure":
            return enc.outputs.azure.create(sdk.AzureOutput(
                account_name=spec.get("azureaccount") or spec.get("account_name"),
                account_key=spec.get("azurekey") or spec.get("account_key"),
                container=spec.get("container"),
            )).id
        return enc.outputs.sftp.create(sdk.SftpOutput(
            host=spec["host"], username=spec.get("user"),
            password=spec.get("password"), port=spec.get("port", 22),
        )).id

    def create_codec_config(self, codec: str, spec: dict) -> str:
        sdk, cfgs = self._sdk, self._api.encoding.configurations
        s = {k: v for k, v in spec.items() if v is not None}
        if codec == "aac":
            return cfgs.audio.aac.create(sdk.AacAudioConfiguration(
                name=s["name"], bitrate=s["bitrate"] * 1000, rate=s["rate"],
            )).id
        common = dict(
            name=s["name"], bitrate=s["bitrate"], rate=s.get("rate"),
            width=s.get("width"), height=s.get("height"),
        )
        if s.get("pixel_format"):
            common["pixel_format"] = getattr(sdk.PixelFormat, s["pixel_format"])
        if codec == "h264":
            return cfgs.video.h264.create(sdk.H264VideoConfiguration(
                profile=getattr(sdk.ProfileH264, s.get("profile", "MAIN")),
                bframes=s.get("bframes"), min_bitrate=s.get("min_bitrate"),
                max_bitrate=s.get("max_bitrate"), bufsize=s.get("bufsize"),
                max_gop=s.get("max_gop"), min_gop=s.get("min_gop"), **common,
            )).id
        if codec == "h265":
            return cfgs.video.h265.create(sdk.H265VideoConfiguration(
                profile=getattr(sdk.ProfileH265, s.get("profile", "main")),
                bframes=s.get("bframes"), min_bitrate=s.get("min_bitrate"),
                max_bitrate=s.get("max_bitrate"), bufsize=s.get("bufsize"),
                max_gop=s.get("max_gop"), min_gop=s.get("min_gop"), **common,
            )).id
        return cfgs.video.vp9.create(sdk.Vp9VideoConfiguration(
            quality=getattr(sdk.Vp9Quality, s.get("quality", "GOOD")),
            rate_undershoot_pct=s.get("rate_undershoot_pct"),
            rate_overshoot_pct=s.get("rate_overshoot_pct"), **common,
        )).id

    def create_encoding(self, name: str) -> str:
        return self._api.encoding.encodings.create(
            self._sdk.Encoding(name=name)
        ).id

    def create_stream(self, encoding_id, codec_config_id, input_id,
                      input_path, name) -> str:
        sdk = self._sdk
        return self._api.encoding.encodings.streams.create(
            encoding_id,
            sdk.Stream(
                name=name, codec_config_id=codec_config_id,
                input_streams=[sdk.StreamInput(
                    input_id=input_id, input_path=input_path,
                    selection_mode=sdk.StreamSelectionMode.AUTO,
                )],
            ),
        ).id

    def create_muxing(self, encoding_id: str, kind: str, spec: dict) -> str:
        sdk = self._sdk
        mux_api = self._api.encoding.encodings.muxings
        streams = [sdk.MuxingStream(stream_id=s) for s in spec["streams"]]
        outputs = [sdk.EncodingOutput(
            output_id=spec["output_id"], output_path=spec["output_path"],
            acl=[sdk.AclEntry(permission=sdk.AclPermission.PUBLIC_READ)],
        )]
        if kind == "mp4":
            return mux_api.mp4.create(encoding_id, sdk.Mp4Muxing(
                streams=streams, outputs=outputs, filename=spec["filename"],
            )).id
        cls = sdk.WebmMuxing if kind == "webm" else sdk.Fmp4Muxing
        ep = mux_api.webm if kind == "webm" else mux_api.fmp4
        return ep.create(encoding_id, cls(
            streams=streams, outputs=outputs,
            segment_length=spec["segment_length"],
            segment_naming=spec["segment_naming"],
            init_segment_name=spec["init_segment_name"],
        )).id

    def start(self, encoding_id: str) -> None:
        self._api.encoding.encodings.start(encoding_id)

    #: a cloud encode of a <=20 s segment that hasn't finished in 2 hours
    #: is wedged, not slow (reference SRCs are single segments)
    WAIT_TIMEOUT_S = 2 * 3600.0

    def wait_until_finished(
        self, encoding_id: str, poll_s: float = 5.0,
        timeout_s: Optional[float] = None,
    ) -> None:
        import time

        sdk = self._sdk
        timeout = timeout_s if timeout_s is not None else self.WAIT_TIMEOUT_S
        deadline = time.monotonic() + timeout
        status = None
        while time.monotonic() < deadline:
            status = self._api.encoding.encodings.status(encoding_id)
            if status.status == sdk.Status.FINISHED:
                return
            if status.status in (sdk.Status.ERROR, sdk.Status.CANCELED):
                raise RuntimeError(
                    f"Bitmovin encoding {encoding_id} ended as {status.status}"
                )
            time.sleep(poll_s)
        raise TimeoutError(
            f"Bitmovin encoding {encoding_id} did not finish within "
            f"{timeout:.0f}s "
            f"(last status: {getattr(status, 'status', 'never polled')})"
        )


def submit_encoding(api: BitmovinApi, plan: BitmovinPlan) -> str:
    """Drive `api` through the reference's call sequence; blocks until the
    cloud encode finishes. Returns the encoding id."""
    input_id = api.create_input(plan.input_kind, plan.input_spec)
    output_id = api.create_output(plan.output_kind, plan.output_spec)
    encoding_id = api.create_encoding(plan.name)

    stream_ids: dict[str, str] = {}
    if plan.audio_config is not None:
        audio_cfg_id = api.create_codec_config("aac", plan.audio_config)
        stream_ids["audio"] = api.create_stream(
            encoding_id, audio_cfg_id, input_id, plan.input_path,
            f"{plan.name}_AUDIO",
        )
    video_cfg_id = api.create_codec_config(plan.codec, plan.codec_config)
    stream_ids["video"] = api.create_stream(
        encoding_id, video_cfg_id, input_id, plan.input_path, plan.name,
    )

    for mux in plan.muxings:
        spec = dict(mux)
        spec["streams"] = [stream_ids[s] for s in mux["streams"]]
        spec["output_id"] = output_id
        api.create_muxing(encoding_id, spec.pop("kind"), spec)

    api.start(encoding_id)
    api.wait_until_finished(encoding_id)
    return encoding_id
