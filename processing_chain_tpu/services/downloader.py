"""Online-services downloader: YouTube "encoding" + Bitmovin cloud artifacts.

Parity target: reference lib/downloader.py:33-1001. Two capabilities:

* **YouTube as an encoder** — pick the available format nearest to the
  requested resolution under a bitrate cap, with codec/protocol/fps
  preferences (reference :225-348), download it into `videoSegments/`, and
  sanity-check the 7-9 s segment duration (reference :118-126).
* **Bitmovin cloud-encode artifacts** — resume levels 0-3 against local /
  remote chunk stores (reference :873-1001) and chunked fMP4/WebM output
  reassembly (reference :787-871), rebuilt on binary init+chunk
  concatenation plus the native stream-copy remux (io.medialib.remux)
  instead of `ffmpeg "concat:…" -c copy` subprocesses.

Network clients are injected interfaces: `YtdlClient` wraps yt-dlp /
youtube-dl when installed (neither is in this image — constructing it
without one raises), and chunk stores duck-type `exists/listdir/download`,
so every decision path is testable offline with fakes.

Reference bugs deliberately NOT copied (SURVEY.md quirks list):
`ffmpeg_version` NameError in the VP9 reassembly path (:820, :860, :867),
`download_from_azure` called but never defined (:439), and missing chunk
files silently becoming "Dummy_entry" entries in the concat command (:812).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Any, Optional, Protocol, Sequence

from ..utils.fsio import atomic_write
from ..utils.log import get_logger
from ..utils import lockdebug

#: segment length sanity window, seconds (reference :118-126)
_SEGMENT_LEN_RANGE = (7, 9)

#: codecs whose Bitmovin cloud encodes land as ONE finished mp4 (MP4Muxing)
#: instead of a chunk tree (reference :698-711)
_H26X = ("h264", "h265", "hevc", "avc")


def fix_codec(vcodec: str) -> str:
    """Codec name normalization for format matching (reference :90-99)."""
    if re.match(".*h264.*", vcodec):
        return "avc"
    if re.match(".*vp9.*", vcodec):
        return "vp9"
    return vcodec


def check_mode(url: str) -> str:
    """Platform for a URL (reference :101-116)."""
    if re.match(r".*youtube\..*", url) or re.match(".*youtu.be.*", url):
        return "youtube"
    if re.match(r".*vimeo\..*", url):
        return "vimeo"
    get_logger().warning(
        "Unsupported download platform! Trying to download but no guarantees."
    )
    return "else"


def check_video_len(path: str) -> bool:
    """True when the downloaded segment is within the 7-9 s window
    (reference check_video_len, :118-126); logs a warning otherwise."""
    from ..io.probe import get_segment_info

    info = get_segment_info(path)
    lo, hi = _SEGMENT_LEN_RANGE
    ok = lo < float(info["video_duration"]) < hi
    if not ok:
        get_logger().warning("Video %s is not within %d-%d seconds length!", path, lo, hi)
    return ok


@dataclass
class SelectedFormat:
    format_id: str
    width: int
    height: int
    fps: float
    protocol_matched: bool
    ext: str = "mp4"


def _protocol_matches(entry_protocol: str, wanted: Optional[str]) -> Optional[bool]:
    """True/False when the entry's protocol family is known, None when the
    entry is neither HLS nor DASH (treated as acceptable, reference
    :236-244)."""
    p = entry_protocol.casefold()
    if "m3u8" in p or "hls" in p:
        return wanted is not None and ("m3u8" in wanted or "hls" in wanted)
    if "dash" in p or "mpd" in p:
        return wanted is not None and ("dash" in wanted or "mpd" in wanted)
    return None


def select_format(
    formats: Sequence[dict],
    height: int,
    bitrate_kbps: float,
    vcodec: str,
    protocol: Optional[str] = None,
    fps: Any = "original",
) -> Optional[SelectedFormat]:
    """Choose the format nearest to `height` whose (video) bitrate is below
    `bitrate_kbps`, preferring the requested protocol; at equal resolution
    distance prefer the highest fps ('original'/'auto') or the fps nearest
    to the requested number.

    Clean reimplementation of the reference's stateful ladder walk
    (lib/downloader.py:225-293): identical choices whenever the walk
    behaves as documented, but four order-dependent artifacts of the
    reference's shared mutable state are deliberately NOT replicated
    (oracle-pinned in tests/test_downloader.py):
    - equal (delta, fps) ties in 'original' mode pick the LAST list entry
      (ours: first);
    - a non-matching-protocol entry seen early can poison the shared
      delta/fps state and permanently block a better protocol-matched
      entry later (the reference can return a 1080p format for a 720p
      request because of it; ours always prefers the matched minimum);
    - plain-https entries unconditionally count as protocol-matched even
      when dash/hls was requested (ours treats protocols outside the
      requested family as neutral — same outcome, different mechanism);
    - the protocol-matched latch flips even on entries REJECTED for codec
      or bitrate, after which every non-matching-protocol candidate is
      skipped — the reference then hard-errors ("not available") on
      ladders where a usable format exists; ours returns that format
      flagged protocol_matched=False."""
    vcodec = fix_codec(vcodec)
    fps_mode = str(fps).casefold()

    candidates: list[tuple[tuple, SelectedFormat]] = []
    for entry in formats:
        if re.match(".*audio only.*", entry.get("format", "")):
            continue
        entry_vcodec = entry.get("vcodec")
        if entry_vcodec is not None and vcodec not in entry_vcodec:
            continue
        # yt-dlp emits explicit "vbr": null next to a valid "tbr"
        rate = entry.get("vbr") or entry.get("tbr")
        if rate is None:
            continue
        if int(bitrate_kbps) < int(rate):
            continue
        if entry.get("height") is None:
            continue
        proto_ok = True
        if protocol is not None:
            matched = _protocol_matches(entry.get("protocol", ""), protocol)
            proto_ok = True if matched is None else matched

        res_delta = abs(int(height) - int(entry["height"]))
        entry_fps = float(entry.get("fps") or 0)
        if fps_mode in ("original", "auto"):
            fps_rank = -entry_fps           # higher fps wins
        else:
            fps_rank = abs(entry_fps - float(fps))  # nearest fps wins
        candidates.append((
            (not proto_ok, res_delta, fps_rank),
            SelectedFormat(
                format_id=str(entry["format_id"]),
                width=int(entry.get("width") or 0),
                height=int(entry["height"]),
                fps=entry_fps,
                protocol_matched=proto_ok,
                ext=entry.get("ext") or "mp4",
            ),
        ))

    if not candidates:
        return None
    candidates.sort(key=lambda c: c[0])
    return candidates[0][1]


# --------------------------------------------------------------- net clients


class YoutubeClient(Protocol):
    def extract_info(self, url: str) -> dict:
        """Metadata dict with a 'formats' list and 'ext' (youtube-dl style)."""
        ...

    def download(self, url: str, format_id: str, outtmpl: str) -> None:
        ...


#: the one answer to "which yt-dlp module does this environment have?"
#: — shared by YtdlClient's constructor and the plan-time capability
#: probe, so the two can never disagree about what an import would find
_YTDL_MODULES = ("yt_dlp", "youtube_dl")


def find_ytdl_module() -> Optional[str]:
    """Name of the importable yt-dlp flavor, or None. The SINGLE
    definition both the runtime import (YtdlClient) and the plan-time
    feasibility probe (`Downloader._youtube_available`) key on: the two
    used to encode the preference order independently, which is exactly
    how a plan-says-infeasible / download-would-have-worked split (or
    the reverse) creeps in."""
    import importlib.util

    for name in _YTDL_MODULES:
        try:
            if importlib.util.find_spec(name) is not None:
                return name
        except (ImportError, ValueError):
            continue
    return None


class YtdlClient:
    """Real client over yt-dlp / youtube-dl, whichever is importable."""

    def __init__(self) -> None:
        import importlib

        name = find_ytdl_module()
        if name is None:
            raise RuntimeError(
                "neither yt-dlp nor youtube-dl is installed; "
                "online YouTube encodes are unavailable"
            )
        self._ytdl = importlib.import_module(name)

    def extract_info(self, url: str) -> dict:
        with self._ytdl.YoutubeDL({"quiet": True}) as ydl:
            return ydl.extract_info(url, download=False)

    def download(self, url: str, format_id: str, outtmpl: str) -> None:
        opts = {
            "format": format_id,
            "outtmpl": outtmpl,
            "quiet": True,
            "prefer_insecure": True,
            "fixup": "never",
            "no-continue": True,
        }
        with self._ytdl.YoutubeDL(opts) as ydl:
            ydl.download([url])


class ChunkStore(Protocol):
    """Remote artifact store (reference SFTP/Azure outputs)."""

    def exists(self, rel_path: str) -> bool:
        """True if `rel_path` exists on the store, whether it names a
        DIRECTORY (chunk tree `<name>/`) or a FILE (`<name>/<name>.mp4`,
        the finished-MP4 layout). Implementations must answer for both —
        SftpStore stat()s either kind."""
        ...

    def listdir(self, rel_path: str) -> list[str]: ...

    def download(self, rel_path: str, local_path: str) -> None: ...


class SftpStore:
    """Paramiko-backed ChunkStore (reference download_from_sftp /
    check_output_existence_level SFTP branches, :746-785, :940-1001).
    The connection is deferred to first use: construction happens at p01
    plan time even for databases whose segments never touch the store, and
    a plan step must not block on a TCP dial. paramiko-missing surfaces at
    construction (cheap, actionable); network errors surface at first
    access. All operations serialize on one lock: p01 runs online jobs
    `-p`-wide and a paramiko SFTP channel is not safe for concurrent
    requests (nor is the lazy connect's check-then-set)."""

    def __init__(self, host: str, port: int, user: str, password: str, root: str) -> None:
        try:
            import paramiko  # type: ignore  # noqa: F401
        except ImportError as exc:
            raise RuntimeError("paramiko is not installed; SFTP store unavailable") from exc

        self._params = (host.split(":")[0], port, user, password)
        self._sftp = None
        self._transport = None
        self._lock = lockdebug.make_lock("downloader")
        self.root = root

    def _client(self):
        # callers hold self._lock
        if self._sftp is None:
            import paramiko  # type: ignore

            host, port, user, password = self._params
            transport = paramiko.Transport((host, port))
            transport.connect(username=user, password=password)
            self._sftp = paramiko.SFTPClient.from_transport(transport)
            self._transport = transport
        return self._sftp

    def _abs(self, rel_path: str) -> str:
        return os.path.join(self.root, rel_path)

    def exists(self, rel_path: str) -> bool:
        with self._lock:
            try:
                self._client().stat(self._abs(rel_path))
                return True
            except OSError:
                return False

    def listdir(self, rel_path: str) -> list[str]:
        with self._lock:
            return self._client().listdir(self._abs(rel_path))

    def download(self, rel_path: str, local_path: str) -> None:
        os.makedirs(os.path.dirname(local_path), exist_ok=True)
        with self._lock:
            self._client().get(self._abs(rel_path), local_path)

    def close(self) -> None:
        with self._lock:
            if self._sftp is not None:
                self._sftp.close()
                self._transport.close()
                self._sftp = self._transport = None


# ------------------------------------------------------- settings loading


@dataclass
class BitmovinSettings:
    """Credentials + endpoints from a `bitmovin_settings/` folder — the
    reference's convention (reference assets bitmovin_settings/
    {keyfile.txt, input_details.yaml, output_details.yaml}; consumed at
    lib/downloader.py:389-446)."""

    api_key: str
    input_details: dict
    output_details: dict


def load_bitmovin_settings(settings_dir: str) -> BitmovinSettings:
    """Read the three settings files. Raises FileNotFoundError with the
    expected layout when absent, so a misconfigured cloud run fails with
    an actionable message instead of mid-upload."""
    import yaml

    keyfile = os.path.join(settings_dir, "keyfile.txt")
    input_file = os.path.join(settings_dir, "input_details.yaml")
    output_file = os.path.join(settings_dir, "output_details.yaml")
    for path in (keyfile, input_file, output_file):
        if not os.path.isfile(path):
            raise FileNotFoundError(
                f"bitmovin settings file {path} missing; expected layout: "
                f"{settings_dir}/{{keyfile.txt,input_details.yaml,"
                "output_details.yaml}"
            )
    with open(keyfile) as f:
        api_key = f.read().strip()
    if not api_key:
        raise ValueError(f"{keyfile} is empty — put the Bitmovin API key there")
    with open(input_file) as f:
        input_details = yaml.safe_load(f) or {}
    with open(output_file) as f:
        output_details = yaml.safe_load(f) or {}
    return BitmovinSettings(api_key, input_details, output_details)


def make_chunk_store(settings: BitmovinSettings) -> Optional["SftpStore"]:
    """Build the output-side chunk store from output_details.yaml (sftp
    only; azure output has no local fetch path — reference's
    `download_from_azure` was called but never defined, downloader.py:439,
    a bug on the do-not-copy list)."""
    out = settings.output_details
    kind = str(out.get("type", "")).casefold()
    if kind != "sftp":
        get_logger().warning(
            "output_details type %r has no chunk-fetch support; resume "
            "levels needing remote chunks are unavailable", kind,
        )
        return None
    return SftpStore(
        host=out["host"],
        port=int(out.get("port", 22)),
        user=out["user"],
        password=out["password"],
        root=out.get("root", out.get("path", "")),
    )


# ---------------------------------------------------------- chunk reassembly


def _chunk_suffixes(codec: str) -> tuple[str, str]:
    """(init suffix, chunk suffix) per codec family (reference :799-805)."""
    if codec == "vp9":
        return "init.hdr", ".chk"
    return "init.mp4", ".m4s"


def _collect_parts(names: Sequence[str], codec: str, where: str) -> tuple[str, list[str]]:
    """Init element + index-ordered chunk list from a directory listing.
    Missing indices are an error (the reference leaves 'Dummy_entry' holes
    that produce broken concat commands, :807-814 — do-not-copy)."""
    init_suffix, chunk_suffix = _chunk_suffixes(codec)
    init_element: Optional[str] = None
    parts: dict[int, str] = {}
    for name in names:
        if name.endswith(init_suffix):
            if init_element is not None:
                get_logger().warning("Second init file found. Please clean %s", where)
            init_element = name
        elif name.endswith(chunk_suffix):
            parts[int(os.path.splitext(name)[0].split("_")[-1])] = name
    if init_element is None:
        raise FileNotFoundError(f"no init file found in {where}")
    missing = sorted(set(range(max(parts) + 1)) - set(parts)) if parts else []
    if missing:
        raise FileNotFoundError(f"missing chunk indices {missing} in {where}")
    return init_element, [parts[i] for i in sorted(parts)]


def concat_chunks(chunk_dir: str, codec: str, out_path: str) -> str:
    """Binary-concatenate init + ordered chunks (what the reference's
    ffmpeg `concat:` protocol does, :819-825) into `out_path`."""
    init_element, parts = _collect_parts(os.listdir(chunk_dir), codec, chunk_dir)
    from ..utils.fsio import atomic_write

    def _write(tmp: str) -> None:
        # atomic: a crash mid-concat must not leave a truncated media
        # file that a later run's exists-check would adopt as complete
        with open(tmp, "wb") as out:
            for name in [init_element, *parts]:
                with open(os.path.join(chunk_dir, name), "rb") as f:
                    out.write(f.read())

    atomic_write(out_path, _write)
    return out_path


# ------------------------------------------------------------------- facade


class Downloader:
    """Online-segment producer for p01 (reference Downloader, :45-1001)."""

    def __init__(
        self,
        video_segments_folder: str,
        youtube: Optional[YoutubeClient] = None,
        store: Optional[ChunkStore] = None,
        overwrite: bool = False,
        bitmovin_api: Optional["bitmovin.BitmovinApi"] = None,
        bitmovin_settings: Optional[BitmovinSettings] = None,
    ) -> None:
        self.video_segments_folder = video_segments_folder
        self.youtube = youtube
        self.store = store
        self.overwrite = overwrite
        self.bitmovin_api = bitmovin_api
        self.bitmovin_settings = bitmovin_settings

    @classmethod
    def from_settings(
        cls, video_segments_folder: str, settings_dir: Optional[str] = None,
        overwrite: bool = False,
    ) -> "Downloader":
        """Construct with the `bitmovin_settings/` folder convention
        (defaults to <repo root>/bitmovin_settings like the reference).
        YouTube needs no credentials; the chunk store comes from
        output_details.yaml."""
        if settings_dir is None:
            settings_dir = os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)
                ))),
                "bitmovin_settings",
            )
        store = None
        bm_settings = None
        bm_api = None
        if os.path.isdir(settings_dir):
            # misconfigured credentials must degrade (store=None), never
            # abort p01: YouTube-only databases need no Bitmovin settings
            # at all, and paramiko raises bare-Exception subclasses
            try:
                settings = load_bitmovin_settings(settings_dir)
                out = settings.output_details
                if str(out.get("host", "")) == "example.com":
                    get_logger().warning(
                        "bitmovin_settings/ still holds the shipped "
                        "template values; cloud chunk store disabled"
                    )
                else:
                    store = make_chunk_store(settings)
                    bm_settings = settings
                    try:
                        from .bitmovin import SdkBitmovinApi

                        bm_api = SdkBitmovinApi(settings.api_key)
                    except RuntimeError as exc:
                        get_logger().info(
                            "Bitmovin cloud submission unavailable (%s); "
                            "resume levels 1-3 still served", exc,
                        )
            except Exception as exc:  # noqa: BLE001 - degrade by design
                get_logger().warning(
                    "bitmovin settings unusable (%s); continuing without a "
                    "cloud chunk store", exc,
                )
        youtube = None
        try:
            youtube = YtdlClient()
        except RuntimeError:
            pass  # no yt-dlp in the environment; YouTube paths unavailable
        return cls(
            video_segments_folder, youtube=youtube, store=store,
            overwrite=overwrite, bitmovin_api=bm_api,
            bitmovin_settings=bm_settings,
        )

    # ------------------------------------------------------------- youtube

    def download_video(
        self,
        url: str,
        width: int,
        height: int,
        filename: str,
        vcodec: str,
        bitrate: float,
        protocol: Optional[str] = None,
        fps: Any = "original",
        force_overwriting: bool = False,
    ) -> Optional[str]:
        """Download the best-matching format; returns the local path or None
        (reference download_video, :153-348)."""
        log = get_logger()
        if protocol not in ("dash", "hls", "mpd", "m3u8", None):
            raise ValueError("Only DASH, HLS, MPD, M3U8 allowed as protocols")
        if self.youtube is None:
            self.youtube = YtdlClient()

        info = self.youtube.extract_info(url)
        chosen = select_format(
            info["formats"], int(height), float(bitrate), vcodec, protocol, fps
        )
        if chosen is None:
            log.error(
                "Combination of vcodec %s and bitrate %s (fps %s) is not "
                "available for %s! Please choose another one.",
                vcodec, bitrate, fps, url,
            )
            return None

        # the selected format's container, not the info-level default —
        # a chosen video-only webm downloads as .webm regardless of
        # info["ext"] (reference keys the exists-check off the wrong ext)
        dl_file = os.path.join(
            self.video_segments_folder, filename + "." + chosen.ext
        )
        if os.path.exists(dl_file) and not (force_overwriting or self.overwrite):
            log.warning("File %s exists; use -f to overwrite.", dl_file)
            return dl_file

        outtmpl = os.path.join(self.video_segments_folder, filename + ".%(ext)s")
        self.youtube.download(url, chosen.format_id, outtmpl)
        if os.path.exists(dl_file):
            check_video_len(dl_file)
        if (int(width), int(height)) != (chosen.width, chosen.height):
            log.warning(
                "The available resolution for bitrate %s is %dx%d@%gfps for "
                "file %s (originally specified: %dx%d, fps: %s)",
                bitrate, chosen.width, chosen.height, chosen.fps, filename,
                width, height, fps,
            )
        if protocol and not chosen.protocol_matched:
            log.warning("Protocol '%s' not available for video %s.", protocol, filename)
        return dl_file

    def _youtube_available(self) -> bool:
        """Whether a YouTube download could succeed in this environment.
        `download_video` constructs YtdlClient lazily, so keying the plan
        decision on `self.youtube is None` would declare a perfectly
        feasible run infeasible (constructed without a client but with
        yt-dlp importable) — probe actual importability, through the
        SAME module-resolution definition the client constructor uses
        (`find_ytdl_module`), so plan and download can never disagree."""
        if self.youtube is not None:
            return True
        return find_ytdl_module() is not None

    def plan_capability(self, seg, force: bool = False) -> Optional[str]:
        """Plan-time feasibility of producing this online segment in THIS
        environment: None when a run can succeed, else an actionable
        reason. The reference discovers these failures only at download
        time, deep inside p01 (lib/downloader.py:306-326 yt-dlp import,
        :734-740 Bitmovin wait) — here p00/p01 fail (or skip under -sos)
        BEFORE any work runs, with the full affected-segment list."""
        if not force and os.path.isfile(
            os.path.join(self.video_segments_folder, seg.filename)
        ):
            return None  # already produced; plan is a no-op
        if seg.video_coding.encoder.casefold() == "bitmovin":
            if self.bitmovin_api is not None and self.store is not None:
                return None
            # resume levels 1-2 work without the SDK: existing chunks
            audio = seg.quality_level.audio_bitrate is not None
            if self._chunk_level(
                seg.filename, seg.quality_level.video_codec, audio
            ) > 0:
                return None
            if self.store is not None and str(
                seg.quality_level.video_codec
            ).casefold() in _H26X:
                return None  # a finished cloud mp4 may still be fetchable
            return (
                "Bitmovin cloud encode needs bitmovin_settings/ credentials "
                "+ the bitmovin-api-sdk (none configured) and no "
                "local/remote chunks exist to resume from"
            )
        if not self._youtube_available():
            return (
                "YouTube download needs yt-dlp (or youtube-dl), which is "
                "not importable in this environment — pip install yt-dlp, "
                "or re-run with -sos to skip online segments"
            )
        return None

    def init_download(self, seg, force: bool = False) -> Optional[str]:
        """Segment-level entry for p01 (reference init_download, :351-385):
        resolves the fps ladder spec against the SRC fps, then downloads."""
        name, _ext = os.path.splitext(seg.filename)
        protocol = getattr(seg.video_coding, "protocol", None)
        # same fps grammar as offline encodes (ops/fps.resolve_fps_spec,
        # used by models/segments.py) so one config line means one rate
        from ..ops.fps import resolve_fps_spec

        target = resolve_fps_spec(
            str(seg.quality_level.fps), float(seg.src.get_fps())
        )
        frame_rate: Any = "original" if target is None else target
        return self.download_video(
            seg.src.youtube_url,
            int(seg.quality_level.width),
            int(seg.quality_level.height),
            name,
            seg.quality_level.video_codec,
            float(seg.quality_level.video_bitrate),
            protocol=protocol.casefold() if protocol else None,
            fps=frame_rate,
            force_overwriting=force,
        )

    # ------------------------------------------------------------ bitmovin

    def _chunk_level(self, filename: str, codec: str, audio: bool) -> int:
        """2 = local chunks complete, 1 = remote chunks complete, 0 = none."""
        codec = codec.casefold()
        root = os.path.splitext(filename)[0]

        def chunks_complete(names: Sequence[str], where: str) -> bool:
            try:
                _collect_parts(names, codec, where)
                return True
            except FileNotFoundError:
                return False

        local_dir = os.path.join(self.video_segments_folder, root)
        if os.path.isdir(local_dir):
            ok = chunks_complete(os.listdir(local_dir), local_dir)
            if ok and audio:
                audio_dir = os.path.join(local_dir, "audio")
                ok = os.path.isdir(audio_dir) and chunks_complete(
                    os.listdir(audio_dir), audio_dir
                )
            if ok:
                return 2

        if self.store is not None and self.store.exists(root):
            ok = chunks_complete(self.store.listdir(root), root)
            if ok and audio:
                remote_audio = os.path.join(root, "audio")
                ok = self.store.exists(remote_audio) and chunks_complete(
                    self.store.listdir(remote_audio), remote_audio
                )
            if ok:
                return 1
        return 0

    def check_output_existence_level(self, filename: str, codec: str, audio: bool) -> int:
        """Resume level (reference check_output_existence_level, :873-1001):
        3 = final segment exists locally, 2 = local chunks complete,
        1 = remote chunks complete, 0 = nothing usable."""
        if os.path.isfile(os.path.join(self.video_segments_folder, filename)):
            return 3
        return self._chunk_level(filename, codec, audio)

    def fetch_remote_chunks(self, filename: str, audio: bool) -> str:
        """Pull the chunk tree for `filename` from the remote store into the
        local segments folder (reference download_from_sftp, :746-785)."""
        if self.store is None:
            raise RuntimeError("no remote chunk store configured")
        root = os.path.splitext(filename)[0]
        local_dir = os.path.join(self.video_segments_folder, root)
        os.makedirs(local_dir, exist_ok=True)
        for name in self.store.listdir(root):
            remote = os.path.join(root, name)
            if name == "audio":
                continue
            self.store.download(remote, os.path.join(local_dir, name))
        if audio:
            audio_dir = os.path.join(local_dir, "audio")
            os.makedirs(audio_dir, exist_ok=True)
            for name in self.store.listdir(os.path.join(root, "audio")):
                self.store.download(
                    os.path.join(root, "audio", name), os.path.join(audio_dir, name)
                )
        return local_dir

    def generate_full_segment(self, filename: str, codec: str, audio: bool = False) -> str:
        """Reassemble the final segment from local chunks (reference
        generate_full_segment, :786-871): binary init+chunk concat, then a
        native stream-copy remux (+ audio mux)."""
        from ..io import medialib

        codec = codec.casefold()
        root, ext = os.path.splitext(filename)
        chunk_dir = os.path.join(self.video_segments_folder, root)
        full_video_path = os.path.join(self.video_segments_folder, filename)

        video_concat = concat_chunks(
            chunk_dir, codec, os.path.join(chunk_dir, root + "_video_only" + ext)
        )
        audio_concat = ""
        if audio:
            audio_dir = os.path.join(chunk_dir, "audio")
            try:
                audio_concat = concat_chunks(
                    audio_dir, codec, os.path.join(audio_dir, root + "_audio_only.mp4")
                )
            except FileNotFoundError:
                get_logger().warning(
                    "No audio file for %s found. Will create a video without audio!",
                    root,
                )
        medialib.remux(video_concat, full_video_path, audio_path=audio_concat)
        return full_video_path

    def encode_bitmovin(self, seg, overwrite: bool = False) -> Optional[str]:
        """Resume-aware Bitmovin path for one segment (reference
        encode_bitmovin, :387-744). Levels 3/2/1 are served from existing
        artifacts; level 0 submits a cloud encode through the injected
        `bitmovin_api` client (services.bitmovin), then reassembles the
        resulting chunks exactly like a level-1 resume."""
        log = get_logger()
        audio = seg.quality_level.audio_bitrate is not None
        filename = seg.filename
        codec = seg.quality_level.video_codec

        force = overwrite or self.overwrite
        h26x = str(codec).casefold() in _H26X
        if not force and os.path.isfile(
            os.path.join(self.video_segments_folder, filename)
        ):
            log.info("%s already exists. Use -f for overwriting", filename)
            return os.path.join(self.video_segments_folder, filename)

        # h26x cloud encodes land as ONE finished mp4 (the plan's MP4Muxing,
        # reference :698-711), not a chunk tree: try pulling it directly
        # (reference's download_from_sftp pre-check, :418-421)
        if not force and h26x:
            final = self._download_final_mp4(filename)
            if final:
                return final

        # with --force the final segment is still regenerated from chunks —
        # a cloud re-encode of identical settings would be wasted spend
        chunk_level = self._chunk_level(filename, codec, audio)
        if chunk_level == 2:
            log.info("%s will be generated from existing local chunks", filename)
            return self.generate_full_segment(filename, codec, audio)
        if chunk_level == 1:
            log.info("%s will be generated from remote chunks", filename)
            self.fetch_remote_chunks(filename, audio)
            return self.generate_full_segment(filename, codec, audio)
        if self.bitmovin_api is None or self.bitmovin_settings is None:
            raise RuntimeError(
                "no cloud artifacts exist for this segment and no Bitmovin "
                "API client is configured (Downloader(bitmovin_api=...) plus "
                "bitmovin_settings/); only resume levels 1-3 are available"
            )
        if self.store is None:
            # check BEFORE submitting: a cloud encode whose output cannot
            # be fetched back is pure spend
            raise RuntimeError(
                "no remote chunk store configured (output_details.yaml) — "
                "refusing to submit a Bitmovin encode whose output could "
                "not be fetched back"
            )
        from . import bitmovin as bm

        plan = bm.plan_encoding(seg, self.bitmovin_settings)
        log.info("submitting Bitmovin encode for %s (%s)", filename, plan.codec)
        bm.submit_encoding(self.bitmovin_api, plan)
        if h26x:
            final = self._download_final_mp4(filename)
            if final is None:
                raise RuntimeError(
                    f"Bitmovin encode for {filename} finished but "
                    f"{os.path.splitext(filename)[0]}.mp4 is not on the store"
                )
            return final
        self.fetch_remote_chunks(filename, audio)
        return self.generate_full_segment(filename, codec, audio)

    def _download_final_mp4(self, filename: str) -> Optional[str]:
        """Pull `<name>/<name>.mp4` (the MP4Muxing layout plan_encoding
        requests) from the store into the segments folder; None when the
        store is absent or the file is not there."""
        if self.store is None:
            return None
        name = os.path.splitext(filename)[0]
        rel = os.path.join(name, f"{name}.mp4")
        if not self.store.exists(rel):
            return None
        final = os.path.join(self.video_segments_folder, filename)
        # atomic download-then-rename: an interrupted transfer must never
        # leave a truncated file at the final segment path, where every
        # later run's isfile pre-check would treat it as a finished encode
        atomic_write(final, lambda tmp: self.store.download(rel, tmp))
        get_logger().info("downloaded finished cloud encode %s", filename)
        return final
