"""Orchestrator: run stages 1-4 in order (reference p00_processAll.py:24-53).

The parsed TestConfig is threaded through so later stages skip re-parsing
(reference p00:38), and `-str "1234"` selects a stage subset.
"""

from __future__ import annotations

from typing import Optional

from ..config import TestConfig
from ..utils.log import get_logger
from . import (
    p01_generate_segments,
    p02_generate_metadata,
    p03_generate_avpvs,
    p04_generate_cpvs,
)

_STAGES = {
    "1": p01_generate_segments,
    "2": p02_generate_metadata,
    "3": p03_generate_avpvs,
    "4": p04_generate_cpvs,
}


def run(cli_args) -> Optional[TestConfig]:
    log = get_logger()
    selection = cli_args.scripts_to_run
    if selection == "all":
        selection = "1234"
    from ..parallel.distributed import (
        barrier_run_id,
        fs_barrier,
        fs_barrier_init,
        process_topology,
    )

    multi_host = process_topology()[1] > 1
    if multi_host:
        barrier_run_id()  # fail fast if PC_RUN_ID is missing/unsafe
    barrier_ready = False
    test_config = None
    for key in "1234":
        if key not in selection:
            continue
        log.info("=== stage p0%s ===", key)
        test_config = _STAGES[key].run(cli_args, test_config=test_config)
        if multi_host and test_config is not None:
            if not barrier_ready:
                fs_barrier_init(test_config.get_logs_path())
                barrier_ready = True
            # multi-host: stage shards differ (p01 by segment, p02-p04 by
            # PVS), so no host may advance until every host finished the
            # stage — its inputs can live on another host's shard
            fs_barrier(f"p0{key}", test_config.get_logs_path())
    return test_config
