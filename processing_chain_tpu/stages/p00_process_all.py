"""Orchestrator: run stages 1-4 in order (reference p00_processAll.py:24-53).

The parsed TestConfig is threaded through so later stages skip re-parsing
(reference p00:38), and `-str "1234"` selects a stage subset.
"""

from __future__ import annotations

from typing import Optional

from ..config import TestConfig
from ..utils.log import get_logger
from . import (
    p01_generate_segments,
    p02_generate_metadata,
    p03_generate_avpvs,
    p04_generate_cpvs,
)

_STAGES = {
    "1": p01_generate_segments,
    "2": p02_generate_metadata,
    "3": p03_generate_avpvs,
    "4": p04_generate_cpvs,
}


def run(cli_args) -> Optional[TestConfig]:
    log = get_logger()
    selection = cli_args.scripts_to_run
    if selection == "all":
        selection = "1234"
    import time

    from ..parallel.distributed import fs_barrier, process_topology

    # barrier gate: only markers written after this run started count
    # (2 min slack for host clock skew)
    run_start = time.time() - 120.0
    test_config = None
    for key in "1234":
        if key not in selection:
            continue
        log.info("=== stage p0%s ===", key)
        test_config = _STAGES[key].run(cli_args, test_config=test_config)
        if process_topology()[1] > 1 and test_config is not None:
            # multi-host: stage shards differ (p01 by segment, p02-p04 by
            # PVS), so no host may advance until every host finished the
            # stage — its inputs can live on another host's shard
            fs_barrier(
                f"p0{key}", test_config.get_logs_path(), min_mtime=run_start
            )
    return test_config
