"""Stage 1: encode all required segments (reference p01_generateSegments.py:30-101)."""

from __future__ import annotations

from typing import Optional

from ..config import TestConfig
from ..engine.jobs import JobRunner
from ..models import segments as seg_model
from ..utils.log import get_logger


def run(cli_args, test_config: Optional[TestConfig] = None) -> TestConfig:
    log = get_logger()
    if test_config is None:
        test_config = TestConfig(
            cli_args.test_config, cli_args.filter_src, cli_args.filter_hrc,
            cli_args.filter_pvs,
        )

    runner = JobRunner(
        force=cli_args.force,
        dry_run=cli_args.dry_run,
        parallelism=cli_args.parallelism,
        name="p01",
    )
    for segment in sorted(test_config.get_required_segments()):
        if getattr(segment.video_coding, "is_online", False):
            if cli_args.skip_online_services:
                log.warning("Skipping online segment %s", segment.filename)
                continue
            log.warning(
                "online encoder %s for %s is not available in this "
                "environment; skipping (use the downloader tool)",
                segment.video_coding.encoder, segment.filename,
            )
            continue
        runner.add(seg_model.encode_segment(segment))
    log.info("p01: %d segment encodes planned", len(runner.jobs))
    # device work is serialized through the single chip; host decode/encode
    # parallelism lives inside the native layer
    runner.run_serial()
    return test_config
