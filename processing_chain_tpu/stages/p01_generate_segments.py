"""Stage 1: encode all required segments (reference p01_generateSegments.py:30-101)."""

from __future__ import annotations

import os
from typing import Optional

from .. import telemetry as tm
from ..config import TestConfig
from ..engine.jobs import Job, JobRunner
from ..models import segments as seg_model
from ..parallel.distributed import local_shard
from ..utils.log import get_logger


def run(cli_args, test_config: Optional[TestConfig] = None) -> TestConfig:
    with tm.stage_span("p01"):
        return _run(cli_args, test_config)


def _run(cli_args, test_config: Optional[TestConfig]) -> TestConfig:
    log = get_logger()
    if test_config is None:
        test_config = TestConfig(
            cli_args.test_config, cli_args.filter_src, cli_args.filter_hrc,
            cli_args.filter_pvs,
        )

    # "warn once per run": a run is one p01 invocation, not the process
    # lifetime (a long-lived caller processing several databases must warn
    # for each)
    seg_model.reset_run_state()
    runner = JobRunner(
        force=cli_args.force,
        dry_run=cli_args.dry_run,
        parallelism=cli_args.parallelism,
        name="p01",
    )
    downloader = None
    infeasible: list[tuple[str, str]] = []  # (segment filename, reason)
    shard_srcs: dict[str, None] = {}  # ordered distinct SRC paths
    # multi-host: each process takes a deterministic shard of the
    # segment set (keyed by filename; distinct outputs per key)
    all_segments = {s.filename: s for s in sorted(test_config.get_required_segments())}
    for _, segment in local_shard(all_segments):
        # priming is an accelerator, never a gate: a segment without a
        # source handle simply contributes nothing to the prime set
        src = getattr(segment, "src", None)
        if src is not None and getattr(src, "file_path", None):
            shard_srcs.setdefault(src.file_path)
        if getattr(segment.video_coding, "is_online", False):
            if cli_args.skip_online_services:
                log.warning("Skipping online segment %s", segment.filename)
                continue
            if downloader is None:
                from ..services import Downloader

                downloader = Downloader.from_settings(
                    test_config.get_video_segments_path()
                )
            # plan-time feasibility (VERDICT r4 #6): a missing yt-dlp /
            # Bitmovin SDK fails HERE with every affected segment named,
            # not minutes later inside the first download job
            reason = downloader.plan_capability(segment, force=cli_args.force)
            if reason is not None:
                infeasible.append((segment.filename, reason))
                continue
            encoder = segment.video_coding.encoder.casefold()
            seg, force = segment, cli_args.force
            if encoder == "bitmovin":
                fn = lambda s=seg, f=force: downloader.encode_bitmovin(s, overwrite=f)  # noqa: E731
            else:
                fn = lambda s=seg, f=force: downloader.init_download(s, force=f)  # noqa: E731
            runner.add(Job(
                label=f"online:{segment.filename}",
                output_path=segment.file_path,
                fn=fn,
            ))
            continue
        runner.add(seg_model.encode_segment(segment))
    if infeasible:
        from ..config.errors import ConfigError

        lines = "\n".join(f"  {name}: {why}" for name, why in infeasible)
        raise ConfigError(
            f"{len(infeasible)} online segment(s) cannot be produced in "
            f"this environment:\n{lines}\n"
            "(use -sos to skip online services, or provide the listed "
            "tooling/credentials)"
        )
    log.info("p01: %d segment encodes planned", len(runner.jobs))
    tm.stage_items("p01", len(runner.jobs))
    # pure host work (libav encode via ctypes releases the GIL): run the
    # encodes `-p`-wide like the reference's Pool(4) (cmd_utils.py:93-101);
    # each encode stays -threads 1, so parallelism comes from the pool
    runner.run()
    _prime_src_priors(list(shard_srcs), dry_run=cli_args.dry_run)
    return test_config


def _prime_src_priors(src_paths: list, *, dry_run: bool = False) -> None:
    """Encode-time priors capture (docs/PRIORS.md): extract each SRC's
    MV/QP/frame-type sidecar while p01 owns the SRC bitstreams, committed
    under the UNCHANGED priors plan hash — later complexity / serve
    cost-feature calls are then pure warm hits with zero extra bitstream
    passes. Gated to store-backed runs by default (the sidecar outlives
    the process there); PC_PRIORS_PRIME=1 forces storeless priming onto
    the mtime-freshness sidecar path, =0 disables. Failures are logged,
    never fatal — priming is an accelerator, not a stage output."""
    mode = os.environ.get("PC_PRIORS_PRIME", "auto")
    if mode == "0" or dry_run or not src_paths:
        return
    from ..store import runtime as store_runtime

    if mode != "1" and store_runtime.active() is None:
        return
    from .. import priors

    log = get_logger()
    for src in src_paths:
        try:
            _, hit = priors.ensure_priors(src)
        except Exception as exc:  # noqa: BLE001 - accelerator, not a gate
            log.warning("priors prime failed for %s: %s", src, exc)
        else:
            log.info("p01: priors %s for %s",
                     "warm" if hit else "primed", os.path.basename(src))
