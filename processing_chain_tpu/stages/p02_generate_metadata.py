"""Stage 2: metadata artifacts (reference p02_generateMetadata.py:33-152)."""

from __future__ import annotations

from typing import Optional

from .. import telemetry as tm
from ..config import TestConfig
from ..engine.jobs import JobRunner
from ..models import metadata as md
from ..parallel.distributed import local_shard
from ..utils.log import get_logger


def run(cli_args, test_config: Optional[TestConfig] = None) -> TestConfig:
    with tm.stage_span("p02"):
        return _run(cli_args, test_config)


def _run(cli_args, test_config: Optional[TestConfig]) -> TestConfig:
    log = get_logger()
    if test_config is None:
        test_config = TestConfig(
            cli_args.test_config, cli_args.filter_src, cli_args.filter_hrc,
            cli_args.filter_pvs,
        )
    # Job-per-PVS (like every other stage) so metadata participates in
    # the artifact store: plan = segment digests + stall schedule, the
    # four tables commit/materialize together. Without a store, Job's
    # skip-existing on the qchanges table plus the model's per-file
    # _maybe_write guards reproduce the legacy behavior. The jobs run
    # `-p`-wide through the pool (ROADMAP item 3): one PVS's tables
    # never read another's, so per-PVS metadata is free throughput —
    # the native demux releases the GIL and the numpy scans are
    # per-file, exactly the p01 encode-pool shape.
    runner = JobRunner(
        force=cli_args.force, dry_run=cli_args.dry_run,
        parallelism=cli_args.parallelism, name="p02",
    )
    n_items = 0
    for _pvs_id, pvs in local_shard(test_config.pvses):
        if cli_args.skip_online_services and pvs.is_online():
            log.warning("Skipping PVS %s because it is an online service", pvs)
            continue
        runner.add(md.metadata_job(pvs, force=cli_args.force))
        n_items += 1
    tm.stage_items("p02", n_items)
    runner.run()
    return test_config
