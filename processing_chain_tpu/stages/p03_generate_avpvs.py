"""Stage 3: AVPVS generation (reference p03_generateAvPvs.py:62-267)."""

from __future__ import annotations

import os
from typing import Optional

from .. import telemetry as tm
from ..config import TestConfig
from ..engine.jobs import JobRunner, device_stage_parallelism
from ..models import avpvs as av
from ..parallel.distributed import local_shard
from ..utils.log import get_logger


def run(cli_args, test_config: Optional[TestConfig] = None) -> TestConfig:
    with tm.stage_span("p03"):
        return _run(cli_args, test_config)


def _run(cli_args, test_config: Optional[TestConfig]) -> TestConfig:
    log = get_logger()
    if test_config is None:
        test_config = TestConfig(
            cli_args.test_config, cli_args.filter_src, cli_args.filter_hrc,
            cli_args.filter_pvs,
        )

    pvs_par = device_stage_parallelism(cli_args.parallelism, "p03")
    runner = JobRunner(
        force=cli_args.force, dry_run=cli_args.dry_run,
        parallelism=pvs_par, name="p03",
    )
    stall_runner = JobRunner(
        force=cli_args.force, dry_run=cli_args.dry_run,
        parallelism=pvs_par, name="p03-stall",
    )
    # p00 parses without the p03-only flags; fall back to the default
    # spinner so orchestrated runs still composite it (the reference p00
    # re-parses per-script args, p00_processAll.py:33-34)
    from ..utils.parse_args import _DEFAULT_SPINNER

    spinner = getattr(cli_args, "spinner_path", None) or _DEFAULT_SPINNER
    avpvs_src_fps = getattr(cli_args, "avpvs_src_fps", False)
    force_60_fps = getattr(cli_args, "force_60_fps", False)
    # writeback knobs: the flag (when given) takes precedence over the
    # env, by becoming it — every model-layer consumer (single-device,
    # batch, stalling) reads the env, so one mechanism serves both
    ffv1_workers = getattr(cli_args, "ffv1_workers", None)
    if ffv1_workers is not None:
        os.environ["PC_FFV1_WORKERS"] = str(max(0, ffv1_workers))
    # always install the pool-aware defaults for whatever is NOT pinned:
    # an explicit --ffv1-workers 0 must still divide the serial writers'
    # slice-threading (PC_FFV1_THREADS) across the `-p` pool width
    av.set_default_fp_workers(pvs_par)
    avpvs_codec = getattr(cli_args, "avpvs_codec", None)
    if avpvs_codec:
        os.environ["PC_AVPVS_CODEC"] = avpvs_codec
    # fused p04 fan-out (PC_FUSE_P04, models/fused): PVSes whose AVPVS
    # is due render the stalling pass + every CPVS context from the
    # same decode. Dry runs must plan exactly like the legacy path, so
    # planning-only runs never engage it. The p04 knobs ride getattr
    # defaults, matching what the p04 stage would use in the same
    # orchestrated run (its namespace carries the same defaults).
    from ..models import fused as fused_mod

    fuse = fused_mod.fused_p04_enabled() and not cli_args.dry_run
    fanouts: dict = {}

    def _fanout(pvs):
        fo = fused_mod.FusedFanout(
            pvs, spinner_path=spinner,
            rawvideo=bool(getattr(cli_args, "rawvideo", False)),
            nonraw_crf=int(getattr(cli_args, "nonraw_crf", 17)),
            preview=bool(getattr(cli_args, "lightweight_preview", False)),
        )
        fanouts[pvs] = fo
        return fo

    shard = local_shard(test_config.pvses)
    eligible = []
    for _pvs_id, pvs in shard:
        if cli_args.skip_online_services and pvs.is_online():
            log.warning("Skipping PVS %s because it is an online service", pvs)
            continue
        eligible.append(pvs)
    tm.stage_items("p03", len(eligible))
    from ..utils.device import device_count, select_device

    gpu_loc = getattr(cli_args, "set_gpu_loc", -1)
    with select_device(gpu_loc):
        # batch route preconditions, cheap-first: dry-run must not touch a
        # backend at all, and device_count() is the hang-guarded probe
        # (utils/device), never a bare jax.devices(). A -g pin means the
        # user wants ONE device busy — meshing over all of them would
        # override the pin via explicit shardings, so the pin disables
        # batching.
        batch = None
        if (
            not cli_args.dry_run
            and gpu_loc < 0
            and device_count() > 1
        ):
            # multi-device: batch the PVS set through the (pvs × time)
            # mesh instead of one device job per PVS (short: lane per PVS;
            # long: lane per segment + native stream-copy concat). The
            # per-PVS skip-existing/--force decision stays with Job
            # semantics (should_run), then due PVSes run as one batch.
            per_pvs = {
                pvs: av.create_avpvs_wo_buffer(
                    pvs, avpvs_src_fps=avpvs_src_fps, force_60_fps=force_60_fps
                )
                for pvs in eligible
            }
            todo = [
                pvs for pvs, job in per_pvs.items()
                if job.should_run(cli_args.force, runner="p03")
            ]
            if fuse:
                # short AND long lanes fan out in the wave driver: the
                # wave schedule pins a long PVS's per-segment lanes to
                # sequential waves in stream order
                # (parallel/p03_batch.plan_waves + models/fused
                # SegmentOrderedTap), so the staged fallback that used
                # to guard out-of-order delivery is gone
                for pvs in todo:
                    _fanout(pvs)
            runner.add(
                av.create_avpvs_wo_buffer_batch(
                    todo, avpvs_src_fps=avpvs_src_fps,
                    force_60_fps=force_60_fps,
                    fanouts=fanouts or None,
                )
            )
            batch = (todo, per_pvs)
        else:
            for pvs in eligible:
                runner.add(
                    av.create_avpvs_wo_buffer(
                        pvs,
                        avpvs_src_fps=avpvs_src_fps,
                        force_60_fps=force_60_fps,
                        fanout=_fanout(pvs) if fuse else None,
                    )
                )
        # two phases: stalling reads the wo_buffer outputs of phase one
        runner.run()
        if batch is not None:
            # batch finals are written outside Job.run: bind them to their
            # plan hashes here (no-op without an active store)
            for pvs in batch[0]:
                batch[1][pvs].commit_to_store()
        # stalling is planned only NOW: its plan input (the wo_buffer
        # render) must exist with its final bytes for the store's
        # hit/miss decision to be about THIS run's input, not a stale one
        for pvs in eligible:
            fo = fanouts.get(pvs)
            if fo is not None and fo.engaged and fo.stall_settled():
                # the fused render already produced AND committed the
                # stalled AVPVS from the in-memory stream (a DEGRADED
                # stalling member falls through to the staged pass —
                # models/fused graceful-degrade contract)
                continue
            stall_runner.add(av.apply_stalling(pvs, spinner_path=spinner))
        stall_runner.run()

    if cli_args.remove_intermediate:
        # only this host's shard: other hosts own (and may still be
        # reading) their own intermediates
        for _, pvs in shard:
            if pvs.has_buffering():
                tmp = pvs.get_avpvs_wo_buffer_file_path()
                if os.path.isfile(tmp):
                    log.debug("removing intermediate %s", tmp)
                    os.unlink(tmp)
                # the intermediate's feature sidecar goes with it
                av.SiTiAccumulator.discard(tmp)
    return test_config
