"""Stage 4: CPVS generation (reference p04_generateCpvs.py:31-81)."""

from __future__ import annotations

from typing import Optional

from .. import telemetry as tm
from ..config import TestConfig
from ..engine.jobs import JobRunner, device_stage_parallelism
from ..models import cpvs as cp
from ..parallel.distributed import local_shard
from ..utils.log import get_logger


def run(cli_args, test_config: Optional[TestConfig] = None) -> TestConfig:
    with tm.stage_span("p04"):
        return _run(cli_args, test_config)


def _run(cli_args, test_config: Optional[TestConfig]) -> TestConfig:
    log = get_logger()
    if test_config is None:
        test_config = TestConfig(
            cli_args.test_config, cli_args.filter_src, cli_args.filter_hrc,
            cli_args.filter_pvs,
        )
    pvs_par = device_stage_parallelism(cli_args.parallelism, "p04")
    # previews run ProRes through the same intra-writeback pool as the
    # p03 renders: install the pool-aware fp default (no-op when pinned)
    from ..models.avpvs import set_default_fp_workers

    set_default_fp_workers(pvs_par)
    runner = JobRunner(
        force=cli_args.force, dry_run=cli_args.dry_run,
        parallelism=pvs_par, name="p04",
    )
    n_items = 0
    for _pvs_id, pvs in local_shard(test_config.pvses):
        if cli_args.skip_online_services and pvs.is_online():
            log.warning("Skipping PVS %s because it is an online service", pvs)
            continue
        n_items += 1
        for pp in test_config.post_processings:
            runner.add(
                cp.create_cpvs(
                    pvs, pp,
                    rawvideo=getattr(cli_args, "rawvideo", False),
                    nonraw_crf=int(getattr(cli_args, "nonraw_crf", 17)),
                )
            )
        if getattr(cli_args, "lightweight_preview", False):
            runner.add(cp.create_preview(pvs))
    tm.stage_items("p04", n_items)
    from ..utils.device import select_device

    with select_device(getattr(cli_args, "set_gpu_loc", -1)):
        runner.run()
    return test_config
