"""Content-addressed artifact store: the chain's cache, checkpoint, and
integrity layer.

The reference (and PR 0's Job model) decides stale-vs-fresh with one bit:
"does the output file exist". Editing a single HRC parameter in the YAML
therefore either silently serves stale artifacts or forces a --force
rebuild of the entire database. This package replaces that bit with a
canonical **plan hash** per job — input file digests, resolved encode
parameters, tool + chain version (keys.py) — and a CAS object directory
with atomic commits, integrity-verified reads, and mark-and-sweep GC
(store.py, gc.py). See docs/STORE.md for the key schema, the on-disk
layout, the GC policy, and the telemetry series.

Layering: models build *plan payloads* (plain dicts with `keys.file_ref`
markers for input files); the engine (engine/jobs.py) resolves and hashes
them against the process-wide active store (runtime.py) at plan time and
commits outputs after a successful run. Nothing in this package imports
the model or stage layers.
"""

from .keys import canonical_json, file_ref, plan_hash  # noqa: F401
from .store import ArtifactStore, Manifest, StoreCorruption  # noqa: F401
