"""Pluggable CAS backends: where artifact bytes live (docs/STORE.md
"Tier hierarchy").

A `StoreBackend` owns one medium full of content-addressed objects and
nothing else — no manifests, no pins, no heat. The store's metadata
plane (manifests, the adoption ledger, the digest cache) always stays
on the store root; backends only hold and serve bytes, keyed by their
sha256. Three implementations ship:

  * `LocalBackend`  — the classic `objects/<sha[:2]>/<sha>` directory
    layout, extracted from store.py unchanged so every existing store
    root keeps working with zero migration (a bare root IS a one-tier
    config).
  * `SharedBackend` — the same layout rooted at a second local-FS path
    (an NFS/fuse mount shared by the fleet): the warm tier.
  * `ObjectBackend` — an S3-shaped cold tier speaking a minimal
    put/get/head/delete/list client interface; the directory-backed
    `DirObjectClient` reference implementation lets tests and CI run
    the full three-tier stack with no cloud in sight.

Commit discipline: `put`/`put_stream` are atomic where the medium
allows (tmp + fsync + rename on filesystems; a single PUT on object
stores) and `put_stream` verifies the streamed content digest BEFORE
the commit becomes visible — the integrity check lives at the boundary
the bytes cross, so a corrupt source can never materialize as a valid
key in another tier.
"""

from __future__ import annotations

import os
from typing import BinaryIO, Callable, Iterator, Optional

from .local import LocalBackend, SharedBackend
from .object import DirObjectClient, ObjectBackend, ObjectClient

__all__ = [
    "StoreBackend",
    "LocalBackend",
    "SharedBackend",
    "ObjectBackend",
    "ObjectClient",
    "DirObjectClient",
    "BackendIntegrityError",
    "make_backend",
    "crashpoint",
    "CRASH_HOOK",
]


class BackendIntegrityError(RuntimeError):
    """Streamed bytes did not match the digest they were keyed under;
    the commit was aborted before becoming visible."""


#: test seam for the placement-move crash-safety suite: when set, it is
#: called with a named commit boundary ("pre_commit" — destination tmp
#: bytes durable but not yet renamed; "pre_delete" — destination commit
#: durable, source copy still present) and may SIGKILL the process to
#: prove neither instant can tear an object or lose the only copy.
#: Never set in production.
CRASH_HOOK: Optional[Callable[[str], None]] = None


def crashpoint(name: str) -> None:
    hook = CRASH_HOOK
    if hook is not None:
        hook(name)


class StoreBackend:
    """The backend protocol. Implementations override everything; the
    base class only documents the contract.

    * `put(src_path, sha256)`      — commit a local file's bytes under a
      digest the caller already computed (the hot commit path: no
      re-hash, hardlink when the medium allows).
    * `put_stream(fileobj, sha256)` — stream bytes in, hashing as they
      arrive; the commit aborts with BackendIntegrityError on mismatch
      and is atomic+durable on success. Returns bytes written. This is
      the only way bytes cross tiers.
    * `open_read(sha256)`          — a binary file object over the bytes
      (an fd for filesystem media: the serve path fd-pins it).
    * `head(sha256)`               — object size, or None when absent.
    * `delete(sha256)`             — True when an object was removed.
    * `list()`                     — (sha256, size) for every object.
    * `local_path(sha256)`         — a filesystem path when the medium
      has one (hardlink materialization, fd serving), else None.
    * `tmp_dirs()`                 — in-flight commit scratch dirs for
      GC's tmp sweep (empty for media without one).
    """

    kind: str = "?"

    def put(self, src_path: str, sha256: str) -> None:
        raise NotImplementedError

    def put_stream(self, fileobj: BinaryIO, sha256: str) -> int:
        raise NotImplementedError

    def open_read(self, sha256: str) -> BinaryIO:
        raise NotImplementedError

    def head(self, sha256: str) -> Optional[int]:
        raise NotImplementedError

    def delete(self, sha256: str) -> bool:
        raise NotImplementedError

    def list(self) -> Iterator[tuple[str, int]]:
        raise NotImplementedError

    def local_path(self, sha256: str) -> Optional[str]:
        return None

    def tmp_dirs(self) -> tuple[str, ...]:
        return ()


def make_backend(kind: str, path: str) -> StoreBackend:
    """Backend factory for the `--store-tiers` spec kinds."""
    path = os.path.abspath(path)
    if kind == "local":
        return LocalBackend(os.path.join(path, "objects"),
                            os.path.join(path, "tmp"))
    if kind == "shared":
        return SharedBackend(path)
    if kind == "object":
        return ObjectBackend(DirObjectClient(path))
    raise ValueError(f"unknown store backend kind {kind!r} "
                     "(expected local, shared, or object)")
