"""Filesystem CAS backends: the classic `objects/` layout (hot) and the
same layout on a second shared root (warm).

The layout and commit protocol are the store's originals, extracted
verbatim (docs/STORE.md "On-disk layout"): `objects/<sha[:2]>/<sha>`,
tmp + rename commits with pid+thread-unique scratch names, an explicit
ingestion-time mtime stamp so GC's min-object-age guard protects
adopted-but-ancient files. An existing flat store root therefore opens
under a LocalBackend with zero migration.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import threading
from typing import BinaryIO, Iterator, Optional

_COPY_BLOCK = 1 << 20


def _link_or_copy(src: str, dst: str) -> None:
    try:
        os.link(src, dst)
    except OSError:
        # cross-device stores (or filesystems without hardlinks) copy
        shutil.copyfile(src, dst)


class LocalBackend:
    """One `objects/` directory plus its in-flight `tmp/` scratch."""

    kind = "local"

    def __init__(self, objects_dir: str, tmp_dir: str) -> None:
        self.objects_dir = os.path.abspath(objects_dir)
        self.tmp_dir = os.path.abspath(tmp_dir)
        os.makedirs(self.objects_dir, exist_ok=True)
        os.makedirs(self.tmp_dir, exist_ok=True)

    # ------------------------------------------------------------ layout

    def local_path(self, sha256: str) -> Optional[str]:
        return os.path.join(self.objects_dir, sha256[:2], sha256)

    def tmp_dirs(self) -> tuple[str, ...]:
        return (self.tmp_dir,)

    def _tmp_name(self, sha256: str) -> str:
        # pid+thread-unique: two workers committing byte-identical
        # objects must not truncate one scratch file under each other
        return os.path.join(
            self.tmp_dir,
            f"{sha256}.{os.getpid()}.{threading.get_ident()}.part",
        )

    # ------------------------------------------------------------ writes

    def put(self, src_path: str, sha256: str) -> None:
        obj = self.local_path(sha256)
        if os.path.isfile(obj):
            return  # identical objects dedupe by construction
        os.makedirs(os.path.dirname(obj), exist_ok=True)
        tmp = self._tmp_name(sha256)
        try:
            _link_or_copy(src_path, tmp)
            os.replace(tmp, obj)
        except BaseException:
            if os.path.isfile(tmp):
                os.unlink(tmp)
            raise
        self._stamp(obj)

    def put_stream(self, fileobj: BinaryIO, sha256: str) -> int:
        from . import BackendIntegrityError, crashpoint

        obj = self.local_path(sha256)
        if os.path.isfile(obj):
            return os.stat(obj).st_size
        os.makedirs(os.path.dirname(obj), exist_ok=True)
        tmp = self._tmp_name(sha256)
        hasher = hashlib.sha256()
        nbytes = 0
        try:
            with open(tmp, "wb") as out:
                while True:
                    block = fileobj.read(_COPY_BLOCK)
                    if not block:
                        break
                    hasher.update(block)
                    nbytes += len(block)
                    out.write(block)
                out.flush()
                os.fsync(out.fileno())
            if hasher.hexdigest() != sha256:
                raise BackendIntegrityError(
                    f"object {sha256[:12]}: streamed digest "
                    f"{hasher.hexdigest()[:12]} does not match its key"
                )
            crashpoint("pre_commit")
            os.replace(tmp, obj)
        except BaseException:
            if os.path.isfile(tmp):
                os.unlink(tmp)
            raise
        self._stamp(obj)
        return nbytes

    @staticmethod
    def _stamp(obj: str) -> None:
        try:
            # hardlinked objects inherit the SOURCE file's mtime; stamp
            # ingestion time explicitly so GC's min-object-age guard
            # protects a just-committed object regardless of its origin
            os.utime(obj)
        except OSError:
            pass

    # ------------------------------------------------------------- reads

    def open_read(self, sha256: str) -> BinaryIO:
        return open(self.local_path(sha256), "rb")

    def head(self, sha256: str) -> Optional[int]:
        try:
            return os.stat(self.local_path(sha256)).st_size
        except OSError:
            return None

    def delete(self, sha256: str) -> bool:
        try:
            os.unlink(self.local_path(sha256))
            return True
        except OSError:
            return False

    def list(self) -> Iterator[tuple[str, int]]:
        try:
            shards = sorted(os.listdir(self.objects_dir))
        except OSError:
            return
        for shard in shards:
            shard_dir = os.path.join(self.objects_dir, shard)
            if not os.path.isdir(shard_dir):
                continue
            for name in sorted(os.listdir(shard_dir)):
                try:
                    yield name, os.stat(
                        os.path.join(shard_dir, name)).st_size
                except OSError:
                    continue


class SharedBackend(LocalBackend):
    """The warm tier: the identical layout rooted at a second local-FS
    path (typically a mount the whole fleet shares). Separate class so
    configs and forensics name the ROLE, not just the medium."""

    kind = "shared"

    def __init__(self, root: str) -> None:
        root = os.path.abspath(root)
        super().__init__(os.path.join(root, "objects"),
                         os.path.join(root, "tmp"))
        self.root = root
