"""S3-shaped cold-tier backend.

`ObjectBackend` speaks a minimal object-store client interface —
put/get/head/delete/list by key — so a real S3/GCS client drops in
behind one adapter. The repo ships `DirObjectClient`, a directory-backed
reference implementation with the same visible semantics (atomic PUT,
flat key namespace, stream reads), so tests and CI exercise the full
three-tier stack without any cloud dependency.

The backend deliberately returns `local_path() -> None` even when the
reference client is directory-backed: cold-tier readers must go through
streamed `open_read`, exactly as they would against a remote store —
keeping the serving code honest about which tiers have fds to pin.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import BinaryIO, Iterator, Optional

_COPY_BLOCK = 1 << 20


class ObjectClient:
    """The S3-shaped client protocol ObjectBackend drives. Keys are
    opaque strings (the backend uses bare sha256 digests)."""

    def put_object_stream(self, key: str, fileobj: BinaryIO) -> int:
        """Store the stream under `key` atomically (visible all-or-
        nothing); returns bytes written."""
        raise NotImplementedError

    def get_object(self, key: str) -> BinaryIO:
        raise NotImplementedError

    def head_object(self, key: str) -> Optional[int]:
        raise NotImplementedError

    def delete_object(self, key: str) -> bool:
        raise NotImplementedError

    def list_objects(self) -> Iterator[tuple[str, int]]:
        raise NotImplementedError

    def tmp_dirs(self) -> tuple[str, ...]:
        return ()


class DirObjectClient(ObjectClient):
    """Directory-backed reference client: one flat namespace of keys
    under `root/`, PUTs staged in `root/.tmp` and renamed in — the
    atomic-PUT semantics of a real object store, on local disk."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self._tmp = os.path.join(self.root, ".tmp")
        os.makedirs(self.root, exist_ok=True)
        os.makedirs(self._tmp, exist_ok=True)

    def _key_path(self, key: str) -> str:
        safe = key.replace("/", "_")
        return os.path.join(self.root, safe)

    def put_object_stream(self, key: str, fileobj: BinaryIO) -> int:
        from . import crashpoint

        dest = self._key_path(key)
        tmp = os.path.join(
            self._tmp, f"{os.path.basename(dest)}."
                       f"{os.getpid()}.{threading.get_ident()}.part")
        nbytes = 0
        try:
            with open(tmp, "wb") as out:
                while True:
                    block = fileobj.read(_COPY_BLOCK)
                    if not block:
                        break
                    nbytes += len(block)
                    out.write(block)
                out.flush()
                os.fsync(out.fileno())
            crashpoint("pre_commit")
            os.replace(tmp, dest)
        except BaseException:
            if os.path.isfile(tmp):
                os.unlink(tmp)
            raise
        return nbytes

    def get_object(self, key: str) -> BinaryIO:
        return open(self._key_path(key), "rb")

    def head_object(self, key: str) -> Optional[int]:
        try:
            return os.stat(self._key_path(key)).st_size
        except OSError:
            return None

    def delete_object(self, key: str) -> bool:
        try:
            os.unlink(self._key_path(key))
            return True
        except OSError:
            return False

    def list_objects(self) -> Iterator[tuple[str, int]]:
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return
        for name in names:
            if name == ".tmp":
                continue
            try:
                yield name, os.stat(os.path.join(self.root, name)).st_size
            except OSError:
                continue

    def tmp_dirs(self) -> tuple[str, ...]:
        return (self._tmp,)


class _HashingReader:
    """Wraps a stream so the digest accumulates as the client consumes
    it — integrity verification rides the single copy the upload makes
    instead of a second full read."""

    def __init__(self, fileobj: BinaryIO) -> None:
        self._f = fileobj
        self.hasher = hashlib.sha256()

    def read(self, n: int = -1) -> bytes:
        block = self._f.read(n)
        if block:
            self.hasher.update(block)
        return block


class ObjectBackend:
    """Cold tier over an ObjectClient; keys are bare sha256 digests."""

    kind = "object"

    def __init__(self, client: ObjectClient) -> None:
        self.client = client

    def put(self, src_path: str, sha256: str) -> None:
        with open(src_path, "rb") as f:
            self.put_stream(f, sha256)

    def put_stream(self, fileobj: BinaryIO, sha256: str) -> int:
        from . import BackendIntegrityError

        if self.client.head_object(sha256) is not None:
            size = self.client.head_object(sha256)
            return int(size or 0)
        reader = _HashingReader(fileobj)
        nbytes = self.client.put_object_stream(sha256, reader)
        if reader.hasher.hexdigest() != sha256:
            # the PUT already landed; take it back out — a wrong-keyed
            # object must never become readable
            self.client.delete_object(sha256)
            raise BackendIntegrityError(
                f"object {sha256[:12]}: streamed digest "
                f"{reader.hasher.hexdigest()[:12]} does not match its key"
            )
        return nbytes

    def open_read(self, sha256: str) -> BinaryIO:
        return self.client.get_object(sha256)

    def head(self, sha256: str) -> Optional[int]:
        return self.client.head_object(sha256)

    def delete(self, sha256: str) -> bool:
        return self.client.delete_object(sha256)

    def list(self) -> Iterator[tuple[str, int]]:
        return self.client.list_objects()

    def local_path(self, sha256: str) -> Optional[str]:
        return None

    def tmp_dirs(self) -> tuple[str, ...]:
        return self.client.tmp_dirs()
