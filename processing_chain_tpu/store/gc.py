"""Mark-and-sweep garbage collection for the artifact store.

Roots (mark phase): every readable manifest, because a manifest IS the
liveness record of a cached plan — plus the pins file, which exempts its
manifests from LRU eviction entirely. Ref-counting is implicit: an object
is live while any surviving manifest (artifact or sidecar) names its
digest.

Sweep phases, in order:
  1. stale tmp/ entries older than `tmp_max_age_s` (crashed writers) —
     swept in EVERY tier's scratch dir, not just the hot root's;
  2. orphan objects no manifest references (older than `min_object_age_s`,
     so an in-flight commit's just-renamed object is never raced) —
     swept per tier, with the tier named in the evidence;
  3. demotion to per-tier budgets (docs/STORE.md "Tier hierarchy"):
     every tier except the last that has outgrown its OWN byte budget
     demotes its coldest objects one rung down — coldest by the heat
     ledger's recorded reads, then by LRU manifest stamp, pinned plans
     last. Demotion moves bytes, it never destroys them: this is the
     "demote before evict" half of the placement policy;
  4. LRU eviction of unpinned manifests, oldest last-used first, until
     referenced bytes fit `size_budget_bytes` (the TOTAL budget, across
     all tiers) — each eviction re-runs the implicit ref-count so
     objects shared with a surviving manifest stay. Because demotion
     ran first, eviction is in practice eviction out of the LAST tier;
     the evidence names the tier each victim's bytes actually left.

Every eviction counts `chain_store_evictions_total`; a `dry_run` pass
reports what would happen without touching disk.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from .. import telemetry as tm
from ..utils.log import get_logger
from .backends import BackendIntegrityError
from .store import STORE_EVICTIONS, ArtifactStore, Manifest


def _manifest_digests(manifest: Manifest) -> set[str]:
    return {d["sha256"] for d in manifest.all_digests()}


def collect(
    store: ArtifactStore,
    size_budget_bytes: Optional[int] = None,
    dry_run: bool = False,
    tmp_max_age_s: float = 3600.0,
    min_object_age_s: float = 3600.0,
    now: Optional[float] = None,
    extra_pins: Optional[set] = None,
    heat=None,
) -> dict:
    """Run one mark-and-sweep pass; returns the summary dict that
    `tools store gc` renders and serve's pressure hook consumes.

    `extra_pins` are EPHEMERAL pins: plan hashes exempt from LRU
    eviction for this pass only, without touching pins.json — the serve
    daemon passes the plans referenced by unfinished requests so the
    cache can never evict an artifact a queued request is about to
    claim. Summary keys beyond the per-phase detail: `bytes_freed`
    (orphans + evictions), `objects_evicted` (object files actually
    unlinked), `pins_honored` (manifests the LRU pass skipped because
    durable or ephemeral pins protect them).

    `heat` (a store.heat.HeatLedger) turns on eviction forensics: every
    victim's evidence dict — the same one `report["victims"]` carries,
    the `store_evict` event ships, and `tools store gc` prints — is
    journaled so a later read/rebuild of the plan can be recognized as
    eviction regret (docs/STORE.md "Access heat & eviction
    forensics")."""
    log = get_logger()
    now = time.time() if now is None else now
    report = {
        "dry_run": dry_run,
        "tmp_removed": 0,
        "orphans_removed": 0,
        "orphan_bytes": 0,
        "evicted_manifests": [],
        #: per-victim evidence dicts (LRU victims AND orphans), the
        #: forensics record: reason, last-used age, recorded reads,
        #: freed bytes, and the budget that triggered the pass
        "victims": [],
        #: per-move evidence dicts from the per-tier-budget demotion
        #: phase (same shape the `store_demote` event ships)
        "demotions": [],
        "demoted_bytes": 0,
        "evicted_bytes": 0,
        "kept_manifests": 0,
        "kept_bytes": 0,
        "bytes_freed": 0,
        "objects_evicted": 0,
        "pins_honored": 0,
    }

    # phase 1: crashed-writer leftovers in EVERY tier's scratch dir —
    # the hot root's tmp/ plus whatever scratch each colder backend
    # stages its commits in
    tmp_dirs: list[str] = []
    for t in store.tiers.tiers:
        for d in t.backend.tmp_dirs():
            if d not in tmp_dirs:
                tmp_dirs.append(d)
    for tmp_dir in tmp_dirs:
        try:
            for name in os.listdir(tmp_dir):
                path = os.path.join(tmp_dir, name)
                try:
                    if now - os.stat(path).st_mtime < tmp_max_age_s:
                        continue
                    if not dry_run:
                        os.unlink(path)
                    report["tmp_removed"] += 1
                except OSError:
                    continue
        except OSError:
            continue

    # mark: manifests (with their LRU stamp) and the digests they hold live
    pins = set(store.pins()) | set(extra_pins or ())
    manifests: list[tuple[float, Manifest]] = []
    for m in store.iter_manifests():
        try:
            mtime = os.stat(store.manifest_path(m.plan_hash)).st_mtime
        except OSError:
            mtime = 0.0
        manifests.append((mtime, m))
    live: set[str] = set()
    for _, m in manifests:
        live.update(_manifest_digests(m))

    # phase 2: orphan objects, swept per tier so a crashed move's
    # leftover copy is collected wherever it sits. The accounting view
    # (`sizes`, and which tier a live object's bytes count against) is
    # the hottest copy, matching store.iter_objects().
    sizes: dict[str, int] = {}
    object_tier: dict[str, str] = {}
    for sha, size, tname in store.tiers.iter_objects():
        sizes[sha] = size
        object_tier[sha] = tname
    for t in store.tiers.tiers:
        for sha, size in t.backend.list():
            if sha in live:
                continue
            path = t.backend.local_path(sha)
            try:
                if path is not None:
                    age_s = now - os.stat(path).st_mtime
                else:
                    # no stat surface (object tier). Cold tiers only
                    # ever receive MOVES of manifest-referenced objects
                    # — never fresh ingests racing their manifest write
                    # — so the min-age guard has nothing to protect
                    age_s = float("inf")
                if age_s < min_object_age_s:
                    continue
                if not dry_run:
                    if not t.backend.delete(sha):
                        continue
                report["orphans_removed"] += 1
                report["orphan_bytes"] += size
                evidence = {
                    "object": sha,
                    "reason": "orphan",
                    "tier": t.name,
                    "age_s": round(min(max(0.0, age_s), 1e12), 3),
                    "freed_bytes": size,
                }
                report["victims"].append(evidence)
                if heat is not None and not dry_run:
                    heat.record_eviction(evidence)
            except OSError:
                continue

    # the heat ledger's recorded reads, fetched ONCE per pass (it
    # merges every replica's journal) — ranks demotion candidates and
    # fills the "what did this plan's history look like" half of the
    # eviction evidence
    recorded_reads = heat.read_counts() if heat is not None else {}

    # phase 3: demotion to per-tier budgets — demote before evict.
    # Coldness ranking: unpinned before pinned, fewest recorded reads
    # first, then oldest newest-owning-manifest LRU stamp first.
    if store.tiers.multi:
        owners: dict[str, tuple[float, str]] = {}
        for mtime, m in manifests:
            for sha in _manifest_digests(m):
                prev = owners.get(sha)
                if prev is None or mtime > prev[0]:
                    owners[sha] = (mtime, m.plan_hash)
        tier_list = store.tiers.tiers
        for i, tier in enumerate(tier_list[:-1]):
            if tier.budget_bytes is None:
                continue
            held = list(tier.backend.list())
            total = sum(size for _, size in held)
            if total <= tier.budget_bytes:
                continue
            dst = tier_list[i + 1]

            def coldness(entry: tuple[str, int]) -> tuple:
                mtime, plan = owners.get(entry[0], (0.0, None))
                reads = recorded_reads.get(plan, 0) if plan else 0
                return (plan in pins, reads, mtime)

            held.sort(key=coldness)
            for sha, size in held:
                if total <= tier.budget_bytes:
                    break
                if sha not in live:
                    continue  # orphan copies are phase 2's job
                mtime, plan = owners.get(sha, (0.0, None))
                if dry_run:
                    evidence = {"object": sha, "op": "demote",
                                "from_tier": tier.name,
                                "to_tier": dst.name, "bytes": size}
                    if plan is not None:
                        evidence["plan"] = plan
                else:
                    try:
                        evidence = store.tiers.demote(
                            sha, tier, dst, plan=plan, heat=heat)
                    except (OSError, BackendIntegrityError) as exc:
                        log.warning(
                            "store gc: demoting %s %s→%s failed: %s",
                            sha[:12], tier.name, dst.name, exc)
                        continue
                evidence["reads"] = (
                    recorded_reads.get(plan, 0) if plan else 0)
                evidence["last_used_age_s"] = round(
                    max(0.0, now - mtime), 3)
                total -= size
                report["demotions"].append(evidence)
                report["demoted_bytes"] += size

    # phase 4: LRU eviction to the size budget (pinned manifests exempt)
    def referenced_bytes(ms: list[tuple[float, Manifest]]) -> int:
        refs: set[str] = set()
        for _, m in ms:
            refs.update(_manifest_digests(m))
        return sum(sizes.get(sha, 0) for sha in refs)

    if size_budget_bytes is not None:
        manifests.sort(key=lambda e: e[0])  # oldest last-used first
        report["pins_honored"] = sum(
            1 for _, m in manifests if m.plan_hash in pins
        )
        while manifests and referenced_bytes(manifests) > size_budget_bytes:
            victim_i = next(
                (i for i, (_, m) in enumerate(manifests)
                 if m.plan_hash not in pins),
                None,
            )
            if victim_i is None:
                log.warning(
                    "store gc: size budget %d unreachable — every remaining "
                    "manifest is pinned", size_budget_bytes,
                )
                break
            victim_mtime, victim = manifests.pop(victim_i)
            survivors: set[str] = set()
            for _, m in manifests:
                survivors.update(_manifest_digests(m))
            doomed = _manifest_digests(victim) - survivors
            freed = sum(sizes.get(sha, 0) for sha in doomed)
            # the tier the victim's bytes actually left: the one
            # holding the most doomed bytes (after demotion ran, that
            # is in practice the LAST tier)
            tier_bytes: dict[str, int] = {}
            for sha in doomed:
                tname = object_tier.get(sha)
                if tname is not None:
                    tier_bytes[tname] = (
                        tier_bytes.get(tname, 0) + sizes.get(sha, 0))
            left_tier = (
                max(tier_bytes, key=tier_bytes.get) if tier_bytes
                else store.tiers.tiers[-1].name)
            evidence = {
                "plan": victim.plan_hash,
                "producer": victim.producer,
                "reason": "over_budget",
                "tier": left_tier,
                "last_used_age_s": round(max(0.0, now - victim_mtime), 3),
                "reads": recorded_reads.get(victim.plan_hash, 0),
                "freed_bytes": freed,
                "objects": len(doomed),
                "budget_bytes": size_budget_bytes,
            }
            if not dry_run:
                store._drop_manifest(victim.plan_hash)
                for sha in doomed:
                    store.tiers.delete_everywhere(sha)
                STORE_EVICTIONS.inc()
                # the event carries the full evidence, not aggregates:
                # the operator render, the forensics journal, and this
                # event stay in agreement because all three ship the
                # same dict
                tm.emit("store_evict", **evidence)
                if heat is not None:
                    heat.record_eviction(evidence)
            report["evicted_manifests"].append(victim.plan_hash)
            report["victims"].append(evidence)
            report["evicted_bytes"] += freed
            report["objects_evicted"] += len(doomed)

    report["kept_manifests"] = len(manifests)
    report["kept_bytes"] = referenced_bytes(manifests)
    report["objects_evicted"] += report["orphans_removed"]
    report["bytes_freed"] = report["orphan_bytes"] + report["evicted_bytes"]
    if not dry_run:
        store.update_gauges(full=True)
    return report


def enforce_budget(
    store: ArtifactStore,
    size_budget_bytes: Optional[int],
    extra_pins: Optional[set] = None,
    dry_run: bool = False,
    heat=None,
) -> dict:
    """The LRU size-budget path as a programmatic API: one collect()
    pass tuned for a LONG-RUNNING caller (serve's pressure hook) — tmp
    and orphan sweeps keep their crash-safety ages, eviction honors both
    durable pins and the caller's ephemeral `extra_pins`. Returns the
    same summary dict as collect(); `tools store gc` and the serve
    pressure hook therefore share one implementation and one report
    vocabulary (bytes_freed / objects_evicted / pins_honored)."""
    return collect(
        store,
        size_budget_bytes=size_budget_bytes,
        dry_run=dry_run,
        extra_pins=extra_pins,
        heat=heat,
    )
