"""Mark-and-sweep garbage collection for the artifact store.

Roots (mark phase): every readable manifest, because a manifest IS the
liveness record of a cached plan — plus the pins file, which exempts its
manifests from LRU eviction entirely. Ref-counting is implicit: an object
is live while any surviving manifest (artifact or sidecar) names its
digest.

Sweep phases, in order:
  1. stale tmp/ entries older than `tmp_max_age_s` (crashed writers);
  2. orphan objects no manifest references (older than `min_object_age_s`,
     so an in-flight commit's just-renamed object is never raced);
  3. LRU eviction of unpinned manifests, oldest last-used first, until
     referenced bytes fit `size_budget_bytes` — each eviction re-runs the
     implicit ref-count so objects shared with a surviving manifest stay.

Every eviction counts `chain_store_evictions_total`; a `dry_run` pass
reports what would happen without touching disk.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from .. import telemetry as tm
from ..utils.log import get_logger
from .store import STORE_EVICTIONS, ArtifactStore, Manifest


def _manifest_digests(manifest: Manifest) -> set[str]:
    return {d["sha256"] for d in manifest.all_digests()}


def collect(
    store: ArtifactStore,
    size_budget_bytes: Optional[int] = None,
    dry_run: bool = False,
    tmp_max_age_s: float = 3600.0,
    min_object_age_s: float = 3600.0,
    now: Optional[float] = None,
    extra_pins: Optional[set] = None,
    heat=None,
) -> dict:
    """Run one mark-and-sweep pass; returns the summary dict that
    `tools store gc` renders and serve's pressure hook consumes.

    `extra_pins` are EPHEMERAL pins: plan hashes exempt from LRU
    eviction for this pass only, without touching pins.json — the serve
    daemon passes the plans referenced by unfinished requests so the
    cache can never evict an artifact a queued request is about to
    claim. Summary keys beyond the per-phase detail: `bytes_freed`
    (orphans + evictions), `objects_evicted` (object files actually
    unlinked), `pins_honored` (manifests the LRU pass skipped because
    durable or ephemeral pins protect them).

    `heat` (a store.heat.HeatLedger) turns on eviction forensics: every
    victim's evidence dict — the same one `report["victims"]` carries,
    the `store_evict` event ships, and `tools store gc` prints — is
    journaled so a later read/rebuild of the plan can be recognized as
    eviction regret (docs/STORE.md "Access heat & eviction
    forensics")."""
    log = get_logger()
    now = time.time() if now is None else now
    report = {
        "dry_run": dry_run,
        "tmp_removed": 0,
        "orphans_removed": 0,
        "orphan_bytes": 0,
        "evicted_manifests": [],
        #: per-victim evidence dicts (LRU victims AND orphans), the
        #: forensics record: reason, last-used age, recorded reads,
        #: freed bytes, and the budget that triggered the pass
        "victims": [],
        "evicted_bytes": 0,
        "kept_manifests": 0,
        "kept_bytes": 0,
        "bytes_freed": 0,
        "objects_evicted": 0,
        "pins_honored": 0,
    }

    # phase 1: crashed-writer leftovers in tmp/
    try:
        for name in os.listdir(store.tmp_dir):
            path = os.path.join(store.tmp_dir, name)
            try:
                if now - os.stat(path).st_mtime < tmp_max_age_s:
                    continue
                if not dry_run:
                    os.unlink(path)
                report["tmp_removed"] += 1
            except OSError:
                continue
    except OSError:
        pass

    # mark: manifests (with their LRU stamp) and the digests they hold live
    pins = set(store.pins()) | set(extra_pins or ())
    manifests: list[tuple[float, Manifest]] = []
    for m in store.iter_manifests():
        try:
            mtime = os.stat(store.manifest_path(m.plan_hash)).st_mtime
        except OSError:
            mtime = 0.0
        manifests.append((mtime, m))
    live: set[str] = set()
    for _, m in manifests:
        live.update(_manifest_digests(m))

    # phase 2: orphan objects
    sizes: dict[str, int] = {}
    for sha, size in store.iter_objects():
        sizes[sha] = size
        if sha in live:
            continue
        path = store.object_path(sha)
        try:
            age_s = now - os.stat(path).st_mtime
            if age_s < min_object_age_s:
                continue
            if not dry_run:
                os.unlink(path)
            report["orphans_removed"] += 1
            report["orphan_bytes"] += size
            evidence = {
                "object": sha,
                "reason": "orphan",
                "age_s": round(max(0.0, age_s), 3),
                "freed_bytes": size,
            }
            report["victims"].append(evidence)
            if heat is not None and not dry_run:
                heat.record_eviction(evidence)
        except OSError:
            continue

    # phase 3: LRU eviction to the size budget (pinned manifests exempt)
    def referenced_bytes(ms: list[tuple[float, Manifest]]) -> int:
        refs: set[str] = set()
        for _, m in ms:
            refs.update(_manifest_digests(m))
        return sum(sizes.get(sha, 0) for sha in refs)

    if size_budget_bytes is not None:
        manifests.sort(key=lambda e: e[0])  # oldest last-used first
        report["pins_honored"] = sum(
            1 for _, m in manifests if m.plan_hash in pins
        )
        # recorded read counts from the heat ledger, fetched ONCE per
        # pass (it merges every replica's journal) — the "what did this
        # plan's history look like" half of the eviction evidence
        recorded_reads = heat.read_counts() if heat is not None else {}
        while manifests and referenced_bytes(manifests) > size_budget_bytes:
            victim_i = next(
                (i for i, (_, m) in enumerate(manifests)
                 if m.plan_hash not in pins),
                None,
            )
            if victim_i is None:
                log.warning(
                    "store gc: size budget %d unreachable — every remaining "
                    "manifest is pinned", size_budget_bytes,
                )
                break
            victim_mtime, victim = manifests.pop(victim_i)
            survivors: set[str] = set()
            for _, m in manifests:
                survivors.update(_manifest_digests(m))
            doomed = _manifest_digests(victim) - survivors
            freed = sum(sizes.get(sha, 0) for sha in doomed)
            evidence = {
                "plan": victim.plan_hash,
                "producer": victim.producer,
                "reason": "over_budget",
                "last_used_age_s": round(max(0.0, now - victim_mtime), 3),
                "reads": recorded_reads.get(victim.plan_hash, 0),
                "freed_bytes": freed,
                "objects": len(doomed),
                "budget_bytes": size_budget_bytes,
            }
            if not dry_run:
                store._drop_manifest(victim.plan_hash)
                for sha in doomed:
                    try:
                        os.unlink(store.object_path(sha))
                    except OSError:
                        pass
                STORE_EVICTIONS.inc()
                # the event carries the full evidence, not aggregates:
                # the operator render, the forensics journal, and this
                # event stay in agreement because all three ship the
                # same dict
                tm.emit("store_evict", **evidence)
                if heat is not None:
                    heat.record_eviction(evidence)
            report["evicted_manifests"].append(victim.plan_hash)
            report["victims"].append(evidence)
            report["evicted_bytes"] += freed
            report["objects_evicted"] += len(doomed)

    report["kept_manifests"] = len(manifests)
    report["kept_bytes"] = referenced_bytes(manifests)
    report["objects_evicted"] += report["orphans_removed"]
    report["bytes_freed"] = report["orphan_bytes"] + report["evicted_bytes"]
    if not dry_run:
        store.update_gauges(full=True)
    return report


def enforce_budget(
    store: ArtifactStore,
    size_budget_bytes: int,
    extra_pins: Optional[set] = None,
    dry_run: bool = False,
    heat=None,
) -> dict:
    """The LRU size-budget path as a programmatic API: one collect()
    pass tuned for a LONG-RUNNING caller (serve's pressure hook) — tmp
    and orphan sweeps keep their crash-safety ages, eviction honors both
    durable pins and the caller's ephemeral `extra_pins`. Returns the
    same summary dict as collect(); `tools store gc` and the serve
    pressure hook therefore share one implementation and one report
    vocabulary (bytes_freed / objects_evicted / pins_honored)."""
    return collect(
        store,
        size_budget_bytes=size_budget_bytes,
        dry_run=dry_run,
        extra_pins=extra_pins,
        heat=heat,
    )
