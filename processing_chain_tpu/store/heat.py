"""The access-heat ledger: read-path flight recorder of the artifact store.

The artifact plane's read path (`GET /v1/artifacts`, docs/SERVE.md) was
write-side observable only: the store counts hits and GC counts
evictions, but nothing records WHO is read, how often, how many bytes,
or what evicting a plan cost — the exact signals the tiered/edge-cached
artifact plane of ROADMAP item 2 needs before any promotion/demotion
policy can be more than a guess. This module is that recorder:

  * **One journal file per replica** (`<store root>/heat/<replica>.jsonl`),
    modeled on serve/spans.py: appends are flushed-not-fsynced (a
    SIGKILLed process cannot take flushed bytes with it — they are the
    kernel's; power-loss durability is deliberately not paid on a
    per-read hot path), O_APPEND so a restart racing its predecessor's
    last flush never interleaves mid-line, and readers tolerate a torn
    final line. Journals merge fleet-wide by simple concatenation —
    per-replica files never contend across processes.
  * **Four record kinds** (the `kind` field):
      - `read`   — one artifact read: `plan`, `mode` (`full` — bytes
        streamed — `not_modified` — a conditional GET answered 304,
        an edge-class hit whose bytes the client's cache already holds —
        or `range` — a single byte range streamed as a 206), `bytes`
        actually served, the `tier` the bytes were found in when the
        store is tiered, the artifact `size` and `size_class`,
        `tenant`, and the measured `ttfb_s`/`dur_s` when the serve
        layer observed them.
      - `move`   — one tier placement move (store/tiers.py): `op`
        (`promote` | `demote`), `from_tier`, `to_tier`, `bytes`, and
        the owning `plan` when known. Written only AFTER the source
        copy is deleted, so a crashed move never journals and a
        retried one journals exactly once.
      - `evict`  — one GC eviction with its evidence (store/gc.py):
        `reason` (`over_budget` | `orphan`), `last_used_age_s`,
        recorded `reads`, `freed_bytes`, and the `budget_bytes`
        pressure trigger.
      - `regret` — a read or rebuild of a plan hash evicted within
        `regret_window_s`: the canonical cache-undersizing signal,
        counted as `chain_store_eviction_regret_total` (an adequately
        sized cache records zero; every regret is a rebuild or a 404
        the budget forced).

Readers (`read_journals`, `aggregate`, `working_set_curve`) serve the
`tools store-heat` report and the fleet merge (telemetry/fleet.py);
`journal_stats` is the tail-sampled cheap summary the few-seconds-
cadence `/fleet` view reads, mirroring serve/spans.journal_stats —
journals are append-only history and the hot path must not reparse an
unbounded file per refresh.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from .. import telemetry as tm
from ..utils import lockdebug
from ..utils.log import get_logger

READS = tm.counter(
    "chain_store_reads_total",
    "artifact reads recorded by the heat ledger, by mode "
    "(full = bytes streamed; not_modified = conditional GET hit; "
    "range = a single byte range streamed as a 206)",
    ("mode",),
)
READ_BYTES = tm.counter(
    "chain_store_read_bytes_total",
    "artifact bytes actually served to readers",
)
REGRET = tm.counter(
    "chain_store_eviction_regret_total",
    "reads or rebuilds of a recently-evicted plan hash — the "
    "cache-undersizing signal (docs/STORE.md)",
    ("via",),
)

#: an eviction is "recent" — and a later read/rebuild of its plan is
#: REGRET — for this long (seconds)
REGRET_WINDOW_S = 3600.0

_SAFE_NAME = re.compile(r"[^A-Za-z0-9._-]+")


def heat_dir(store_root: str) -> str:
    """The ledger directory of one store root."""
    return os.path.join(os.path.abspath(store_root), "heat")


def _journal_name(replica: str) -> str:
    return _SAFE_NAME.sub("_", replica) + ".jsonl"


class HeatLedger:
    """Append-only per-replica heat journal + the regret detector.

    Thread-safe: the HTTP read path, the submit path (rebuild regret)
    and the GC pass all record through one ledger. Appends are flushed
    per record and any disk failure degrades to a logged warning — the
    ledger is observability, it must never break the read path it
    observes."""

    def __init__(self, store_root: str, replica: str,
                 regret_window_s: float = REGRET_WINDOW_S) -> None:
        self.root = heat_dir(store_root)
        self.replica = replica
        self.path = os.path.join(self.root, _journal_name(replica))
        self.regret_window_s = float(regret_window_s)
        self._lock = lockdebug.make_lock("store_heat")
        self._f = None      # guarded-by: _lock
        self._seq = 0       # guarded-by: _lock
        #: plan -> (evict ts, evicting replica) within the regret window,
        #: fed by our own evictions and a throttled incremental scan of
        #: the peer journals (evictions elsewhere in the fleet must
        #: regret HERE when this replica serves the re-read)
        self._evicted: dict = {}       # guarded-by: _lock
        self._offsets: dict = {}       # guarded-by: _lock
        self._last_refresh = 0.0       # guarded-by: _lock
        self._refresh_interval_s = 1.0

    # ------------------------------------------------------------ writes

    def _seal_torn_tail(self) -> None:
        """A predecessor SIGKILLed mid-write leaves a torn final line.
        Readers skip it, but O_APPEND would glue THIS incarnation's
        first record onto it and lose both — terminate the torn line
        before appending so our records stay parseable."""
        try:
            with open(self.path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")
        except FileNotFoundError:
            return
        except OSError:
            pass  # the append itself will surface a real disk fault

    def _append(self, record: dict) -> None:
        """One journal record (spans.py discipline). Never raises."""
        record.setdefault("ts", round(time.time(), 6))
        record["replica"] = self.replica
        record["pid"] = os.getpid()
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            try:
                if self._f is None:
                    os.makedirs(self.root, exist_ok=True)
                    # append-only stream: torn tails are tolerated by
                    # readers, and O_APPEND keeps a restarted replica
                    # racing its predecessor's last flush from
                    # interleaving mid-line
                    self._seal_torn_tail()
                    self._f = open(self.path, "a")
                self._f.write(json.dumps(record, sort_keys=True) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                get_logger().warning(
                    "store heat: could not append %s record",
                    record.get("kind"), exc_info=True)
                try:
                    if self._f is not None:
                        self._f.close()
                except OSError:
                    pass
                self._f = None

    def record_read(self, plan: str, nbytes: int, mode: str = "full", *,
                    size: Optional[int] = None,
                    size_class: Optional[str] = None,
                    tenant: str = "",
                    tier: Optional[str] = None,
                    ttfb_s: Optional[float] = None,
                    dur_s: Optional[float] = None) -> None:
        """One artifact read (full stream, single-range 206, or
        conditional-GET 304). `tier` is the store tier the read found
        the bytes in (docs/STORE.md "Tier hierarchy")."""
        READS.labels(mode=mode).inc()
        if nbytes:
            READ_BYTES.inc(int(nbytes))
        record = {
            "kind": "read",
            "plan": plan,
            "mode": mode,
            "bytes": int(nbytes),
            "tenant": tenant,
        }
        if tier is not None:
            record["tier"] = tier
        if size is not None:
            record["size"] = int(size)
        if size_class is not None:
            record["size_class"] = size_class
        if ttfb_s is not None:
            record["ttfb_s"] = round(ttfb_s, 6)
        if dur_s is not None:
            record["dur_s"] = round(dur_s, 6)
        self._append(record)

    def record_move(self, evidence: dict) -> None:
        """One tier placement move, with the evidence store/tiers.py
        assembled (shared shape with the `store_promote`/`store_demote`
        events). Called AFTER the source delete — see the crash-safety
        ordering note in the module docstring."""
        self._append({"kind": "move", **evidence})

    def record_eviction(self, evidence: dict) -> None:
        """One GC eviction, with the per-victim evidence store/gc.py
        assembled (shared shape with the `store_evict` event and the
        `tools store gc` render)."""
        record = {"kind": "evict", **evidence}
        plan = evidence.get("plan")
        if plan:
            with self._lock:
                self._evicted[plan] = (time.time(), self.replica)
        self._append(record)

    def note_read_or_rebuild(self, plan: str,
                             via: str = "read") -> Optional[dict]:
        """Regret check: if `plan` was evicted within the regret window
        (by ANY replica — peers' journals are consulted), count one
        eviction regret and journal it. Returns the regret record, or
        None when the miss is not regretful (never built, or evicted
        long ago)."""
        now = time.time()
        with self._lock:
            self._refresh_locked(now)
            entry = self._evicted.get(plan)
            if entry is None:
                return None
            evicted_ts, evicted_by = entry
            if now - evicted_ts > self.regret_window_s:
                self._evicted.pop(plan, None)
                return None
        REGRET.labels(via=via).inc()
        record = {
            "kind": "regret",
            "plan": plan,
            "via": via,
            "evicted_ago_s": round(max(0.0, now - evicted_ts), 3),
            "evicted_by": evicted_by,
        }
        tm.emit("store_regret", plan=plan, via=via,
                evicted_ago_s=record["evicted_ago_s"],
                evicted_by=evicted_by)
        self._append(record)
        return record

    # holds-lock: _lock
    def _refresh_locked(self, now: float) -> None:
        """Throttled incremental scan of every replica's journal for
        evict records. Offsets only ever advance to the end of the last
        COMPLETE line, so a torn tail is re-read whole once its newline
        lands."""
        if now - self._last_refresh < self._refresh_interval_s:
            return
        self._last_refresh = now
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return
        for name in names:
            if not name.endswith(".jsonl"):
                continue
            path = os.path.join(self.root, name)
            offset = self._offsets.get(name, 0)
            try:
                with open(path) as f:
                    f.seek(offset)
                    chunk = f.read()
            except OSError:
                continue
            end = chunk.rfind("\n")
            if end < 0:
                continue
            self._offsets[name] = offset + end + 1
            for line in chunk[:end].splitlines():
                try:
                    record = json.loads(line)
                except ValueError:
                    continue
                if (isinstance(record, dict)
                        and record.get("kind") == "evict"
                        and record.get("plan")):
                    self._evicted[record["plan"]] = (
                        record.get("ts", 0.0),
                        record.get("replica", "?"),
                    )
        cutoff = now - self.regret_window_s
        for plan in [p for p, (ts, _) in self._evicted.items()
                     if ts < cutoff]:
            self._evicted.pop(plan, None)

    def read_counts(self) -> dict:
        """plan -> recorded read count, merged over every replica's
        journal — the GC evidence's `reads` field (store/gc.py)."""
        counts: dict = {}
        for record in read_journals(self.root):
            if record.get("kind") == "read" and record.get("plan"):
                counts[record["plan"]] = counts.get(record["plan"], 0) + 1
        return counts

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass


# ---------------------------------------------------------------- readers


def read_journal(path: str) -> list[dict]:
    """One journal file; tolerates torn lines (the one write a crash
    can interrupt — same discipline serve/spans.py pins). A torn line
    is usually the tail, but a restarted replica seals its
    predecessor's torn tail with a newline and appends after it, so a
    long-lived journal can carry one mid-file; either way every
    complete record stands."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue  # torn line: every complete record stands
                if isinstance(record, dict):
                    out.append(record)
    except OSError:
        return []
    return out


def read_journals(root: str) -> list[dict]:
    """Every replica's heat journal under `root`, merged and ordered by
    (ts, replica, seq)."""
    records: list[dict] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        if name.endswith(".jsonl"):
            records.extend(read_journal(os.path.join(root, name)))
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("replica", ""),
                                r.get("seq", 0)))
    return records


def aggregate(root: str) -> dict:
    """The full-history ledger rollup the heat report renders:
    per-plan read/bytes/last-access accounting, per-replica sums (the
    fleet-merge identity check: merged totals MUST equal the by-replica
    sums — both come from the same records), and fleet totals including
    regrets and evictions."""
    per_plan: dict = {}
    by_replica: dict = {}
    by_tier: dict = {}
    totals = {"reads": 0, "full": 0, "not_modified": 0, "range": 0,
              "bytes": 0, "regrets": 0, "evictions": 0,
              "promotions": 0, "demotions": 0}
    for record in read_journals(root):
        kind = record.get("kind")
        if kind == "read":
            plan = record.get("plan") or "?"
            entry = per_plan.setdefault(plan, {
                "reads": 0, "full": 0, "not_modified": 0, "range": 0,
                "bytes": 0, "last_ts": 0.0, "size": 0, "tiers": {},
            })
            mode = record.get("mode")
            if mode not in ("full", "not_modified", "range"):
                mode = "full"
            nbytes = int(record.get("bytes") or 0)
            entry["reads"] += 1
            entry[mode] += 1
            entry["bytes"] += nbytes
            entry["last_ts"] = max(entry["last_ts"],
                                   record.get("ts", 0.0))
            if record.get("size"):
                entry["size"] = max(entry["size"], int(record["size"]))
            tier = record.get("tier")
            if tier:
                entry["tiers"][tier] = entry["tiers"].get(tier, 0) + 1
                t = by_tier.setdefault(tier, {"reads": 0, "bytes": 0})
                t["reads"] += 1
                t["bytes"] += nbytes
            rep = by_replica.setdefault(record.get("replica", "?"),
                                        {"reads": 0, "bytes": 0})
            rep["reads"] += 1
            rep["bytes"] += nbytes
            totals["reads"] += 1
            totals[mode] += 1
            totals["bytes"] += nbytes
        elif kind == "move":
            if record.get("op") == "promote":
                totals["promotions"] += 1
            else:
                totals["demotions"] += 1
        elif kind == "evict":
            totals["evictions"] += 1
        elif kind == "regret":
            totals["regrets"] += 1
    return {"per_plan": per_plan, "by_replica": by_replica,
            "by_tier": by_tier, "totals": totals}


def plan_size(entry: dict) -> int:
    """Best artifact-size estimate for one per-plan aggregate entry:
    the recorded manifest size, else bytes-per-full-read."""
    if entry.get("size"):
        return int(entry["size"])
    if entry.get("full"):
        return int(entry["bytes"] / max(1, entry["full"]))
    return 0


def working_set_curve(per_plan: dict) -> list[dict]:
    """The hot-set curve, hottest plan first: after the k hottest
    plans, what fraction of the stored bytes serves what fraction of
    the reads ("X% of bytes serve Y% of reads"). One point per plan;
    the report downsamples for display."""
    entries = sorted(per_plan.values(), key=lambda e: -e["reads"])
    total_reads = sum(e["reads"] for e in entries)
    total_bytes = sum(plan_size(e) for e in entries)
    curve: list[dict] = []
    cum_reads = 0
    cum_bytes = 0
    for i, entry in enumerate(entries):
        cum_reads += entry["reads"]
        cum_bytes += plan_size(entry)
        curve.append({
            "plans": i + 1,
            "reads_frac": round(cum_reads / total_reads, 4)
            if total_reads else 0.0,
            "bytes_frac": round(cum_bytes / total_bytes, 4)
            if total_bytes else 0.0,
        })
    return curve


def journal_stats(root: str, tail_bytes: int = 1 << 19) -> dict:
    """Cheap fleet-view summary (serve/spans.journal_stats's sibling):
    total size from stat, per-kind/mode counts parsed from each
    journal's TAIL. `sampled: true` flags that some journal exceeded
    the tail window — the counts then cover the recent window, not all
    time (no silent cap)."""
    stats = {"files": 0, "bytes": 0, "total": 0, "reads": 0, "full": 0,
             "not_modified": 0, "range": 0, "bytes_served": 0,
             "evictions": 0, "regrets": 0, "moves": 0, "sampled": False}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return stats
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(root, name)
        try:
            size = os.stat(path).st_size
            with open(path) as f:
                if size > tail_bytes:
                    stats["sampled"] = True
                    f.seek(size - tail_bytes)
                    f.readline()  # discard the mid-record partial
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        record = json.loads(line)
                    except ValueError:
                        continue  # torn tail (or mid-window garbage)
                    stats["total"] += 1
                    kind = record.get("kind")
                    if kind == "read":
                        stats["reads"] += 1
                        mode = record.get("mode")
                        if mode in ("full", "not_modified", "range"):
                            stats[mode] += 1
                        stats["bytes_served"] += \
                            int(record.get("bytes") or 0)
                    elif kind == "move":
                        stats["moves"] += 1
                    elif kind == "evict":
                        stats["evictions"] += 1
                    elif kind == "regret":
                        stats["regrets"] += 1
        except OSError:
            continue
        stats["files"] += 1
        stats["bytes"] += size
    return stats
