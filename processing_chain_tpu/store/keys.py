"""Canonical plan hashing: the cache key schema of the artifact store.

A *plan* is a plain JSON-able dict describing everything that determines
an artifact's content: which input files (by content digest), which
resolved parameters (codec, rate control, canvas geometry, event lists),
and which producer version. Two runs that would produce the same artifact
hash to the same key; any semantic change — one flipped HRC parameter,
one re-encoded input segment — changes the key and invalidates exactly
the artifacts downstream of it.

Input files appear in plans as `file_ref(path)` markers so the model
layer never hashes anything itself; `resolve_plan` replaces each marker
with the file's content digest (sha256 + size) using a stat-keyed digest
cache, so a warm run pays one stat() per input instead of re-hashing
multi-GB SRC files.

Keys are versioned twice over: KEY_SCHEMA_VERSION (the shape of this
module's output — bump on any change to canonicalization or the resolved
marker format) and the chain version (tool provenance: artifacts built by
a different chain build are not trusted as equal). Both are folded into
every hash by `plan_hash`.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
from typing import Any, Callable, Optional
from ..utils import lockdebug

#: bump when canonical_json / resolve_plan output shape changes
KEY_SCHEMA_VERSION = 1

#: digest read granularity (also the "head" spot-check window)
_BLOCK = 1 << 20

_FILE_MARKER = "__file__"


class PlanError(ValueError):
    """A plan payload cannot be canonicalized (unhashable value types)."""


def file_ref(path: str) -> dict:
    """Marker for an input file in a plan payload; resolved to a content
    digest by `resolve_plan` at hash time."""
    return {_FILE_MARKER: os.path.abspath(os.fspath(path))}


@functools.lru_cache(maxsize=1)
def chain_version() -> str:
    from ..utils.version import get_processing_chain_version

    return get_processing_chain_version()


def _canonical(value: Any) -> Any:
    """Normalize a payload value into the canonical JSON-able subset:
    dicts (string keys, sorted at dump time), lists (tuples collapse into
    them), bools/ints/floats/strings/None. Anything else is a schema bug
    and raises instead of hashing repr() noise."""
    if isinstance(value, dict):
        out = {}
        for k, v in value.items():
            if not isinstance(k, str):
                raise PlanError(f"plan keys must be strings, got {k!r}")
            out[k] = _canonical(v)
        return out
    if isinstance(value, (list, tuple)):
        return [_canonical(v) for v in value]
    if isinstance(value, float):
        # integral floats and ints must collide (YAML parses `24` and
        # `24.0` interchangeably across databases)
        return int(value) if value.is_integer() else value
    if value is None or isinstance(value, (bool, int, str)):
        return value
    raise PlanError(f"unhashable plan value {value!r} ({type(value).__name__})")


def canonical_json(payload: Any) -> str:
    """Deterministic serialization: sorted keys, no whitespace, normalized
    numbers. The hash input format — stable across processes and dict
    insertion orders."""
    return json.dumps(
        _canonical(payload), sort_keys=True, separators=(",", ":"),
        ensure_ascii=True, allow_nan=False,
    )


def sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def hash_file(path: str) -> dict:
    """Full + head content digest of a file: {"sha256", "head_sha256",
    "size"}. The head digest (first _BLOCK bytes) is the cheap spot-check
    window for verified reads of large artifacts."""
    full = hashlib.sha256()
    head = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        first = True
        for block in iter(lambda: f.read(_BLOCK), b""):
            if first:
                head.update(block[:_BLOCK])
                first = False
            full.update(block)
            size += len(block)
    return {"sha256": full.hexdigest(), "head_sha256": head.hexdigest(),
            "size": size}


def _stat_key(path: str, st: os.stat_result) -> str:
    return f"{path}|{st.st_size}|{st.st_mtime_ns}"


class DigestCache:
    """Content digests keyed by (path, size, mtime_ns), optionally
    persisted as JSON inside the store root. A file whose stat signature
    is unchanged serves its digest without re-reading; a rewrite that
    preserves both size and mtime_ns is indistinguishable by design (the
    same trust model as make/ninja/bazel local caches). Thread-safe:
    commit-time hash re-resolution runs on JobRunner worker threads, and
    `atomic_write`'s tmp name is pid-unique, not thread-unique, so an
    unlocked save() from two workers could persist a truncated file."""

    def __init__(self, path: Optional[str] = None) -> None:
        self._path = path
        self._entries: dict[str, dict] = {}
        self._dirty = 0
        self._lock = lockdebug.make_lock("digest_cache")
        if path and os.path.isfile(path):
            try:
                with open(path) as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    self._entries = loaded
            except (OSError, ValueError):
                self._entries = {}

    def digest(self, path: str) -> dict:
        """{"sha256", "head_sha256", "size"} for `path` (raises OSError
        when unreadable)."""
        path = os.path.abspath(path)
        key = _stat_key(path, os.stat(path))
        with self._lock:
            hit = self._entries.get(key)
        if hit is not None:
            return hit
        entry = hash_file(path)  # outside the lock: hashing can be slow
        with self._lock:
            self._entries[key] = entry
            self._dirty += 1
        return entry

    def save(self) -> None:
        from ..utils.fsio import atomic_write

        with self._lock:
            if not self._path or not self._dirty:
                return
            # prune entries whose stat signature no longer matches disk:
            # every input rewrite adds a fresh key, so without this the
            # persisted cache would grow by one dead entry per rewrite
            # of every SRC/intermediate, forever (one stat per entry,
            # once per run end)
            live = {}
            for key, entry in self._entries.items():
                path = key.rsplit("|", 2)[0]
                try:
                    if _stat_key(path, os.stat(path)) == key:
                        live[key] = entry
                except OSError:
                    continue  # deleted input: drop its entries
            self._entries = live

            def _write(tmp: str) -> None:
                with open(tmp, "w") as f:
                    json.dump(live, f)

            try:
                atomic_write(self._path, _write)
                self._dirty = 0
            except OSError:
                pass  # cache persistence is best-effort by contract


def resolve_plan(payload: Any, digest: Callable[[str], dict]) -> Any:
    """Deep-copy `payload` with every file_ref marker replaced by
    {"file": basename, "sha256": ..., "size": ...}. `digest` is
    DigestCache.digest or equivalent. Raises OSError when a referenced
    input does not exist — callers decide whether that degrades to the
    legacy exists-check or aborts."""
    if isinstance(payload, dict):
        if set(payload) == {_FILE_MARKER}:
            path = payload[_FILE_MARKER]
            d = digest(path)
            # basename, not the absolute path: the same database rendered
            # under two mount points must produce equal keys
            return {"file": os.path.basename(path), "sha256": d["sha256"],
                    "size": d["size"]}
        return {k: resolve_plan(v, digest) for k, v in payload.items()}
    if isinstance(payload, (list, tuple)):
        return [resolve_plan(v, digest) for v in payload]
    return payload


def plan_hash(payload: dict, digest: Optional[Callable[[str], dict]] = None) -> str:
    """The cache key: sha256 over the canonical serialization of the
    resolved payload, folded with the key schema + chain version."""
    resolved = resolve_plan(payload, digest) if digest is not None else payload
    envelope = {
        "schema": KEY_SCHEMA_VERSION,
        "chain": chain_version(),
        "plan": resolved,
    }
    return sha256_hex(canonical_json(envelope).encode())
