"""The cache-key INPUT schema: every hidden input, declared once.

Plan-hash identity is the load-bearing wall of the system: the
content-addressed store (docs/STORE.md) serves bytes by plan hash and
chain-serve dedupes across tenants by it, so an input that influences
artifact bytes but escapes the plan is a silent cache-poisoning bug —
the same plan hash would name two different byte streams, and whichever
got committed first is served to every overlapping request.

This module is the single source of truth for which *environment*
inputs exist and how each one is accounted for. chainlint's
``plan-purity`` rule (tools/chainlint/planpurity.py) traces every
``os.environ`` / ``os.getenv`` / env-wrapper read through the call
graph and fails when a read that can reach artifact bytes is not
declared here; the ``PC_PLAN_DEBUG`` runtime recorder
(utils/plandebug.py) verifies the ``exempt`` claims dynamically by
failing the suite when one plan hash ever commits two different byte
streams.

Entry statuses:

  * ``plan``    — the input changes artifact bytes; its (effective) value
    must be folded into the plan payload. The checker verifies the read
    also reaches a plan-constructing function, so the declaration can't
    go stale: deleting the plan field re-opens the finding.
  * ``covered`` — byte-affecting, but folded into plans through a
    DERIVED value the static pass cannot link to the env read (name it
    in ``via``). The runtime recorder still guards the claim: if the
    derivation ever stops covering the input, same-plan/different-bytes
    fires.
  * ``exempt``  — the input provably never alters encoded bytes (thread
    counts, prefetch depths, chunk granularity). Every read site must
    carry a ``# plan-exempt: (reason)`` annotation, and the claim stays
    under the runtime recorder's same-plan/different-bytes gate.

Adding an env knob that can touch an output path = add the read site,
declare it here, and either fold it into the plan or annotate the read
``# plan-exempt`` — chainlint fails until all agree (the same
three-surface contract as telemetry/catalog.py).

The registry is consumed by AST (never imported) so the linter works on
any tree; keep every entry a literal.
"""

from __future__ import annotations

#: env input -> {"status": "plan"|"exempt", "reason": …[, "plan_key": …]}
ENV_INPUTS: dict[str, dict] = {
    # ---------------------------------------------------- byte-affecting
    "PC_AVPVS_CODEC": {
        "status": "plan",
        "plan_key": "codec",
        "reason": "selects the AVPVS intermediate codec (ffv1 vs "
                  "rawvideo): different container bytes by definition; "
                  "models/avpvs records the EFFECTIVE codec in every "
                  "avpvs plan",
    },
    "PC_FFV1_SLICES": {
        "status": "plan",
        "plan_key": "ffv1_slices",
        "reason": "slices change FFV1 bitstream structure, hence bytes; "
                  "the effective slice count is recorded in the avpvs "
                  "plan payloads (ffv1_effective_slices)",
    },
    "PC_RESIZE_METHOD": {
        "status": "plan",
        "plan_key": "resize",
        "reason": "banded/fused resize differs from the bit-exact gather "
                  "path by up to one code value per pixel — different "
                  "decoded frames, different bytes; plans record the "
                  "effective method (ops/resize.plan_resize_method)",
    },
    "JAX_PLATFORMS": {
        "status": "covered",
        "via": "resize",
        "reason": "backend selection changes the auto resize method "
                  "(TPU fused/banded vs CPU gather — up to one code "
                  "value per pixel); plans capture it through "
                  "ops/resize.plan_resize_method's 'auto:<backend>' "
                  "identity, derived from jax.default_backend() rather "
                  "than this env read",
    },
    # ------------------------------------------------ never alters bytes
    "PC_FUSE_P04": {
        "status": "exempt",
        "reason": "routing only: the fused p03+p04 fan-out (models/"
                  "fused) renders the stalling pass and every CPVS from "
                  "the in-memory quantized frames a decode of the "
                  "artifact would return (lossless intermediates), "
                  "through the SAME transform/compositor/writer code as "
                  "the staged path — decoded-identical bytes under "
                  "unchanged plan hashes, pinned by tests/test_fused.py "
                  "and the fused-smoke CI parity gate",
    },
    "PC_FFV1_THREADS": {
        "status": "exempt",
        "reason": "slice-threading width parallelizes the encode of the "
                  "slice layout the plan already records (ffv1_slices "
                  "captures its effect on the default slice count); the "
                  "thread count itself does not alter encoded bytes",
    },
    "PC_FFV1_WORKERS": {
        "status": "exempt",
        "reason": "frame-parallel worker count schedules whole-frame "
                  "encodes across private contexts; the slices=0 regime "
                  "it selects is captured by the recorded ffv1_slices, "
                  "and worker count itself does not alter encoded bytes",
    },
    "PC_CHUNK_FRAMES": {
        "status": "exempt",
        "reason": "frames-per-device-batch granularity; the emitted "
                  "frame stream is identical at any chunking (pinned by "
                  "the batch-vs-single parity tests)",
    },
    "PC_DECODE_WORKERS": {
        "status": "exempt",
        "reason": "segment-decode prefetch width; MultiSegmentPrefetcher "
                  "preserves segment order, so the decoded stream is "
                  "identical at any width",
    },
    "PC_HOST_BATCH": {
        "status": "exempt",
        "reason": "batched host I/O is byte-identical to the per-frame "
                  "fallback (the host-path-smoke CI parity gate)",
    },
    "PC_PRIORS_CHUNK": {
        "status": "exempt",
        "reason": "frames-per-native-crossing granularity of the priors "
                  "extractor (priors/extract.py); the per-frame record "
                  "stream — and therefore the deterministic sidecar bytes "
                  "— is identical at any chunking (pinned by the "
                  "chunking-parity test in tests/test_priors.py)",
    },
    "PC_STORE_DIR": {
        "status": "exempt",
        "reason": "names WHERE the store lives, never what any artifact "
                  "contains",
    },
    "PC_STORE_TIERS": {
        "status": "exempt",
        "reason": "names WHERE artifact bytes are placed across store "
                  "tiers (and the budgets moving them), never what any "
                  "artifact contains",
    },
    "PC_RUN_ID": {
        "status": "exempt",
        "reason": "multi-process barrier namespace (parallel/distributed "
                  "rendezvous files); no artifact byte depends on it",
    },
    "PC_MEDIA_FAULTS": {
        "status": "exempt",
        "reason": "test/CI/chaos fault injection at the native media "
                  "boundary (io/faults.py): every clause aborts the "
                  "consuming execution (exception or EOF-kill) before "
                  "any artifact commits, so no committed byte ever "
                  "depends on it; production never sets it "
                  "(docs/ROBUSTNESS.md)",
    },
    "PC_MEDIA_DEADLINE_S": {
        "status": "exempt",
        "reason": "wall-clock budget on native decode/encode crossings "
                  "(io/faults.guarded_call): an expiry aborts the "
                  "crossing with MediaDeadlineExpired before any "
                  "artifact commits; the frames delivered by surviving "
                  "crossings are identical at any budget",
    },
    "PC_ISOLATE_DECODE": {
        "status": "exempt",
        "reason": "first-contact SRC validation routing (io/isolate.py): "
                  "the supervised child decodes and DISCARDS frames — "
                  "it decides whether the replica may touch the SRC at "
                  "all, and never produces artifact bytes",
    },
    "JAX_NUM_PROCESSES": {
        "status": "exempt",
        "reason": "process topology shards WHICH process renders each "
                  "lane; per-artifact bytes are topology-invariant "
                  "(distributed dryrun parity)",
    },
    "JAX_PROCESS_ID": {
        "status": "exempt",
        "reason": "process topology shards WHICH process renders each "
                  "lane; per-artifact bytes are topology-invariant "
                  "(distributed dryrun parity)",
    },
}

#: module path prefixes whose env reads carry NO plan obligation: they
#: drive benches, stress harnesses and operator CLIs — their outputs are
#: not cache-addressed artifacts, so a knob there cannot poison the
#: store. (Artifact-producing code must not live under these paths.)
OUT_OF_SCOPE_MODULES = (
    "bench.py",
    "tools/",                        # repo-root harness scripts
    "processing_chain_tpu/tools/",   # operator CLI surfaces
)

#: call-name tails treated as artifact-byte producers by the checker: a
#: function that (transitively) issues one of these calls is part of the
#: byte surface an undeclared env input must not reach.
BYTE_SINK_CALLS = (
    "VideoWriter",     # every encoded container write goes through it
    "run_bucket",      # the p03 device-wave writeback
    "write_batch",     # native batched encode
    "concat_video",    # stream-copy assembly of tmp renders
    "remux",           # container rewrite of an assembled artifact
    "save_priors",     # the plan-hashed .priors.npz sidecar writer
)

#: function/method NAMES whose bodies are byte-producing by protocol
#: even without a recognizable sink call (serve executors write artifact
#: bytes through opaque helpers).
BYTE_PRODUCER_DEFS = (
    "run_batch",       # serve Executor protocol (docs/SERVE.md)
)
