"""Process-wide active-store slot.

The engine consults `active()` at plan and commit time; the CLI calls
`configure()` once per dispatch from `--store DIR` / `--no-store` /
`PC_STORE_DIR`. Holding this in its own module (instead of threading a
store object through four stages and three model layers) mirrors the
telemetry registry's design: call sites pay one attribute load when no
store is configured.
"""

from __future__ import annotations

import os
from typing import Optional

from .store import ArtifactStore

_ACTIVE: Optional[ArtifactStore] = None


def configure(root: Optional[str],
              tiers: Optional[str] = None) -> Optional[ArtifactStore]:
    """Install the store rooted at `root` (created on demand) as the
    process-wide active store; None deactivates. `tiers` is an optional
    `--store-tiers` placement spec (store/tiers.py: warm/cold backends
    and per-tier budgets); a bare root stays a one-tier config. Returns
    the store."""
    global _ACTIVE
    _ACTIVE = ArtifactStore(root, tier_spec=tiers) if root else None
    return _ACTIVE


def configure_from_args(args) -> Optional[ArtifactStore]:
    """CLI wiring: --no-store wins, then --store DIR, then PC_STORE_DIR;
    the tier spec comes from --store-tiers, then PC_STORE_TIERS.
    Always reassigns the slot so successive in-process dispatches (tests,
    orchestrators) never inherit a previous run's store by accident."""
    if getattr(args, "no_store", False):
        return configure(None)
    # plan-exempt: (names WHERE the store lives, never what an artifact contains)
    root = getattr(args, "store", None) or os.environ.get("PC_STORE_DIR") or None
    # plan-exempt: (names WHERE artifact bytes are placed, never what they contain)
    tiers = getattr(args, "store_tiers", None) or os.environ.get("PC_STORE_TIERS") or None
    return configure(root, tiers=tiers)


def active() -> Optional[ArtifactStore]:
    return _ACTIVE
