"""CAS object directory with atomic commits and integrity-verified reads.

On-disk layout under the store root (docs/STORE.md):

    objects/<digest[:2]>/<digest>     content-addressed artifact bytes
    manifests/<plan_hash>.json        one manifest per cached plan
    tmp/                              in-flight commits (pid-unique names)
    pins.json                         {plan_hash: label} GC roots
    seen-paths.jsonl                  every output path ever bound (the
                                      adoption ledger: survives manifest
                                      eviction/corruption drops)
    digest-cache.json                 stat-keyed input digest cache

Commit protocol: artifact bytes are hardlinked (copied across devices)
into tmp/ first, fsync'd, then os.replace'd into objects/ — a writer
crashed at any instant leaves at worst a tmp/ orphan that GC sweeps,
never a half-object under a valid digest. The manifest is written last
(atomic_write), so a plan hash resolves only to fully-committed bytes.

Read protocol (`serve_hit`): the manifest's object is spot-checked
(size + head digest; full digest for small objects or deep verifies),
then materialized to the legacy output path by hardlink when possible.
A mismatch anywhere counts `chain_store_corrupt_total`, drops the
manifest, and the caller rebuilds — corruption converts to a cache miss,
never to a served artifact. Media objects additionally get a container
read-back probe (open + decode one frame) at commit, which rejects the
write-time corruption class the round-5 advisor reproduced (10-bit
rawvideo muxed into AVI reads back as garbage) before it can be cached.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional

from .. import telemetry as tm
from ..utils.fsio import atomic_write
from ..utils.log import get_logger
from . import keys
from .backends import BackendIntegrityError
from .backends.local import _link_or_copy
from .tiers import TieredStore
from ..utils import lockdebug, plandebug

STORE_HITS = tm.counter(
    "chain_store_hits_total", "jobs served from the artifact store", ("runner",)
)
STORE_MISSES = tm.counter(
    "chain_store_misses_total", "plan hashes with no committed artifact",
    ("runner",),
)
STORE_EVICTIONS = tm.counter(
    "chain_store_evictions_total", "manifests evicted by GC (LRU or orphan)"
)
STORE_CORRUPT = tm.counter(
    "chain_store_corrupt_total",
    "integrity failures detected on read (digest or container probe)",
)
STORE_ADOPTIONS = tm.counter(
    "chain_store_adoptions_total",
    "pre-store artifacts adopted on first sight (legacy skip-existing parity)",
)
STORE_BYTES = tm.gauge(
    "chain_store_object_bytes", "bytes held in the store's object directory"
)
STORE_OBJECTS = tm.gauge(
    "chain_store_objects", "objects held in the store's object directory"
)

#: full-digest verification threshold for ordinary (non-deep) reads
_FULL_VERIFY_MAX = 64 << 20

#: containers worth a read-back probe (everything the chain muxes)
_MEDIA_EXTS = {".avi", ".mp4", ".mkv", ".webm", ".mov"}


class StoreCorruption(RuntimeError):
    """An artifact failed integrity verification (digest mismatch or a
    container that does not read back)."""


@dataclass
class Manifest:
    """One cached plan → artifact binding (manifests/<plan_hash>.json)."""

    plan_hash: str
    object: dict  # {"sha256", "head_sha256", "size"}
    producer: str = ""
    created_at: float = 0.0
    chain_version: str = ""
    provenance: dict = field(default_factory=dict)
    media: Optional[dict] = None  # commit-time read-back probe summary
    sidecars: dict = field(default_factory=dict)  # suffix -> object digest dict
    #: path RELATIVE to the output's directory -> digest (relative so a
    #: relocated database still materializes companions next to the new
    #: dest instead of resurrecting the old tree)
    extras: dict = field(default_factory=dict)
    materialized: Optional[dict] = None  # {"path", "size", "mtime_ns"}

    def to_json(self) -> dict:
        return {
            "planHash": self.plan_hash,
            "object": self.object,
            "producer": self.producer,
            "createdAt": self.created_at,
            "chainVersion": self.chain_version,
            "provenance": self.provenance,
            "media": self.media,
            "sidecars": self.sidecars,
            "extras": self.extras,
            "materialized": self.materialized,
        }

    @classmethod
    def from_json(cls, data: dict) -> "Manifest":
        return cls(
            plan_hash=data["planHash"],
            object=data["object"],
            producer=data.get("producer", ""),
            created_at=float(data.get("createdAt", 0.0)),
            chain_version=data.get("chainVersion", ""),
            provenance=data.get("provenance", {}),
            media=data.get("media"),
            sidecars=data.get("sidecars", {}),
            extras=data.get("extras", {}),
            materialized=data.get("materialized"),
        )

    def all_digests(self) -> list[dict]:
        """Main object + sidecars + extras, for verification and GC."""
        return [self.object, *self.sidecars.values(), *self.extras.values()]


def _probe_readback(path: str) -> Optional[dict]:
    """Open a media container and decode one frame; a summary dict on
    success, None for non-media files or when the native media boundary
    is unavailable in this environment, StoreCorruption when the file
    does not read back."""
    if os.path.splitext(path)[1].lower() not in _MEDIA_EXTS:
        return None
    try:
        from ..io import medialib
        medialib.ensure_loaded()
    except Exception:
        return None  # no decoder on this host: digest checks still apply
    from ..io.medialib import MediaError
    from ..io.video import VideoReader

    try:
        streams = medialib.probe(path).get("streams", [])
        with VideoReader(path) as reader:
            decoded = 0
            for _ in reader:
                decoded += 1
                break
            if decoded == 0:
                raise MediaError("no frames decodable")
            return {
                "pix_fmt": reader.pix_fmt,
                "width": reader.width,
                "height": reader.height,
                "fps": round(reader.fps, 6),
                "streams": len(streams),
            }
    except MediaError as exc:
        raise StoreCorruption(f"{path}: container read-back failed: {exc}") from exc


class ArtifactStore:
    """Content-addressed store rooted at one directory. Thread-compatible
    with the chain's job pools: commits are tmp+rename (last writer of an
    identical object wins, harmlessly), manifests are whole-file atomic
    writes, and the digest cache and adoption ledger carry their own
    locks (commit-time hash re-resolution runs on JobRunner workers)."""

    def __init__(self, root: str, tier_spec: Optional[str] = None) -> None:
        self.root = os.path.abspath(root)
        self.objects_dir = os.path.join(self.root, "objects")
        self.manifests_dir = os.path.join(self.root, "manifests")
        self.tmp_dir = os.path.join(self.root, "tmp")
        for d in (self.objects_dir, self.manifests_dir, self.tmp_dir):
            os.makedirs(d, exist_ok=True)
        # the tier hierarchy (docs/STORE.md "Tier hierarchy"): index 0 is
        # ALWAYS this root's own objects/ directory, so a bare root is
        # just a one-tier config and opens with zero migration
        if tier_spec:
            self.tiers = TieredStore.from_spec(
                tier_spec, self.objects_dir, self.tmp_dir)
        else:
            self.tiers = TieredStore.single(self.objects_dir, self.tmp_dir)
        self.digests = keys.DigestCache(os.path.join(self.root, "digest-cache.json"))
        self._pins_path = os.path.join(self.root, "pins.json")
        #: lazily-built set of output paths the store has ever bound
        #: (manifests ∪ the durable seen-paths ledger) — the
        #: adopt-vs-rebuild discriminator (see should_adopt)
        self._known_paths: Optional[set[str]] = None
        self._paths_path = os.path.join(self.root, "seen-paths.jsonl")
        self._paths_lock = lockdebug.make_lock("store_paths")
        self._seen_paths: Optional[set[str]] = None  # lazy ledger cache
        #: incrementally-maintained gauge state ({"objects", "bytes"});
        #: None until the first update_gauges walk
        self._gauge_stats: Optional[dict] = None

    # ------------------------------------------------------------- hashing

    def plan_hash(self, payload: dict) -> str:
        """Resolve a payload's file_refs through this store's digest cache
        and hash it. Raises OSError when an input file is missing."""
        return keys.plan_hash(payload, digest=self.digests.digest)

    # -------------------------------------------------------------- layout

    def object_path(self, sha256: str) -> str:
        return os.path.join(self.objects_dir, sha256[:2], sha256)

    def manifest_path(self, plan_hash: str) -> str:
        return os.path.join(self.manifests_dir, plan_hash + ".json")

    # ------------------------------------------------------------ manifests

    def lookup(self, plan_hash: str) -> Optional[Manifest]:
        path = self.manifest_path(plan_hash)
        try:
            with open(path) as f:
                return Manifest.from_json(json.load(f))
        except FileNotFoundError:
            return None
        except OSError as exc:
            # transient environment error (EMFILE/EIO/EACCES), not data
            # corruption: degrade to a miss but leave the manifest alone —
            # deleting a healthy cache entry over a busy file table would
            # force a spurious rebuild and misreport corruption
            get_logger().warning("store: cannot read manifest %s (%s); "
                                 "treating as a miss", path, exc)
            return None
        except (ValueError, KeyError) as exc:
            # an unparseable manifest is corruption reported as a miss;
            # the rebuild's commit overwrites it atomically. Deleting it
            # HERE would make read-only surfaces (ls, verify without
            # --drop, gc --dry-run — all funnel through lookup) mutate
            # the store as a side effect.
            get_logger().warning(
                "store: unreadable manifest %s (%s); treating as a miss "
                "(`tools store verify --drop` removes it)", path, exc,
            )
            STORE_CORRUPT.inc()
            return None

    def iter_manifests(self) -> Iterator[Manifest]:
        try:
            names = sorted(os.listdir(self.manifests_dir))
        except OSError:
            return
        for name in names:
            if not name.endswith(".json"):
                continue
            m = self.lookup(name[:-5])
            if m is not None:
                yield m

    def _write_manifest(self, manifest: Manifest) -> None:
        def _write(tmp: str) -> None:
            with open(tmp, "w") as f:
                json.dump(manifest.to_json(), f, indent=1, sort_keys=True)

        atomic_write(self.manifest_path(manifest.plan_hash), _write)

    def _drop_manifest(self, plan_hash: str) -> None:
        try:
            os.unlink(self.manifest_path(plan_hash))
        except FileNotFoundError:
            pass

    def touch(self, manifest: Manifest) -> None:
        """LRU bookkeeping: manifest file mtime is the last-used stamp."""
        try:
            os.utime(self.manifest_path(manifest.plan_hash))
        except OSError:
            pass

    # --------------------------------------------------------------- pins

    def pins(self) -> dict[str, str]:
        try:
            with open(self._pins_path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    def pin(self, plan_hash: str, label: str = "") -> None:
        pins = self.pins()
        pins[plan_hash] = label or time.strftime("%Y-%m-%d")
        self._write_pins(pins)

    def unpin(self, plan_hash: str) -> None:
        pins = self.pins()
        if pins.pop(plan_hash, None) is not None:
            self._write_pins(pins)

    def _write_pins(self, pins: dict) -> None:
        def _write(tmp: str) -> None:
            with open(tmp, "w") as f:
                json.dump(pins, f, indent=1, sort_keys=True)

        atomic_write(self._pins_path, _write)

    # ---------------------------------------------------- adoption ledger

    def _load_seen_paths(self) -> set[str]:
        """The ledger, loaded once per store object (JSONL: one JSON
        string per line; a torn last line from a crash is skipped)."""
        if self._seen_paths is None:
            seen: set[str] = set()
            try:
                with open(self._paths_path) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            entry = json.loads(line)
                        except ValueError:
                            continue  # torn tail from a crashed appender
                        if isinstance(entry, str):
                            seen.add(entry)
            except OSError:
                pass
            self._seen_paths = seen
        return self._seen_paths

    def _record_seen_path(self, path: str) -> None:
        """Durably record an output path the store has bound. The ledger
        must outlive the manifest: GC eviction (or a corruption drop)
        removes the manifest but leaves the materialized output on disk,
        and without this record a later run with a CHANGED plan would
        re-adopt those stale bytes instead of rebuilding (defeating
        hash-equality staleness exactly where it matters). O(1) per
        commit: append-only JSONL, deduped through the in-memory cache.
        Best-effort: a persistence failure degrades to the legacy
        adoption trust."""
        path = os.path.abspath(path)
        with self._paths_lock:
            seen = self._load_seen_paths()
            if path in seen:
                return
            seen.add(path)
            if self._known_paths is not None:
                self._known_paths.add(path)
            try:
                with open(self._paths_path, "a") as f:
                    f.write(json.dumps(path) + "\n")
            except OSError:
                pass

    # -------------------------------------------------------------- commit

    def _ingest(self, path: str) -> dict:
        """Hash `path` and commit its bytes into the hot tier atomically
        (tmp + rename with a pid+thread-unique scratch name; the backend
        stamps ingestion-time mtime so GC's min-object-age guard holds);
        returns the digest dict. Identical objects dedupe by construction
        — across every tier: bytes already held cold are not re-ingested
        hot, the read path promotes them when they earn it."""
        digest = keys.hash_file(path)
        if self.tiers.locate(digest["sha256"]) is None:
            self.tiers.hot.backend.put(path, digest["sha256"])
            if self._gauge_stats is not None:
                self._gauge_stats["objects"] += 1
                self._gauge_stats["bytes"] += digest["size"]
        return digest

    def commit(
        self,
        plan_hash: str,
        output_path: str,
        producer: str = "",
        provenance: Optional[dict] = None,
        sidecar_suffixes: tuple = (),
        extra_outputs: tuple = (),
        adopted: bool = False,
    ) -> Manifest:
        """Bind `plan_hash` to the artifact at `output_path` (plus any
        existing `output_path + suffix` sidecars and `extra_outputs`
        companion files at their own absolute paths). The container
        read-back probe runs BEFORE ingestion: an artifact that does not
        decode is rejected here, at the boundary where rebuilding is
        cheap, instead of being served as a 'verified' cache hit later."""
        media = _probe_readback(output_path)
        digest = self._ingest(output_path)
        sidecars = {}
        for suffix in sidecar_suffixes:
            side = output_path + suffix
            if os.path.isfile(side):
                sidecars[suffix] = self._ingest(side)
        extras = {}
        base = os.path.dirname(os.path.abspath(output_path))
        for extra in extra_outputs:
            if os.path.isfile(extra):
                rel = os.path.relpath(os.path.abspath(extra), base)
                extras[rel] = self._ingest(extra)
        st = os.stat(output_path)
        provenance = dict(provenance or {})
        if adopted:
            provenance["adopted"] = True
        manifest = Manifest(
            plan_hash=plan_hash,
            object=digest,
            producer=producer,
            created_at=time.time(),
            chain_version=keys.chain_version(),
            provenance=provenance,
            media=media,
            sidecars=sidecars,
            extras=extras,
            materialized={"path": os.path.abspath(output_path),
                          "size": st.st_size, "mtime_ns": st.st_mtime_ns},
        )
        self._write_manifest(manifest)
        self._record_seen_path(output_path)
        # plan-purity recorder (PC_PLAN_DEBUG, utils/plandebug): every
        # commit binds plan hash -> content digest; two different byte
        # streams under one hash fail the suite's sessionfinish gate
        plandebug.record(plan_hash, digest["sha256"], producer=producer,
                         scope=self.root)
        self.update_gauges()
        return manifest

    def should_adopt(self, output_path: str) -> bool:
        """Whether an existing output the store has never seen should be
        adopted (committed as-is under the current plan hash) instead of
        rebuilt. True exactly when the store has never bound this path —
        neither a live manifest nor the durable seen-paths ledger (which
        survives GC eviction and corruption drops) knows it. Pre-store
        artifacts keep the legacy skip-existing trust on the first
        store-enabled run; a path the store HAS tracked whose plan hash
        no longer matches is genuinely stale and must rebuild."""
        if self._known_paths is None:
            self._known_paths = {
                m.materialized["path"]
                for m in self.iter_manifests()
                if m.materialized
            } | self._load_seen_paths()
        return os.path.abspath(output_path) not in self._known_paths

    # ---------------------------------------------------------------- read

    def verify_object(self, digest: dict, deep: bool = False) -> None:
        """Raise StoreCorruption unless the stored object matches its
        digest: size always, head digest always, full digest when small
        or `deep`. The object may live in ANY tier; a cold-tier copy is
        verified through the backend's streamed read — the same checks,
        at whichever boundary holds the bytes."""
        sha = digest["sha256"]
        located = self.tiers.head(sha)
        if located is None:
            raise StoreCorruption(f"object {sha[:12]} missing")
        tier, size = located
        if size != digest["size"]:
            raise StoreCorruption(
                f"object {sha[:12]}: size {size} != recorded "
                f"{digest['size']} (tier {tier.name})"
            )
        obj = tier.backend.local_path(sha)
        if obj is not None:
            if deep or size <= _FULL_VERIFY_MAX:
                found = keys.hash_file(obj)
                if found["sha256"] != sha:
                    raise StoreCorruption(
                        f"object {sha[:12]}: content digest mismatch "
                        f"(tier {tier.name})"
                    )
            else:
                with open(obj, "rb") as f:
                    head = f.read(1 << 20)
                if keys.sha256_hex(head) != digest["head_sha256"]:
                    raise StoreCorruption(
                        f"object {sha[:12]}: head digest mismatch "
                        f"(tier {tier.name})"
                    )
        else:  # no filesystem path (object tier): stream the same checks
            with tier.backend.open_read(sha) as f:
                if deep or size <= _FULL_VERIFY_MAX:
                    hasher = hashlib.sha256()
                    while True:
                        block = f.read(1 << 20)
                        if not block:
                            break
                        hasher.update(block)
                    if hasher.hexdigest() != sha:
                        raise StoreCorruption(
                            f"object {sha[:12]}: content digest mismatch "
                            f"(tier {tier.name})"
                        )
                elif keys.sha256_hex(f.read(1 << 20)) != digest["head_sha256"]:
                    raise StoreCorruption(
                        f"object {sha[:12]}: head digest mismatch "
                        f"(tier {tier.name})"
                    )

    def drop_corrupt_objects(self, manifest: Manifest) -> None:
        """Unlink every object of `manifest` that fails verification. The
        bytes must go WITH the manifest: the rebuild produces the same
        content digest, and `_ingest` dedupes on object existence — a
        corrupt object left in place would be silently re-adopted and
        re-detected on every later run."""
        for digest in manifest.all_digests():
            try:
                self.verify_object(digest, deep=True)
            except StoreCorruption:
                if not self.tiers.delete_everywhere(digest["sha256"]):
                    continue
                if self._gauge_stats is not None:
                    self._gauge_stats["objects"] -= 1
                    self._gauge_stats["bytes"] -= digest["size"]

    def _materialize_one(self, digest: dict, dest: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
        obj = self.object_path(digest["sha256"])
        if not os.path.isfile(obj) and self.tiers.multi:
            # the bytes live in a colder tier: promote first (digest-
            # verified at the boundary they cross), then hardlink from
            # the hot copy exactly like the flat-store path
            try:
                self.tiers.promote(digest["sha256"])
            except BackendIntegrityError as exc:
                raise StoreCorruption(str(exc)) from exc
        try:
            if os.path.samefile(obj, dest):
                # dest already IS the object (hardlink). Linking through
                # tmp would strand it: POSIX rename of two links of one
                # inode is a silent NO-OP, leaving tmp behind to fail
                # the NEXT materialize with EEXIST — which converted a
                # perfectly warm hit into a spurious rebuild whenever
                # two destinations share one plan hash (sibling HRCs
                # with identical wo_buffer plans).
                return
        except OSError:
            pass  # dest missing (or stat raced): materialize normally
        tmp = f"{dest}.store.{os.getpid()}.part"
        try:
            if os.path.isfile(tmp):
                # stale strand from a pre-fix run or a crashed
                # materialize: heal it instead of failing EEXIST
                os.unlink(tmp)
            _link_or_copy(obj, tmp)
            os.replace(tmp, dest)
        except BaseException:
            if os.path.isfile(tmp):
                os.unlink(tmp)
            raise

    def _dest_current(self, manifest: Manifest, dest: str) -> bool:
        """Cheap staleness check for an already-materialized output: stat
        signature equality with what commit/materialize recorded."""
        rec = manifest.materialized
        if not rec or rec.get("path") != os.path.abspath(dest):
            return False
        try:
            st = os.stat(dest)
        except OSError:
            return False
        return st.st_size == rec["size"] and st.st_mtime_ns == rec["mtime_ns"]

    def serve_hit(
        self, manifest: Manifest, dest: str, materialize: bool = True,
        deep: bool = False,
    ) -> bool:
        """Serve a plan-hash hit: verify the object, then ensure `dest`
        (and sidecars) hold its bytes. False — after counting the
        corruption and dropping the manifest — means the caller must
        rebuild; the store never serves bytes it cannot vouch for."""
        try:
            for digest in manifest.all_digests():
                self.verify_object(digest, deep=deep)
        except StoreCorruption as exc:
            get_logger().warning(
                "store: %s (plan %s, producer %r); %s", exc,
                manifest.plan_hash[:12], manifest.producer,
                "dropping manifest and rebuilding" if materialize
                else "would drop manifest and rebuild (dry-run: store "
                     "left untouched)",
            )
            STORE_CORRUPT.inc()
            tm.emit("store_corrupt", plan=manifest.plan_hash,
                    producer=manifest.producer, error=str(exc)[:300])
            if materialize:
                # dry-run planning must not mutate the store: report the
                # corruption (counter + "would rebuild") and leave the
                # drop to the real run
                self.drop_corrupt_objects(manifest)
                self._drop_manifest(manifest.plan_hash)
            return False  # rebuild required
        if not materialize:  # dry-run planning: count the hit, touch nothing
            return True
        # extras rebase onto the CURRENT dest (they are stored relative
        # to the output's directory): a relocated database materializes
        # its companions next to the new output instead of resurrecting
        # the directory tree recorded at commit time
        extra_dest = os.path.dirname(os.path.abspath(dest))
        try:
            if not self._dest_current(manifest, dest):
                self._materialize_one(manifest.object, dest)
                for suffix, digest in manifest.sidecars.items():
                    self._materialize_one(digest, dest + suffix)
                for rel, digest in manifest.extras.items():
                    self._materialize_one(
                        digest, os.path.normpath(os.path.join(extra_dest, rel))
                    )
                st = os.stat(dest)
                manifest.materialized = {
                    "path": os.path.abspath(dest),
                    "size": st.st_size, "mtime_ns": st.st_mtime_ns,
                }
                self._write_manifest(manifest)
                self._record_seen_path(dest)
            else:
                # main output untouched, but a companion may have been
                # deleted out-of-band (e.g. -r removed an intermediate's
                # sidecar): restore any that are missing
                for suffix, digest in manifest.sidecars.items():
                    if not os.path.isfile(dest + suffix):
                        self._materialize_one(digest, dest + suffix)
                for rel, digest in manifest.extras.items():
                    path = os.path.normpath(os.path.join(extra_dest, rel))
                    if not os.path.isfile(path):
                        self._materialize_one(digest, path)
            self.touch(manifest)
            return True
        except (OSError, StoreCorruption) as exc:
            get_logger().warning(
                "store: could not materialize %s -> %s (%s); rebuilding",
                manifest.plan_hash[:12], dest, exc,
            )
            return False

    # ------------------------------------------------------- tiered reads

    def locate_tier(self, sha256: str) -> Optional[str]:
        """The name of the hottest tier holding the object, or None."""
        tier = self.tiers.locate(sha256)
        return tier.name if tier is not None else None

    def open_object_read(
        self, sha256: str, plan: Optional[str] = None, heat=None,
    ) -> tuple:
        """Open an object for serving: `(hit_tier, path, fileobj, size)`.

        The hit tier is the one the read FOUND the bytes in (counted in
        `chain_store_tier_hits_total` and journaled with the read); a
        non-hot hit is promoted read-through first — digest-verified at
        the boundary it crosses — and then served from the hot copy's
        fd, falling back to a direct backend stream when the promotion
        cannot complete (e.g. hot disk full). `path` is None when the
        serving tier has no filesystem path (a direct cold stream)."""
        from .tiers import TIER_HITS

        located = self.tiers.head(sha256)
        if located is None:
            raise FileNotFoundError(f"object {sha256[:12]} in no tier")
        tier, size = located
        hit = tier.name
        TIER_HITS.labels(tier=hit).inc()
        if tier is not self.tiers.hot and self.tiers.promote_on_read:
            try:
                self.tiers.promote(sha256, plan=plan, heat=heat)
                path = self.object_path(sha256)
                return hit, path, open(path, "rb"), size
            except (OSError, BackendIntegrityError) as exc:
                get_logger().warning(
                    "store: read-through promotion of %s from %s failed "
                    "(%s); serving from %s directly",
                    sha256[:12], hit, exc, hit,
                )
        path = tier.backend.local_path(sha256)
        return hit, path, tier.backend.open_read(sha256), size

    # ----------------------------------------------------------- accounting

    def iter_objects(self) -> Iterator[tuple[str, int]]:
        """(sha256, size) for every object across all tiers, deduped to
        the hottest copy (a mid-move duplicate counts once)."""
        for sha, size, _tier in self.tiers.iter_objects():
            yield sha, size

    def stats(self) -> dict:
        n = 0
        total = 0
        for _, size in self.iter_objects():
            n += 1
            total += size
        manifests = sum(
            1 for f in os.listdir(self.manifests_dir) if f.endswith(".json")
        ) if os.path.isdir(self.manifests_dir) else 0
        out = {"objects": n, "bytes": total, "manifests": manifests,
               "pins": len(self.pins())}
        if self.tiers.multi:
            out["tiers"] = self.tiers.tier_stats()
        return out

    def update_gauges(self, full: bool = False) -> None:
        """Refresh the byte/object gauges. The full objects/ walk runs
        once (then GC passes force it with `full=True`); per-commit calls
        apply the increments _ingest tracked — a walk per commit would
        make store population O(N²) in stat calls."""
        if not tm.enabled():
            return
        if full or self._gauge_stats is None:
            s = self.stats()
            self._gauge_stats = {"objects": s["objects"], "bytes": s["bytes"]}
            self.tiers.update_gauges()
        STORE_BYTES.set(self._gauge_stats["bytes"])
        STORE_OBJECTS.set(self._gauge_stats["objects"])
