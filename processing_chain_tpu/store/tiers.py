"""Hot/warm/cold placement over pluggable CAS backends (docs/STORE.md
"Tier hierarchy").

A `TieredStore` composes an ordered list of `Tier`s — index 0 is always
the store root's own `objects/` directory (the hot tier), colder tiers
follow. Reads fall through hot→warm→cold; the tier a read FOUND the
bytes in is the hit tier (`chain_store_tier_hits_total{tier=…}`), and a
non-hot hit is promoted read-through so the next reader pays local
latency. GC-pressure demotion moves the coldest objects the other way
when a tier outgrows its own byte budget (store/gc.py: demote before
evict; eviction only out of the last tier).

Placement moves are crash-safe by ordering: the bytes are streamed into
the destination backend (digest-verified at the boundary they cross,
committed atomic+durable) and only THEN deleted from the source — a
SIGKILL at any instant leaves either the untouched source, a tmp
scratch for GC, or a harmless both-tiers duplicate that the next move
pass completes. The heat ledger's move record is written after the
source delete, so a crashed move is never counted and a retried one
counts exactly once.

A bare store root is just a one-tier config: `TieredStore.single()`
wraps the classic layout with no budgets and no colder tiers, and every
code path degrades to the original flat-store behavior.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Iterator, Optional

from .. import telemetry as tm
from ..utils import lockdebug
from .backends import (
    BackendIntegrityError,
    LocalBackend,
    StoreBackend,
    crashpoint,
    make_backend,
)

TIER_HITS = tm.counter(
    "chain_store_tier_hits_total",
    "artifact reads by the tier the bytes were found in", ("tier",),
)
TIER_PROMOTIONS = tm.counter(
    "chain_store_tier_promotions_total",
    "objects promoted toward hot, labeled by the tier they LEFT",
    ("tier",),
)
TIER_DEMOTIONS = tm.counter(
    "chain_store_tier_demotions_total",
    "objects demoted toward cold, labeled by the tier they ENTERED",
    ("tier",),
)
TIER_BYTES = tm.gauge(
    "chain_store_tier_bytes", "bytes held per store tier", ("tier",)
)

#: spec-entry budgets: plain bytes or K/M/G/T suffixed
_BUDGET_RE = re.compile(r"^(\d+(?:\.\d+)?)([kKmMgGtT]?)$")


class TierSpecError(ValueError):
    """A malformed `--store-tiers` / PC_STORE_TIERS spec."""


def parse_budget(text: str) -> int:
    m = _BUDGET_RE.match(text.strip())
    if not m:
        raise TierSpecError(f"unparseable byte budget {text!r} "
                            "(expected e.g. 500M, 2G, 1048576)")
    scale = {"": 1, "k": 1 << 10, "m": 1 << 20,
             "g": 1 << 30, "t": 1 << 40}[m.group(2).lower()]
    return int(float(m.group(1)) * scale)


@dataclass
class Tier:
    """One rung of the hierarchy: a name forensics can print, a backend
    holding the bytes, and an optional byte budget that triggers
    demotion (NOT eviction) when outgrown."""

    name: str
    backend: StoreBackend
    budget_bytes: Optional[int] = None

    def bytes_held(self) -> int:
        return sum(size for _, size in self.backend.list())


def parse_tier_spec(spec: str) -> tuple[Optional[int], list]:
    """Parse a `--store-tiers` spec into (hot_budget, extra tiers).

    Grammar: comma/semicolon-separated entries —

        hot[@BUDGET]                   budget for the implicit hot tier
        shared=PATH[@BUDGET]           a warm tier (shared local-FS root)
        local=PATH[@BUDGET]            a warm tier (plain local root)
        object=PATH[@BUDGET]           an S3-shaped cold tier (the
                                       directory-backed reference client)

    e.g. `hot@64M,shared=/mnt/warm@2G,object=/mnt/cold`. Tier names are
    assigned by kind: shared/local entries are warm, object entries are
    cold (duplicates numbered warm2, cold2, …).
    """
    hot_budget: Optional[int] = None
    tiers: list[Tier] = []
    used_names: set[str] = set()
    for raw in re.split(r"[;,]", spec):
        entry = raw.strip()
        if not entry:
            continue
        budget: Optional[int] = None
        if "@" in entry:
            entry, _, budget_text = entry.rpartition("@")
            budget = parse_budget(budget_text)
        if entry == "hot":
            hot_budget = budget
            continue
        if "=" not in entry:
            raise TierSpecError(
                f"unparseable tier entry {raw!r} (expected "
                "hot[@BUDGET] or kind=path[@BUDGET])")
        kind, _, path = entry.partition("=")
        kind = kind.strip()
        if not path:
            raise TierSpecError(f"tier entry {raw!r} names no path")
        base = "cold" if kind == "object" else "warm"
        name = base
        n = 2
        while name in used_names:
            name = f"{base}{n}"
            n += 1
        used_names.add(name)
        tiers.append(Tier(name=name, backend=make_backend(kind, path),
                          budget_bytes=budget))
    # warm tiers sort before cold regardless of spec order — falling
    # through hot→warm→cold is the contract, not an accident of the
    # command line
    tiers.sort(key=lambda t: t.backend.kind == "object")
    return hot_budget, tiers


class TieredStore:
    """The ordered tier list plus the placement moves between rungs."""

    def __init__(self, tiers: list, promote_on_read: bool = True) -> None:
        if not tiers:
            raise ValueError("a TieredStore needs at least the hot tier")
        self.tiers: list[Tier] = list(tiers)
        self.promote_on_read = promote_on_read
        # guarded-by: _move_lock — cross-tier moves of distinct objects
        # are independent, but two concurrent moves of ONE object could
        # interleave a delete under a copy; one lock is cheap because
        # moves are rare (reads dominate by orders of magnitude)
        self._move_lock = lockdebug.make_lock("store_tiers")

    @classmethod
    def single(cls, objects_dir: str, tmp_dir: str) -> "TieredStore":
        """A bare store root as a one-tier config — zero migration."""
        return cls([Tier("hot", LocalBackend(objects_dir, tmp_dir))])

    @classmethod
    def from_spec(cls, spec: str, objects_dir: str,
                  tmp_dir: str) -> "TieredStore":
        hot_budget, extra = parse_tier_spec(spec)
        hot = Tier("hot", LocalBackend(objects_dir, tmp_dir),
                   budget_bytes=hot_budget)
        return cls([hot, *extra])

    # ------------------------------------------------------------- lookup

    @property
    def hot(self) -> Tier:
        return self.tiers[0]

    @property
    def multi(self) -> bool:
        return len(self.tiers) > 1

    def tier(self, name: str) -> Tier:
        for t in self.tiers:
            if t.name == name:
                return t
        raise KeyError(f"no store tier named {name!r}")

    def locate(self, sha256: str) -> Optional[Tier]:
        """The hottest tier holding the object (reads fall through in
        this order; a mid-move duplicate resolves to the hotter copy)."""
        for t in self.tiers:
            if t.backend.head(sha256) is not None:
                return t
        return None

    def head(self, sha256: str) -> Optional[tuple]:
        for t in self.tiers:
            size = t.backend.head(sha256)
            if size is not None:
                return t, size
        return None

    def iter_objects(self) -> Iterator[tuple[str, int, str]]:
        """(sha256, size, tier name) for every object, deduped to the
        hottest copy — the accounting view GC and stats consume."""
        seen: set[str] = set()
        for t in self.tiers:
            for sha, size in t.backend.list():
                if sha in seen:
                    continue
                seen.add(sha)
                yield sha, size, t.name

    def tier_stats(self) -> dict:
        """Per-tier {objects, bytes, budget_bytes} (no dedup: a mid-move
        duplicate is real disk in both tiers)."""
        out: dict[str, dict] = {}
        for t in self.tiers:
            n = 0
            total = 0
            for _, size in t.backend.list():
                n += 1
                total += size
            out[t.name] = {"objects": n, "bytes": total,
                           "budget_bytes": t.budget_bytes}
        return out

    def update_gauges(self) -> None:
        if not tm.enabled():
            return
        for name, s in self.tier_stats().items():
            TIER_BYTES.labels(tier=name).set(s["bytes"])

    # -------------------------------------------------------------- moves

    def promote(self, sha256: str, plan: Optional[str] = None,
                heat=None) -> Optional[dict]:
        """Move an object to the hot tier (read-through promotion).
        Returns the move evidence dict, or None when already hot."""
        src = self.locate(sha256)
        if src is None:
            raise FileNotFoundError(f"object {sha256[:12]} in no tier")
        if src is self.hot:
            return None
        return self._move(sha256, src, self.hot, op="promote",
                          plan=plan, heat=heat)

    def demote(self, sha256: str, src: Tier, dst: Tier,
               plan: Optional[str] = None, heat=None) -> dict:
        return self._move(sha256, src, dst, op="demote",
                          plan=plan, heat=heat)

    def _move(self, sha256: str, src: Tier, dst: Tier, op: str,
              plan: Optional[str] = None, heat=None) -> dict:
        """Copy-verify-commit-then-delete. The source copy survives
        until the destination commit is durable; the heat record lands
        only after the delete, so crashed moves never double-count."""
        with self._move_lock:  # holds-lock: store_tiers
            nbytes = dst.backend.head(sha256)
            if nbytes is None:
                with src.backend.open_read(sha256) as f:
                    try:
                        nbytes = dst.backend.put_stream(f, sha256)
                    except BackendIntegrityError:
                        # the SOURCE copy is corrupt: surface it as the
                        # store-corruption class the read path already
                        # converts to a rebuild — never delete the only
                        # (even corrupt) copy here
                        raise
            crashpoint("pre_delete")
            src.backend.delete(sha256)
        evidence = {"object": sha256, "op": op, "from_tier": src.name,
                    "to_tier": dst.name, "bytes": int(nbytes)}
        if plan is not None:
            evidence["plan"] = plan
        if op == "promote":
            TIER_PROMOTIONS.labels(tier=src.name).inc()
            tm.emit("store_promote", **evidence)
        else:
            TIER_DEMOTIONS.labels(tier=dst.name).inc()
            tm.emit("store_demote", **evidence)
        if heat is not None:
            heat.record_move(evidence)
        return evidence

    def delete_everywhere(self, sha256: str) -> bool:
        """Unlink the object from every tier holding it (corruption
        drops and final eviction)."""
        removed = False
        for t in self.tiers:
            removed = t.backend.delete(sha256) or removed
        return removed
