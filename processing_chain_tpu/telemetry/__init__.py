"""Telemetry subsystem: metrics registry + structured run events.

The quantitative observability layer the tracing module (wall-time
spans) and provenance .log files don't cover: counters/gauges/histograms
for throughput and queueing, and a structured JSONL event log for run
forensics. See docs/TELEMETRY.md for the metric catalog and the event
schema, and tools/run_report.py for the aggregated human-readable view.

Enablement is process-wide and OFF by default; every instrumentation
site in the chain is guarded so a disabled run pays one attribute check
per call site, with zero allocation. `--telemetry DIR` on any stage CLI
enables it and persists three artifacts into DIR at exit:

    metrics_<ts>.json    registry snapshot (counters/gauges/histograms)
    metrics_<ts>.prom    Prometheus textfile-collector export
    events_<ts>.jsonl    the structured event log

plus a trace_<ts>.json span report (shared with `--trace`), all under
one collision-safe <ts> stamp so tools/run_report.py can join them.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from typing import Iterator, Optional

from .events import (  # noqa: F401  (re-exports)
    EVENTS,
    EventLog,
    EventLogHandler,
    attach_log_handler,
    detach_log_handler,
    emit,
    read_jsonl,
)
from .heartbeat import (  # noqa: F401
    HEARTBEATS,
    HeartbeatRegistry,
    TaskCancelled,
)
from ..utils import lockdebug
from .metrics import (  # noqa: F401
    DEFAULT_DEPTH_BUCKETS,
    DEFAULT_LATENCY_BUCKETS,
    REGISTRY,
    MetricError,
    MetricsRegistry,
    counter,
    gauge,
    histogram,
)


def enabled() -> bool:
    return REGISTRY.enabled


def enable() -> None:
    REGISTRY.enabled = True
    EVENTS.enabled = True
    HEARTBEATS.enabled = True


def disable() -> None:
    REGISTRY.enabled = False
    EVENTS.enabled = False
    HEARTBEATS.enabled = False


def reset() -> None:
    """Zero all series, drop all events and heartbeats (for a fresh run
    in one process — registrations and bound handles stay valid)."""
    REGISTRY.reset()
    EVENTS.clear()
    HEARTBEATS.reset()


def unique_stamp() -> str:
    """Wall-clock stamp that never collides within a process even when
    two callers hit the same second: pid + a monotonic counter."""
    global _STAMP_SEQ
    with _STAMP_LOCK:
        _STAMP_SEQ += 1
        seq = _STAMP_SEQ
    return f"{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}-{seq}"


_STAMP_SEQ = 0
_STAMP_LOCK = lockdebug.make_lock("stamp")

# Cross-layer counters the stage spans diff against. Frames/bytes are
# incremented by the prefetch pipeline (engine/prefetch.py) where every
# decoded and encoded chunk already flows through one choke point.
FRAMES_DECODED = counter(
    "chain_frames_decoded_total", "video frames decoded into the pipeline"
)
FRAMES_ENCODED = counter(
    "chain_frames_encoded_total", "video frames written back out"
)
BYTES_ENCODED = counter(
    "chain_bytes_encoded_total", "raw plane bytes handed to writers"
)
STAGE_SECONDS = gauge(
    "chain_stage_wall_seconds", "wall time of the last run of each stage",
    ("stage",),
)
STAGE_ITEMS = gauge(
    "chain_stage_items", "work items handled by the last run of each stage",
    ("stage",),
)


@contextmanager
def stage_span(stage: str, **fields) -> Iterator[None]:
    """Wrap one stage run (p01..p04): emits stage_start/stage_end events
    carrying the frames/bytes counter deltas, from which a report derives
    per-stage throughput without any per-stage plumbing inside the
    models layer. Also opens the stage's live heartbeat (units = jobs;
    planned by JobRunner.add, advanced by Job completion) so /status can
    answer per-stage progress + ETA while the stage runs."""
    if not REGISTRY.enabled and not HEARTBEATS.enabled:
        yield
        return
    from . import profiling as _profiling

    before = (
        FRAMES_DECODED.get(), FRAMES_ENCODED.get(), BYTES_ENCODED.get(),
    )
    # component seconds (decode/encode blocked time, device transfer,
    # device step) diffed across the stage: the per-stage grounding of
    # the attribution engine's bottleneck verdicts
    before_comp = (
        _profiling.components_from_live()[0] if REGISTRY.enabled else None
    )
    # decoder opens diffed per stage: the attribution engine refuses a
    # decode_bound verdict for a stage that opened ZERO decoders (its
    # consumer-blocked seconds are in-memory plumbing — the fused p04
    # fan-out — not decode; telemetry/profiling.attribute_run)
    before_opens = (
        REGISTRY.sum_series("chain_io_decoder_opens_total", None)
        if REGISTRY.enabled else None
    )
    emit("stage_start", stage=stage, **fields)
    HEARTBEATS.stage_begin(stage)
    t0 = time.perf_counter()
    status = "ok"
    try:
        yield
    except BaseException:
        status = "fail"
        raise
    finally:
        wall = time.perf_counter() - t0
        STAGE_SECONDS.labels(stage=stage).set(wall)
        HEARTBEATS.stage_end(stage, status)
        extra = dict(fields)
        if before_comp is not None:
            # only components measured by the END of the stage get a
            # delta (a series born mid-stage starts from 0); components
            # with no series at all stay absent — the attribution engine
            # reports them as unmeasured instead of zero
            after_comp = _profiling.components_from_live()[0]
            extra["components"] = {
                comp: round(total - before_comp.get(comp, 0.0), 4)
                for comp, total in after_comp.items()
            }
            after_opens = REGISTRY.sum_series(
                "chain_io_decoder_opens_total", None
            )
            if after_opens is not None:
                extra["decoder_opens"] = int(
                    after_opens - (before_opens or 0.0)
                )
        emit(
            "stage_end",
            stage=stage,
            status=status,
            duration_s=round(wall, 4),
            frames_decoded=FRAMES_DECODED.get() - before[0],
            frames_encoded=FRAMES_ENCODED.get() - before[1],
            bytes_encoded=BYTES_ENCODED.get() - before[2],
            **extra,
        )


def stage_items(stage: str, n: float) -> None:
    """Record a stage's work-item count on both surfaces at once: the
    STAGE_ITEMS gauge (post-run metrics) and the live status document
    (the `items` field next to the jobs-based progress)."""
    STAGE_ITEMS.labels(stage=stage).set(n)
    HEARTBEATS.stage_items(stage, n)


def write_outputs(out_dir: str, stamp: Optional[str] = None) -> dict[str, str]:
    """Persist the registry + event log into `out_dir` under one stamp.
    Returns {"metrics": path, "prom": path, "events": path, "stamp": s}."""
    stamp = stamp or unique_stamp()
    os.makedirs(out_dir, exist_ok=True)
    paths = {
        "metrics": REGISTRY.write_json(
            os.path.join(out_dir, f"metrics_{stamp}.json")
        ),
        "prom": REGISTRY.write_prometheus(
            os.path.join(out_dir, f"metrics_{stamp}.prom")
        ),
        "events": EVENTS.write_jsonl(
            os.path.join(out_dir, f"events_{stamp}.jsonl")
        ),
        "stamp": stamp,
    }
    return paths
