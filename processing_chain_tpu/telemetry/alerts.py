"""Burn-rate alerting: the watcher over every flight recorder.

The fleet records everything — request spans with SLO histograms,
artifact-plane heat/regret journals, device-plane wave/compile
journals — but until this module nothing *watched* the recorders: a
breached read SLO was only visible if an operator happened to run
fleet-top. The `AlertEngine` closes that gap. Each replica's service
maintenance tick hands it the merged fleet view (telemetry/fleet.py)
and it grades every rule declared in `telemetry/catalog.py
ALERT_RULES`:

  * **SLO burn rules** are multi-window multi-burn-rate (the SRE
    shape): per (tenant × class) flow the engine snapshots the
    cumulative (count, in-band) pair from the fleet-merged histograms
    and computes the error-budget burn rate over each declared window
    pair (`catalog.BURN_RATE_WINDOWS`: fast 5m/1h pages, slow 30m/6h
    tickets). A pair trips only when BOTH its windows burn — the short
    window makes the alert fast to fire and fast to resolve, the long
    window keeps one bad minute from paging.
  * **Cross-plane rules** watch the other recorders: active watchdog
    stall/hard-timeout episodes, eviction-regret records accruing
    inside the fast window (store/heat.py — the cache is undersized),
    mesh geometry buckets wasting past the fragmentation threshold
    (parallel/meshobs.py), and replicas gone `stale` (serve-info on
    disk, process not answering).

Fire/resolve transitions are durable journal records under the
spans/heat/meshobs discipline — append-only per-replica JSONL with
torn-tail sealing, never raising into the service that hosts the
engine — with dedup keys (an already-firing alert is re-notified on a
throttle, never re-fired) so the merged stream stays coherent when
several replicas evaluate concurrently. `/fleet/alerts` serves the
folded view; `tools fleet-doctor` joins the journal with the other
planes into incident timelines.

The autoscale advisor (serve/autoscale.py) shares this journal: its
`scale` recommendation records ride the same files, so every scale
decision is attributable next to the alerts that motivated it.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Optional

from ..utils import lockdebug
from ..utils.log import get_logger
from . import catalog
from .events import emit
from .metrics import counter, gauge
from .profiling import FRAGMENTATION_WASTE_THRESHOLD

FIRED = counter(
    "chain_alerts_fired_total",
    "alert fire transitions graded by this replica's engine", ("rule",),
)
RESOLVED = counter(
    "chain_alerts_resolved_total",
    "alert resolve transitions graded by this replica's engine",
    ("rule",),
)
ACTIVE = gauge(
    "chain_alerts_active",
    "alerts currently firing in this replica's engine",
)

_SAFE_NAME = re.compile(r"[^A-Za-z0-9._-]+")

#: while an alert stays firing, one `renotify` record per this many
#: seconds (scaled by the engine's window_scale) — the dedup contract:
#: the condition holding is one incident, not a record per evaluation
DEFAULT_RENOTIFY_S = 300.0

#: the error budget an SLO flow may spend: 1 - target fraction
_BUDGET_FRACTION = 1.0 - catalog.SLO_TARGET_FRACTION


def alerts_dir(root: str) -> str:
    """The alert-journal directory of one serve root."""
    return os.path.join(os.path.abspath(root), "alerts")


def _journal_name(replica: str) -> str:
    return _SAFE_NAME.sub("_", replica) + ".jsonl"


# ------------------------------------------------------------- journal


class AlertJournal:
    """Append-only per-replica alert journal (the spans/heat/meshobs
    discipline): lazily opened, torn predecessor tails sealed before
    the first append, every failure degraded to a logged warning —
    alerting must never take down the service it watches."""

    def __init__(self, root: str, replica: str) -> None:
        self.root = os.path.abspath(root)
        self.replica = replica
        self.path = os.path.join(self.root, _journal_name(replica))
        self._lock = lockdebug.make_lock("alert_journal")
        self._f = None      # guarded-by: _lock
        self._seq = 0       # guarded-by: _lock

    def _seal_torn_tail(self) -> None:
        """A predecessor SIGKILLed mid-write leaves a torn final line.
        Readers skip it, but O_APPEND would glue THIS incarnation's
        first record onto it and lose both — terminate the torn line
        before appending so our records stay parseable."""
        try:
            with open(self.path, "rb+") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() == 0:
                    return
                f.seek(-1, os.SEEK_END)
                if f.read(1) != b"\n":
                    f.write(b"\n")
        except FileNotFoundError:
            return
        except OSError:
            pass  # the append itself will surface a real disk fault

    def append(self, record: dict) -> None:
        """One journal record. Never raises."""
        record.setdefault("ts", round(time.time(), 6))
        record["replica"] = self.replica
        record["pid"] = os.getpid()
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            try:
                if self._f is None:
                    os.makedirs(self.root, exist_ok=True)
                    self._seal_torn_tail()
                    self._f = open(self.path, "a")
                self._f.write(json.dumps(record, sort_keys=True) + "\n")
                self._f.flush()
            except (OSError, ValueError):
                get_logger().warning(
                    "alerts: could not append %s record",
                    record.get("kind"), exc_info=True)
                try:
                    if self._f is not None:
                        self._f.close()
                except OSError:
                    pass
                self._f = None

    def close(self) -> None:
        with self._lock:
            try:
                if self._f is not None:
                    self._f.close()
            except OSError:
                pass
            self._f = None


# ----------------------------------------------------------- burn math


class FlowWindow:
    """Cumulative (count, in-band) snapshots of one graded flow, from
    which windowed burn rates derive. The fleet histograms are
    cumulative, so a *windowed* error fraction needs the delta between
    two snapshots; the engine snapshots once per evaluation and this
    class answers "how fast did this flow burn budget over the last W
    seconds"."""

    __slots__ = ("snaps",)

    def __init__(self) -> None:
        #: (ts, cumulative count, cumulative in-band count)
        self.snaps: list = []

    def add(self, ts: float, count: float,
            within_band: Optional[float]) -> None:
        inband = count * within_band if within_band is not None else count
        self.snaps.append((ts, float(count), float(inband)))

    def prune(self, now: float, keep_s: float) -> None:
        cutoff = now - keep_s
        # keep one snapshot OLDER than the horizon so the longest
        # window always has a far edge to delta against
        while len(self.snaps) > 2 and self.snaps[1][0] <= cutoff:
            self.snaps.pop(0)

    def burn(self, now: float, window_s: float) -> Optional[float]:
        """Error-budget burn rate over the trailing window: the error
        fraction of the observations inside it, divided by the budget
        fraction (1 == spending exactly the whole budget at the SLO
        boundary). None while the window holds no new observations.
        History shorter than the window grades over what exists — the
        engine would otherwise be blind for the first long-window
        span of every incident."""
        if len(self.snaps) < 2:
            return None
        t1, c1, i1 = self.snaps[-1]
        t0, c0, i0 = self.snaps[0]
        for snap in self.snaps:
            if snap[0] >= now - window_s:
                t0, c0, i0 = snap
                break
        if t1 <= t0:
            return None
        d_count = c1 - c0
        if d_count <= 0:
            return None
        d_err = max(0.0, d_count - (i1 - i0))
        return (d_err / d_count) / _BUDGET_FRACTION


# -------------------------------------------------------------- engine


class AlertEngine:
    """Grades `catalog.ALERT_RULES` against successive fleet views and
    journals the fire/resolve lifecycle. One engine per replica; dedup
    keys keep the fleet-merged stream coherent when several evaluate.

    `window_scale` uniformly compresses every declared window (and the
    re-notify throttle) — the soak harness squeezes hours into seconds
    without forking the rule declarations the production fleet runs.
    """

    def __init__(self, root: str, replica: str, *,
                 journal: Optional[AlertJournal] = None,
                 window_scale: float = 1.0,
                 renotify_s: float = DEFAULT_RENOTIFY_S,
                 rules: Optional[dict] = None) -> None:
        self.root = os.path.abspath(root)
        self.replica = replica
        self.window_scale = float(window_scale)
        self.renotify_s = float(renotify_s) * self.window_scale
        self.rules = dict(rules if rules is not None
                          else catalog.ALERT_RULES)
        self.journal = journal or AlertJournal(alerts_dir(root), replica)
        self._lock = lockdebug.make_lock("alert_engine")
        self._flows: dict = {}    # flow key -> FlowWindow  # guarded-by: _lock
        self._active: dict = {}   # alert key -> state dict  # guarded-by: _lock
        self._fire_count = 0      # guarded-by: _lock
        #: longest horizon any window needs, for snapshot pruning
        self._keep_s = max(
            w["long_s"] for w in catalog.BURN_RATE_WINDOWS.values()
        ) * self.window_scale * 1.25

    # ------------------------------------------------------- evaluation

    def evaluate(self, view: dict, now: Optional[float] = None) -> dict:
        """One grading pass over a fleet-view document. Returns
        {"active": [...], "fired": [...], "resolved": [...]} — the
        transitions this pass produced plus everything still firing.
        Never raises: a rule that cannot grade is logged and skipped
        (alerting must not sink the maintenance tick that hosts it)."""
        now = time.time() if now is None else now
        conditions: dict = {}
        for rule, spec in self.rules.items():
            try:
                for cond in self._grade_rule(rule, spec, view, now):
                    conditions[cond["alert"]] = cond
            except Exception:  # noqa: BLE001 - one bad rule must not mute the rest
                get_logger().warning(
                    "alerts: rule %s failed to grade", rule,
                    exc_info=True)
        return self._transition(conditions, now)

    def _grade_rule(self, rule: str, spec: dict, view: dict,
                    now: float) -> list:
        source = spec.get("source")
        if source in ("slo", "read_slo"):
            return self._grade_burn(rule, spec, view.get(source) or {},
                                    now)
        if source == "stalls":
            return self._grade_stalls(rule, spec, view.get("stalls") or [])
        if source == "heat":
            return self._grade_heat(rule, spec, view.get("heat") or {},
                                    now)
        if source == "mesh":
            return self._grade_mesh(rule, spec, view.get("mesh") or {})
        if source == "replicas":
            return self._grade_stale(rule, spec,
                                     view.get("replicas") or [])
        raise ValueError(f"rule {rule}: unknown source {source!r}")

    def _grade_burn(self, rule: str, spec: dict, report: dict,
                    now: float) -> list:
        """Multi-window multi-burn-rate over one SLO report section:
        per flow, snapshot the cumulative cell and trip when any
        declared window pair burns past its rate on BOTH windows."""
        phase = spec["phase"]
        out: list = []
        for tenant in sorted(report):
            for cls in sorted(report[tenant]):
                cell = report[tenant][cls].get(phase)
                if not cell or not cell.get("count"):
                    continue
                with self._lock:
                    flow = self._flows.setdefault(
                        (rule, tenant, cls), FlowWindow())
                    flow.add(now, cell["count"], cell.get("within_band"))
                    flow.prune(now, self._keep_s)
                    tripped = None
                    for wname, w in sorted(
                            catalog.BURN_RATE_WINDOWS.items()):
                        short = flow.burn(
                            now, w["short_s"] * self.window_scale)
                        long = flow.burn(
                            now, w["long_s"] * self.window_scale)
                        if short is not None and long is not None and \
                                short >= w["burn_rate"] and \
                                long >= w["burn_rate"]:
                            tripped = (wname, w, short)
                            break
                if tripped is None:
                    continue
                wname, w, short = tripped
                labels = {"tenant": tenant, "class": cls,
                          "phase": phase}
                out.append({
                    "alert": _alert_key(rule, labels),
                    "rule": rule, "labels": labels,
                    "severity": spec.get("severity", "ticket"),
                    "value": round(short, 2),
                    "threshold": w["burn_rate"], "window": wname,
                    "reason": (
                        f"{tenant}/{cls} {phase} burning error budget "
                        f"at {short:.1f}x over the {wname} windows "
                        f"(threshold {w['burn_rate']:g}x)"),
                })
        return out

    def _grade_stalls(self, rule: str, spec: dict, stalls: list) -> list:
        incident = spec.get("incident", "stalled")
        out: list = []
        for stall in stalls:
            if stall.get("incident", "stalled") != incident:
                continue
            labels = {"replica": stall.get("replica", "?"),
                      "task": stall.get("task", "?"),
                      "stage": stall.get("stage") or "-"}
            out.append({
                "alert": _alert_key(rule, labels),
                "rule": rule, "labels": labels,
                "severity": spec.get("severity", "ticket"),
                "value": stall.get("beat_age_s"),
                "threshold": None, "window": None,
                "reason": (
                    f"{labels['replica']}: {stall.get('kind', 'task')} "
                    f"'{labels['task']}' {incident} for "
                    f"{stall.get('beat_age_s', 0):.0f}s "
                    f"(stage {labels['stage']})"),
            })
        return out

    def _grade_heat(self, rule: str, spec: dict, heat: dict,
                    now: float) -> list:
        regrets = heat.get("regrets")
        if regrets is None:
            return []
        window_s = (catalog.BURN_RATE_WINDOWS["fast"]["short_s"]
                    * self.window_scale)
        with self._lock:
            flow = self._flows.setdefault((rule,), FlowWindow())
            # the stats are tail-sampled, so the cumulative count can
            # slide DOWN as old records leave the window; clamp to
            # monotonic so a slide never reads as fresh regret
            prev = flow.snaps[-1][1] if flow.snaps else 0.0
            flow.add(now, max(float(regrets), prev), None)
            flow.prune(now, self._keep_s)
            delta = 0.0
            if len(flow.snaps) >= 2:
                far = flow.snaps[0]
                for snap in flow.snaps:
                    if snap[0] >= now - window_s:
                        far = snap
                        break
                delta = flow.snaps[-1][1] - far[1]
        if delta < spec.get("min_regrets", 1):
            return []
        labels = {"plane": "store"}
        return [{
            "alert": _alert_key(rule, labels),
            "rule": rule, "labels": labels,
            "severity": spec.get("severity", "ticket"),
            "value": int(delta), "threshold": spec.get("min_regrets", 1),
            "window": "fast",
            "reason": (
                f"{int(delta)} eviction regret(s) inside the fast "
                "window — recently-evicted artifacts are being re-read "
                "or rebuilt (hot tier undersized)"),
        }]

    def _grade_mesh(self, rule: str, spec: dict, mesh: dict) -> list:
        out: list = []
        for bucket, b in sorted((mesh.get("buckets") or {}).items()):
            waves = b.get("waves", 0)
            waste = b.get("waste_fraction", 0.0)
            if waves < spec.get("min_waves", 3) or \
                    waste < FRAGMENTATION_WASTE_THRESHOLD:
                continue
            labels = {"bucket": bucket}
            out.append({
                "alert": _alert_key(rule, labels),
                "rule": rule, "labels": labels,
                "severity": spec.get("severity", "ticket"),
                "value": waste,
                "threshold": FRAGMENTATION_WASTE_THRESHOLD,
                "window": None,
                "reason": (
                    f"mesh bucket {bucket} wastes "
                    f"{waste:.0%} of its slots over {waves} waves "
                    f"(threshold {FRAGMENTATION_WASTE_THRESHOLD:.0%})"),
            })
        return out

    def _grade_stale(self, rule: str, spec: dict,
                     replicas: list) -> list:
        stale_after = (spec.get("stale_after_s", 30.0)
                       * self.window_scale)
        out: list = []
        for rep in replicas:
            if rep.get("status") != "stale":
                continue
            age = rep.get("last_seen_s")
            if age is None or age < stale_after:
                continue
            labels = {"replica": rep.get("replica", "?")}
            out.append({
                "alert": _alert_key(rule, labels),
                "rule": rule, "labels": labels,
                "severity": spec.get("severity", "page"),
                "value": round(age, 1), "threshold": stale_after,
                "window": None,
                "reason": (
                    f"replica {labels['replica']} has a serve-info "
                    f"registration but stopped answering "
                    f"{age:.0f}s ago"),
            })
        return out

    # ------------------------------------------------------ transitions

    def _transition(self, conditions: dict, now: float) -> dict:
        """Diff this pass's tripped conditions against the firing set:
        new keys fire (journal + event + counter, once — the dedup
        contract), persisting keys re-notify on the throttle, vanished
        keys resolve."""
        fired: list = []
        resolved: list = []
        renotify: list = []
        with self._lock:
            for key, cond in conditions.items():
                state = self._active.get(key)
                if state is None:
                    self._fire_count += 1
                    alert_id = (f"al-{_SAFE_NAME.sub('_', self.replica)}"
                                f"-{self._fire_count:04d}")
                    state = {"id": alert_id, "fired_ts": now,
                             "notified_ts": now, **cond}
                    self._active[key] = state
                    fired.append(dict(state))
                else:
                    state.update({k: cond[k] for k in
                                  ("value", "reason", "window")})
                    if now - state["notified_ts"] >= self.renotify_s:
                        state["notified_ts"] = now
                        renotify.append(dict(state))
            for key in [k for k in self._active if k not in conditions]:
                state = self._active.pop(key)
                state["resolved_ts"] = now
                state["duration_s"] = round(now - state["fired_ts"], 3)
                resolved.append(state)
            active = [dict(s) for s in self._active.values()]
        for state in fired:
            self.journal.append({
                "kind": "fired", "id": state["id"],
                "alert": state["alert"], "rule": state["rule"],
                "severity": state["severity"],
                "labels": state["labels"], "value": state["value"],
                "threshold": state["threshold"],
                "window": state["window"], "reason": state["reason"],
                "ts": round(now, 6),
            })
            FIRED.labels(rule=state["rule"]).inc()
            emit("alert_fired", rule=state["rule"], alert=state["alert"],
                 id=state["id"], severity=state["severity"],
                 reason=state["reason"])
        for state in renotify:
            self.journal.append({
                "kind": "renotify", "id": state["id"],
                "alert": state["alert"], "rule": state["rule"],
                "severity": state["severity"],
                "labels": state["labels"], "value": state["value"],
                "reason": state["reason"], "ts": round(now, 6),
            })
        for state in resolved:
            self.journal.append({
                "kind": "resolved", "id": state["id"],
                "alert": state["alert"], "rule": state["rule"],
                "severity": state["severity"],
                "labels": state["labels"],
                "duration_s": state["duration_s"], "ts": round(now, 6),
            })
            RESOLVED.labels(rule=state["rule"]).inc()
            emit("alert_resolved", rule=state["rule"],
                 alert=state["alert"], id=state["id"],
                 duration_s=state["duration_s"])
        ACTIVE.set(len(active))
        return {"active": active, "fired": fired, "resolved": resolved}

    def active(self) -> list:
        with self._lock:
            return [dict(s) for s in self._active.values()]

    def close(self) -> None:
        self.journal.close()


def _alert_key(rule: str, labels: dict) -> str:
    """The dedup key: rule plus its sorted labels. One firing condition
    == one key == one alert, however many passes re-observe it."""
    tail = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{rule}{{{tail}}}" if tail else rule


# -------------------------------------------------------------- readers


def read_journal(path: str) -> list[dict]:
    """One journal file; tolerates torn lines (the one write a crash
    can interrupt — the spans/heat discipline)."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue  # torn line: every complete record stands
                if isinstance(record, dict):
                    out.append(record)
    except OSError:
        return []
    return out


def read_journals(root: str) -> list[dict]:
    """Every replica's alert journal under `root`, merged and ordered
    by (ts, replica, seq)."""
    records: list[dict] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        if name.endswith(".jsonl"):
            records.extend(read_journal(os.path.join(root, name)))
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("replica", ""),
                                r.get("seq", 0)))
    return records


def fold(records: list) -> dict:
    """Collapse a merged record stream into per-alert lifecycle state:
    alert key -> {state, id, rule, labels, fired_ts, last_ts, ...}.
    Later records win; a `fired` after a `resolved` re-opens the key
    (each firing episode keeps its own id)."""
    alerts: dict = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "scale":
            continue
        key = rec.get("alert")
        if not key:
            continue
        entry = alerts.setdefault(key, {"alert": key})
        if kind == "fired":
            entry.update({
                "state": "firing", "id": rec.get("id"),
                "rule": rec.get("rule"),
                "severity": rec.get("severity"),
                "labels": rec.get("labels"),
                "value": rec.get("value"), "window": rec.get("window"),
                "reason": rec.get("reason"),
                "fired_ts": rec.get("ts"), "fired_by": rec.get("replica"),
                "episodes": entry.get("episodes", 0) + 1,
            })
            entry.pop("resolved_ts", None)
            entry.pop("duration_s", None)
        elif kind == "renotify":
            entry["value"] = rec.get("value", entry.get("value"))
            entry["reason"] = rec.get("reason", entry.get("reason"))
        elif kind == "resolved":
            entry.update({
                "state": "resolved", "resolved_ts": rec.get("ts"),
                "duration_s": rec.get("duration_s"),
            })
        entry["last_ts"] = rec.get("ts")
    return alerts


def active_alerts(root: str) -> list[dict]:
    """Every alert still firing across the fleet's journals, oldest
    first — the /fleet summary and fleet-top's alert line."""
    folded = fold(read_journals(alerts_dir(root)))
    active = [a for a in folded.values() if a.get("state") == "firing"]
    active.sort(key=lambda a: a.get("fired_ts", 0.0))
    return active


def alerts_report(root: str) -> dict:
    """The /fleet/alerts document: folded lifecycle state plus raw
    journal counts. Works from durable state only — no replica needs
    to be alive."""
    records = read_journals(alerts_dir(root))
    folded = fold(records)
    by_kind: dict = {}
    for rec in records:
        kind = rec.get("kind", "?")
        by_kind[kind] = by_kind.get(kind, 0) + 1
    active = sorted((a for a in folded.values()
                     if a.get("state") == "firing"),
                    key=lambda a: a.get("fired_ts", 0.0))
    resolved = sorted((a for a in folded.values()
                       if a.get("state") == "resolved"),
                      key=lambda a: a.get("resolved_ts", 0.0))
    return {
        "schema": 1,
        "generated_at": round(time.time(), 3),
        "root": os.path.abspath(root),
        "rules": sorted(catalog.ALERT_RULES),
        "active": active,
        "resolved": resolved[-32:],
        "counts": by_kind,
    }


def latest_scale(root: str) -> Optional[dict]:
    """The newest autoscale recommendation journaled under `root`
    (serve/autoscale.py rides this journal), or None."""
    latest = None
    for rec in read_journals(alerts_dir(root)):
        if rec.get("kind") == "scale":
            latest = rec
    return latest


def find_alert(root: str, ref: str) -> Optional[dict]:
    """Resolve an alert id (`al-…`) or dedup key to its folded state
    plus every raw journal record of the episode — the fleet-doctor
    incident anchor."""
    records = read_journals(alerts_dir(root))
    key = None
    for rec in records:
        if rec.get("id") == ref or rec.get("alert") == ref:
            key = rec.get("alert")
            break
    if key is None:
        return None
    folded = fold(records).get(key)
    if folded is None:
        return None
    episode = [r for r in records if r.get("alert") == key]
    return {**folded, "records": episode}


def journal_stats(root: str) -> dict:
    """Cheap size/count stats of the alert journals for status lines."""
    files = 0
    nbytes = 0
    try:
        names = sorted(os.listdir(root))
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        files += 1
        try:
            nbytes += os.path.getsize(os.path.join(root, name))
        except OSError:
            pass
    return {"files": files, "bytes": nbytes}
