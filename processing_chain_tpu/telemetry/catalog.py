"""The telemetry NAME catalog: every metric and event, declared once.

Three surfaces ship these names — the live endpoint (PR 3), the
persisted metrics/events artifacts (PR 1), and the attribution engine +
docs tables (PR 5) — and nothing stopped a new call site from minting a
name none of the others know about. This module is the single source of
truth; chainlint's ``telemetry-name`` rule enforces that

  * every ``tm.counter/gauge/histogram("…")`` literal in the tree is
    declared here with the same kind, and
  * every ``emit("…")`` literal is declared in ``EVENTS``, and
  * every name here appears in docs/TELEMETRY.md (and every ``chain_*``
    token in that doc appears here) — the doc can't silently drift.

Adding a metric or event = add it at the call site, here, and in the
doc table; chainlint fails until all three agree.

Entries are ``name -> kind`` (kinds: counter/gauge/histogram). The
registry itself stays permissive at runtime — tests mint ad-hoc names —
so this is a static contract, not a runtime gate.
"""

from __future__ import annotations

#: metric name -> prometheus kind
METRICS: dict[str, str] = {
    # engine/jobs.py — job accounting
    "chain_jobs_planned_total": "counter",
    "chain_jobs_skipped_total": "counter",
    "chain_jobs_deduped_total": "counter",
    "chain_jobs_failed_total": "counter",
    "chain_jobs_redone_total": "counter",
    "chain_job_duration_seconds": "histogram",
    # utils/runner.py — host task execution
    "chain_runner_in_flight": "gauge",
    "chain_task_duration_seconds": "histogram",
    # engine/prefetch.py + io/video.py — pipeline frame flow
    "chain_frames_decoded_total": "counter",
    "chain_frames_encoded_total": "counter",
    "chain_bytes_encoded_total": "counter",
    "chain_queue_depth": "histogram",
    "chain_pipeline_wait_seconds_total": "counter",
    # io — batched host frame path (PR 4)
    "chain_io_batch_calls_total": "counter",
    # io — decoder opens: the fused chain's one-decode-per-SRC invariant
    "chain_io_decoder_opens_total": "counter",
    # io — the decode-once invariant's second axis: demux/parse passes
    # that are NOT decoder opens (io/medialib), plus the shared
    # post-encode scan cache (io/sharedscan) and the get_framesizes
    # memo (io/framesizes) that keep them at one per written file
    "chain_io_scan_passes_total": "counter",
    "chain_io_sharedscan_hits_total": "counter",
    "chain_io_sharedscan_misses_total": "counter",
    "chain_io_framesizes_cache_hits_total": "counter",
    "chain_bufpool_hits_total": "counter",
    "chain_bufpool_misses_total": "counter",
    "chain_bufpool_recycled_bytes_total": "counter",
    # parallel — device traffic
    "chain_device_transfer_seconds_total": "counter",
    "chain_device_transfer_bytes_total": "counter",
    "chain_device_step_seconds": "histogram",
    # stages
    "chain_stage_wall_seconds": "gauge",
    "chain_stage_items": "gauge",
    # store (PR 2)
    "chain_store_hits_total": "counter",
    "chain_store_misses_total": "counter",
    "chain_store_adoptions_total": "counter",
    "chain_store_evictions_total": "counter",
    "chain_store_corrupt_total": "counter",
    "chain_store_object_bytes": "gauge",
    "chain_store_objects": "gauge",
    # store/heat.py — the access-heat ledger: read accounting and the
    # eviction-regret cache-undersizing signal (docs/STORE.md "Access
    # heat & eviction forensics")
    "chain_store_reads_total": "counter",
    "chain_store_read_bytes_total": "counter",
    "chain_store_eviction_regret_total": "counter",
    # store/tiers.py — hot/warm/cold placement over pluggable CAS
    # backends (docs/STORE.md "Tier hierarchy")
    "chain_store_tier_hits_total": "counter",
    "chain_store_tier_promotions_total": "counter",
    "chain_store_tier_demotions_total": "counter",
    "chain_store_tier_bytes": "gauge",
    # serve/ — the always-on processing service (docs/SERVE.md)
    "chain_serve_requests_total": "counter",
    "chain_serve_units_total": "counter",
    "chain_serve_request_seconds": "histogram",
    "chain_serve_warm_request_seconds": "histogram",
    "chain_serve_queue_depth": "gauge",
    "chain_serve_inflight": "gauge",
    "chain_serve_waves_total": "counter",
    "chain_serve_wave_lanes": "histogram",
    "chain_serve_gc_evicted_bytes_total": "counter",
    "chain_serve_lease_steals_total": "counter",
    "chain_serve_fenced_settles_total": "counter",
    "chain_serve_claim_reverts_total": "counter",
    "chain_serve_quarantined_total": "counter",
    "chain_serve_poisoned_total": "counter",
    # serve/ SLO phase histograms, per (tenant × priority-class) —
    # merged across replicas by telemetry/fleet.py and graded against
    # SLO_BANDS below (docs/TELEMETRY.md "Fleet observability")
    "chain_serve_queue_wait_seconds": "histogram",
    "chain_serve_execution_seconds": "histogram",
    "chain_serve_e2e_seconds": "histogram",
    # serve/ read-path SLO histograms, per (tenant × size class) —
    # TTFB and full-stream latency of /v1/artifacts, merged by
    # telemetry/fleet.py and graded against READ_SLO_BANDS below
    "chain_serve_read_ttfb_seconds": "histogram",
    "chain_serve_read_seconds": "histogram",
    # serve/cost.py — predicted-cost model: per-tenant accounting,
    # admission refusals, and the observed-vs-predicted audit trail
    # (docs/SERVE.md "Cost-aware scheduling & admission")
    "chain_serve_cost_predicted_seconds_total": "counter",
    "chain_serve_cost_observed_seconds_total": "counter",
    "chain_serve_cost_error_ratio": "histogram",
    "chain_serve_cost_rejected_total": "counter",
    "chain_serve_cost_calibration_scale": "gauge",
    # priors/ — codec-prior extraction (docs/PRIORS.md)
    "chain_priors_extract_total": "counter",
    "chain_priors_cache_hits_total": "counter",
    "chain_priors_frames_total": "counter",
    "chain_priors_mvs_total": "counter",
    "chain_priors_extract_seconds": "histogram",
    # telemetry/profiling.py — resource monitor (PR 5)
    "chain_resource_rss_bytes": "gauge",
    "chain_resource_open_fds": "gauge",
    "chain_resource_cpu_percent": "gauge",
    "chain_resource_queue_depth": "gauge",
    "chain_bufpool_free_bytes": "gauge",
    "chain_bufpool_outstanding_bytes": "gauge",
    "chain_device_memory_bytes": "gauge",
    # parallel/meshobs.py — device-plane flight recorder: per-wave
    # occupancy/waste accounting and the compile ledger (docs/PERF.md
    # "my waves are wasteful")
    "chain_mesh_waves_total": "counter",
    "chain_mesh_wave_slots_total": "counter",
    "chain_mesh_wave_seconds": "histogram",
    "chain_mesh_waste_fraction": "gauge",
    "chain_mesh_recompiles_total": "counter",
    "chain_mesh_compile_seconds_total": "counter",
    # parallel/distributed.py — multi-process (DCN) visibility
    "chain_dist_collective_bytes_total": "counter",
    "chain_dist_barrier_seconds_total": "counter",
    # io/faults.py + io/isolate.py + models/fused.py — hostile-input
    # hardening (docs/ROBUSTNESS.md)
    "chain_media_faults_injected_total": "counter",
    "chain_media_deadline_expired_total": "counter",
    "chain_isolated_decodes_total": "counter",
    "chain_fused_members_degraded_total": "counter",
    # telemetry/alerts.py — the burn-rate engine (docs/TELEMETRY.md
    # "Alerting & the scale signal"): fire/resolve lifecycle counts per
    # rule and the live active-alert gauge
    "chain_alerts_fired_total": "counter",
    "chain_alerts_resolved_total": "counter",
    "chain_alerts_active": "gauge",
    # serve/autoscale.py — the machine-readable scale signal
    "chain_scale_desired_replicas": "gauge",
    "chain_scale_backlog_seconds": "gauge",
}

#: structured event-log record names (docs/TELEMETRY.md "Event schema")
EVENTS: frozenset = frozenset({
    "log_meta",        # head record of every events_<ts>.jsonl
    "run_start",
    "run_end",
    "stage_start",
    "stage_end",
    "job_planned",
    "job_skip",
    "job_redo",
    "job_start",
    "job_end",
    "queue_depth",
    "device_step",
    "store_corrupt",
    "store_evict",
    "task_stalled",
    "task_recovered",
    "task_hard_timeout",
    "barrier_wait",
    "serve_request",       # serve/service.py — request accepted
    "serve_request_done",  # serve/service.py — request completed/failed
    "serve_requeued",      # serve/queue.py — interrupted job requeued
    "serve_gc",            # serve/pressure.py — budget pass ran
    "store_regret",        # store/heat.py — recently-evicted plan re-read
                           # or rebuilt (cache undersizing)
    "store_promote",       # store/tiers.py — object moved toward hot
    "store_demote",        # store/tiers.py — object moved toward cold
    "serve_drain",         # serve/service.py — replica drain state flipped
    "serve_lease_stolen",  # serve/queue.py — dead/expired lease reclaimed
    "serve_lease_lost",    # serve/queue.py — heartbeat found its lease gone
    "serve_settle_fenced",     # serve/queue.py — stale-epoch settle refused
    "serve_claim_reverted",    # serve/queue.py — mid-claim disk error undone
    "serve_quarantined",   # serve/queue.py — permanent failure parked
    "serve_src_poisoned",  # serve/queue.py — SRC digest quarantined fleet-wide
    "serve_admission_rejected",  # serve/cost.py — over-budget POST refused
    "serve_wave",          # serve/scheduler.py — one wave dispatched
    "priors_extract",      # priors/model.py — one extraction pass finished
    "media_fault_injected",    # io/faults.py — PC_MEDIA_FAULTS clause fired
    "media_deadline_expired",  # io/faults.py — native crossing abandoned
    "fused_member_degraded",   # models/fused.py — member dropped mid-stream
    "mesh_wave",       # parallel/meshobs.py — one wave-step dispatched,
                       # with its valid/pad slot breakdown
    "mesh_compile",    # parallel/meshobs.py — first dispatch of a step:
                       # one compile-ledger entry with its geometry
    "dist_init",       # parallel/distributed.py — jax.distributed joined
    "dist_collective", # parallel/distributed.py — one cross-process
                       # collective with its payload bytes
    "alert_fired",     # telemetry/alerts.py — a burn-rate rule tripped
    "alert_resolved",  # telemetry/alerts.py — a firing rule's condition
                       # cleared
    "scale_signal",    # serve/autoscale.py — a desired-replica
                       # recommendation was (re)graded

    "log",             # WARNING+ console records bridged into the log
})

# --------------------------------------------------------------- SLOs
#
# Declared latency bands for the serve fleet, per SLO phase and
# priority class (seconds). The phases map onto the three histograms
# above: queue_wait_s (enqueue/requeue → claim), execution_s (claim →
# settle), e2e_s (request submit → done). The fleet view
# (telemetry/fleet.py, /fleet, tools fleet-top) grades every
# (tenant × priority) flow against these: a flow is "ok" when at least
# SLO_TARGET_FRACTION of its observations fall inside the band.
# Declared HERE — next to the metric names — so the bands are one
# auditable contract, not per-dashboard folklore; tools serve-soak and
# serve-chaos read the same declaration.

#: phase -> {priority class -> band, seconds}
SLO_BANDS: dict[str, dict[str, float]] = {
    "queue_wait_s": {"interactive": 2.5, "normal": 30.0, "bulk": 300.0},
    "execution_s": {"interactive": 30.0, "normal": 120.0, "bulk": 600.0},
    "e2e_s": {"interactive": 60.0, "normal": 300.0, "bulk": 1800.0},
}

#: a flow meets its SLO when this fraction of observations is in-band
SLO_TARGET_FRACTION = 0.99

#: bucket layout of the three SLO phase histograms: the default latency
#: buckets extended PAST every band above. Load-bearing: the fleet
#: view grades bands from cumulative bucket counts, and a band beyond
#: the largest finite bucket could never report a breach (every
#: observation would sit "inside" the +Inf bucket). A test pins
#: max(band) <= max(finite bucket).
SLO_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0, 600.0, 1800.0, 3600.0,
)

# ------------------------------------------------------ read-path SLOs
#
# The artifact read path (/v1/artifacts, docs/SERVE.md) is graded per
# (tenant × SIZE class), not priority class: a 300 MiB render and a
# 200 KiB thumbnail cannot share a latency band, and the reader does
# not send a priority. Two phases: read_ttfb_s (request → first body
# byte; what an edge cache feels) and read_s (request → last byte).
# A 304 answer observes TTFB only — there is no stream to time.

#: artifact size (bytes, exclusive upper bound; None = unbounded)
#: -> size-class label, checked in order
READ_SIZE_CLASSES: tuple = (
    (1 << 20, "lt1m"),
    (16 << 20, "lt16m"),
    (256 << 20, "lt256m"),
    (None, "ge256m"),
)


def read_size_class(nbytes: int) -> str:
    """The size-class label of one artifact's byte count."""
    for bound, label in READ_SIZE_CLASSES:
        if bound is None or nbytes < bound:
            return label
    return READ_SIZE_CLASSES[-1][1]


#: read phase -> {size class -> band, seconds}
READ_SLO_BANDS: dict[str, dict[str, float]] = {
    "read_ttfb_s": {"lt1m": 0.05, "lt16m": 0.1, "lt256m": 0.25,
                    "ge256m": 0.5},
    "read_s": {"lt1m": 0.25, "lt16m": 2.5, "lt256m": 30.0,
               "ge256m": 120.0},
}

#: bucket layout of the two read histograms: sub-millisecond floor
#: (a warm 304 answers in microseconds; SLO_LATENCY_BUCKETS' 5 ms
#: floor would flatten the whole TTFB distribution into one bucket)
#: and, as above, extended past every READ_SLO_BANDS band so a breach
#: is always representable. The same test pins max(band) <=
#: max(finite bucket).
READ_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# ---------------------------------------------------------- alert rules
#
# The burn-rate engine (telemetry/alerts.py) evaluates every rule below
# against the fleet-merged view; firing/resolved transitions are durable
# journal records and surface at /fleet/alerts. Declared HERE — next to
# the SLO bands they grade — so the alerting contract is the same
# auditable artifact as the bands themselves; chainlint's
# telemetry-name rule drift-checks every rule name against
# docs/TELEMETRY.md both ways (doc tokens spell them `alert:<name>`).

#: multi-window multi-burn-rate pairs (SRE shape): a pair trips only
#: when BOTH its short and long windows burn error budget faster than
#: `burn_rate` × the steady rate that would exactly exhaust the budget.
#: The fast pair pages on sudden total breaches within minutes; the
#: slow pair catches sustained low-grade burns the fast pair's short
#: memory forgives. Window seconds scale uniformly via the engine's
#: `window_scale` (soak harnesses compress hours into seconds).
BURN_RATE_WINDOWS: dict[str, dict[str, float]] = {
    "fast": {"short_s": 300.0, "long_s": 3600.0, "burn_rate": 14.4},
    "slow": {"short_s": 1800.0, "long_s": 21600.0, "burn_rate": 6.0},
}

#: alert rule name -> declaration. `source` picks the fleet-view plane
#: the rule reads; burn rules grade one SLO phase per (tenant × class)
#: flow, cross-plane rules watch the other flight recorders. Severity
#: is advisory routing ("page" vs "ticket"), not engine behaviour.
ALERT_RULES: dict[str, dict] = {
    # SLO burn over the fleet-merged request-phase histograms
    "slo_burn_queue_wait": {"source": "slo", "phase": "queue_wait_s",
                            "severity": "page"},
    "slo_burn_execution": {"source": "slo", "phase": "execution_s",
                           "severity": "page"},
    "slo_burn_e2e": {"source": "slo", "phase": "e2e_s",
                     "severity": "page"},
    # SLO burn over the artifact read path (TTFB / full stream)
    "slo_burn_read_ttfb": {"source": "read_slo", "phase": "read_ttfb_s",
                           "severity": "page"},
    "slo_burn_read_stream": {"source": "read_slo", "phase": "read_s",
                             "severity": "ticket"},
    # cross-plane: watchdog stall episodes (telemetry/watchdog.py)
    "watchdog_task_stalled": {"source": "stalls", "incident": "stalled",
                              "severity": "ticket"},
    "watchdog_hard_timeout": {"source": "stalls",
                              "incident": "hard_timeout",
                              "severity": "page"},
    # cross-plane: eviction-regret records (store/heat.py) — the cache
    # is undersized while regrets accrue inside the fast short window
    "store_eviction_regret": {"source": "heat", "severity": "ticket",
                              "min_regrets": 1},
    # cross-plane: device-plane fragmentation (parallel/meshobs.py) —
    # any geometry bucket wasting more than the fragmentation threshold
    # over at least `min_waves` waves
    "mesh_waste_high": {"source": "mesh", "severity": "ticket",
                        "min_waves": 3},
    # cross-plane: a replica whose serve-info exists but whose process
    # stopped answering — "gone", as opposed to merely quiet
    "fleet_replica_stale": {"source": "replicas", "severity": "page",
                            "stale_after_s": 30.0},
}
