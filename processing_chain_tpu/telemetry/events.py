"""Structured run-event log: an append-only list of JSON records.

One record per interesting state transition — run start/end, stage
start/end, per-job planned/start/end/skip/redo/fail, prefetch queue
samples, device compile timings — written out by `--telemetry DIR` as
events_<ts>.jsonl and consumed by tools/run_report.py.

Same enablement contract as the metrics registry: `emit()` starts with
one attribute check and allocates nothing while telemetry is off, so the
call can sit on hot-ish paths unguarded (per-chunk, per-job — never
per-frame).

Records may carry OPTIONAL distributed-tracing fields (docs/TELEMETRY.md
"Fleet observability & tracing"): `trace_id` (the request's trace
context — serve request events carry it; job events carry the first of
their trace ids plus `trace_ids` when one execution answers several)
and `request_ids` (every request a job event answers). Emit sites add
them where the context exists; consumers treat absence as "not
serve-originated", never as an error — batch-chain events predate the
serve layer and stay valid without them.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Optional
from ..utils import lockdebug


class EventLog:
    """Thread-safe, in-memory, bounded event recorder.

    The cap exists so a pathological emitter (e.g. a queue-depth sampler
    on a week-long run) degrades to dropped samples + a drop counter,
    never to unbounded host memory; `drops` is exported in the tail
    record so a report can say the log is partial.
    """

    def __init__(self, max_events: int = 200_000) -> None:
        self._lock = lockdebug.make_lock("events")
        self._events: list[dict] = []  # guarded-by: _lock
        self.max_events = max_events
        self.drops = 0  # guarded-by: _lock
        self.enabled = False
        self._t0 = time.time()
        self._t0_perf = time.perf_counter()
        self._stream = None  # guarded-by: _lock

    def open_stream(self, path: str) -> str:
        """Additionally append every record to `path` AS IT IS EMITTED,
        so a run that crashes or hangs still leaves its event history on
        disk for forensics (tools run-report renders such a file as a
        partial run). write_jsonl to the same path at run end replaces
        the stream with the canonical complete file."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        # chainlint: disable=atomic-write (live forensics stream: appended per record while the run is alive; read_jsonl tolerates a torn tail, and write_jsonl atomically replaces it with the canonical file at exit)
        f = open(path, "w")
        f.write(json.dumps({
            "event": "log_meta", "t": 0.0,
            "epoch_t0": round(self._t0, 3), "streaming": True,
        }) + "\n")
        f.flush()
        with self._lock:
            old, self._stream = self._stream, f
        if old is not None:
            old.close()
        return path

    def close_stream(self) -> None:
        with self._lock:
            stream, self._stream = self._stream, None
        if stream is not None:
            try:
                stream.close()
            except OSError:
                pass

    def emit(self, event: str, **fields) -> None:
        if not self.enabled:
            return
        record = {
            "t": round(time.perf_counter() - self._t0_perf, 6),
            "event": event,
        }
        record.update(fields)
        with self._lock:
            if self._stream is not None:
                try:
                    # flushed per record, and BEFORE the memory-cap check:
                    # the stream is disk-backed forensics for runs that
                    # never reach an orderly shutdown, so a week-long run
                    # that overflowed the in-memory log must still record
                    # its tail (watchdog stalls, the crash) on disk
                    self._stream.write(json.dumps(record) + "\n")
                    self._stream.flush()
                except (OSError, TypeError, ValueError):
                    pass  # forensics stream must never break the run
            if len(self._events) >= self.max_events:
                self.drops += 1
                return
            self._events.append(record)

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        self.close_stream()
        with self._lock:
            self._events.clear()
            self.drops = 0
        self._t0 = time.time()
        self._t0_perf = time.perf_counter()

    def write_jsonl(self, path: str) -> str:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with self._lock:
            events = list(self._events)
            drops = self.drops
            t0 = self._t0
            # the canonical end-of-run file replaces any live stream to
            # the same path; close first so the rewrite wins on Windows
            # semantics too, not only via POSIX last-writer
            stream, self._stream = self._stream, None
        if stream is not None:
            try:
                stream.close()
            except OSError:
                pass
        from ..utils.fsio import atomic_write

        def _write(tmp: str) -> None:
            with open(tmp, "w") as f:
                f.write(json.dumps({
                    "event": "log_meta", "t": 0.0, "epoch_t0": round(t0, 3),
                    "n_events": len(events), "dropped": drops,
                }) + "\n")
                for record in events:
                    f.write(json.dumps(record) + "\n")

        atomic_write(path, _write)
        return path


def read_jsonl(path: str) -> list[dict]:
    """Inverse of write_jsonl (used by tools/run_report.py); tolerates a
    truncated final line from an interrupted writer."""
    out: list[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                break
    return out


EVENTS = EventLog()


def emit(event: str, **fields) -> None:
    EVENTS.emit(event, **fields)


class EventLogHandler(logging.Handler):
    """Bridges WARNING+ chain log records into the event log, so the
    structured record of a run carries the same anomalies the console
    showed (skip-existing warnings, degraded-path notices, errors).

    Runs as a SECOND handler on the "main" logger next to the ANSI
    console handler — which is why `_ColorFormatter` must not mutate
    `record.levelname` in place (utils/log.py): the escaped name would
    leak into these structured records depending on handler order.
    """

    def __init__(self, log: Optional[EventLog] = None) -> None:
        super().__init__(level=logging.WARNING)
        self._log = log or EVENTS

    def emit(self, record: logging.LogRecord) -> None:  # noqa: A003
        try:
            self._log.emit(
                "log",
                level=record.levelname,
                message=record.getMessage(),
            )
        except Exception:  # pragma: no cover - logging must never raise
            self.handleError(record)


def attach_log_handler(logger: logging.Logger) -> EventLogHandler:
    """Install (idempotently) the event-log bridge on `logger`."""
    for h in logger.handlers:
        if isinstance(h, EventLogHandler):
            return h
    handler = EventLogHandler()
    logger.addHandler(handler)
    return handler


def detach_log_handler(logger: logging.Logger) -> None:
    for h in list(logger.handlers):
        if isinstance(h, EventLogHandler):
            logger.removeHandler(h)
