"""Fleet-wide observability: replica discovery, SLO merge, trace assembly.

One chain-serve root can be served by any number of replica processes
(docs/SERVE.md "Running multiple replicas"), and before this module
each of them answered /status and /metrics only for ITSELF — nobody
could say "what is the fleet doing" or "what happened to request X"
without ssh'ing into every process. The collector here builds one
merged view from three sources that all outlive any single replica:

  * **serve-info files** — every replica writes `{url, replica, pid,
    replica_epoch}` at startup; `discover_replicas` scans the root for
    them and probes each /status + /metrics, marking dead ones instead
    of failing (a fleet view with one dead replica renders partial
    data, it does not crash).
  * **the shared durable state** — queue records and request docs under
    the root are the fleet's ground truth regardless of who is alive;
    counts come from disk, not from any replica's memory.
  * **the span journal** (serve/spans.py) — the per-replica transition
    history, merged into cross-replica request traces by
    `assemble_trace`, with the gapless-chain completeness check.

The SLO layer: each replica's /metrics carries the per-(tenant ×
priority-class) phase histograms (`chain_serve_queue_wait_seconds`,
`chain_serve_execution_seconds`, `chain_serve_e2e_seconds`);
`merge_histograms` sums them bucket-wise across replicas (cumulative
bucket counts sum to cumulative bucket counts — no rebinning), and
`slo_report` grades every flow against the declared bands in
`telemetry/catalog.SLO_BANDS`: estimated p50/p95/p99 plus the fraction
of observations inside the band.

Served as `/fleet` on every replica's LiveServer, rendered by `tools
fleet-top`, and consumed by `tools trace show` (the cross-replica
timeline, Chrome-trace export via profiling.build_chrome_trace).
"""

from __future__ import annotations

import json
import os
import re
import time
import urllib.error
import urllib.request
from typing import Iterable, Optional

from ..serve import spans as serve_spans
from ..store import heat as store_heat
from . import alerts as alerts_mod
from . import catalog

#: SLO phase -> the metric whose histogram measures it
PHASE_METRICS = {
    "queue_wait_s": "chain_serve_queue_wait_seconds",
    "execution_s": "chain_serve_execution_seconds",
    "e2e_s": "chain_serve_e2e_seconds",
}

#: read-path SLO phase -> metric (per tenant × size class; graded
#: against catalog.READ_SLO_BANDS — docs/STORE.md "Access heat &
#: eviction forensics")
READ_PHASE_METRICS = {
    "read_ttfb_s": "chain_serve_read_ttfb_seconds",
    "read_s": "chain_serve_read_seconds",
}

#: per-tenant cost-accounting counters merged into the /fleet "cost"
#: section (serve/cost.py; docs/SERVE.md "Cost-aware scheduling &
#: admission")
COST_COUNTERS = (
    "chain_serve_cost_predicted_seconds_total",
    "chain_serve_cost_observed_seconds_total",
    "chain_serve_cost_rejected_total",
)
#: per-tier placement metrics merged into the /fleet "store_tiers"
#: section (store/tiers.py; docs/STORE.md "Tier hierarchy")
TIER_METRICS = (
    "chain_store_tier_hits_total",
    "chain_store_tier_promotions_total",
    "chain_store_tier_demotions_total",
    "chain_store_tier_bytes",
)

#: device-plane wave counters (parallel/meshobs.py) — all cumulative
#: per-replica event counts, so the fleet merge is a plain sum
MESH_METRICS = (
    "chain_mesh_waves_total",
    "chain_mesh_wave_slots_total",
    "chain_mesh_recompiles_total",
    "chain_mesh_compile_seconds_total",
)
#: the observed/predicted audit histogram (same section)
COST_ERROR_METRIC = "chain_serve_cost_error_ratio"

#: percentiles the SLO report estimates from the merged buckets
PERCENTILES = (0.50, 0.95, 0.99)


# ------------------------------------------------------------ discovery


def discover_replicas(root: str) -> list[dict]:
    """Every serve-info document under `root` (top level only): any
    JSON file carrying both `url` and `replica` counts — the default
    `serve-info.json` and per-replica `--info-file`s alike. Stale files
    from dead generations stay listed (the probe marks them dead);
    replicas that re-registered under the same id keep only the
    newest file's claim."""
    infos: dict[str, dict] = {}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(root, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "url" not in doc \
                or "replica" not in doc:
            continue
        doc["info_file"] = name
        try:
            doc["info_mtime"] = os.stat(path).st_mtime
        except OSError:
            doc["info_mtime"] = 0.0
        prev = infos.get(doc["replica"])
        if prev is None or doc["info_mtime"] >= prev["info_mtime"]:
            infos[doc["replica"]] = doc
    return sorted(infos.values(), key=lambda d: d["replica"])


def _fetch(url: str, timeout_s: float) -> Optional[bytes]:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.read()
    except (urllib.error.URLError, TimeoutError, OSError, ValueError):
        return None


# ----------------------------------------------------- prometheus parse

_PROM_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[^\s]+)$"
)
_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _prom_samples(text: str) -> Iterable[tuple]:
    """(name, labels, value) per sample line of one /metrics render —
    the ONE place the line grammar, label unescaping and value parsing
    live; parse_histograms and parse_counters both consume it (an
    escaping fix must not have to land twice)."""
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line.strip())
        if m is None:
            continue
        labels = {k: v.replace('\\"', '"').replace("\\\\", "\\")
                  for k, v in _LABEL.findall(m.group("labels") or "")}
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        yield m.group("name"), labels, value


def parse_histograms(text: str, names: Iterable[str]) -> dict:
    """The named histograms out of one /metrics render. Returns
    {(name, labelitems): {"labels", "buckets" (cumulative, by le
    string), "sum", "count"}} where labelitems is the sorted tuple of
    (label, value) pairs excluding `le`."""
    wanted = set(names)
    out: dict = {}

    def entry(name: str, labels: dict) -> dict:
        key = (name, tuple(sorted(labels.items())))
        return out.setdefault(key, {
            "labels": labels, "buckets": {}, "sum": 0.0, "count": 0,
        })

    for name, labels, value in _prom_samples(text):
        base, _, suffix = name.rpartition("_")
        if base not in wanted or suffix not in ("bucket", "sum", "count"):
            continue
        if suffix == "bucket":
            le = labels.pop("le", "+Inf")
            entry(base, labels)["buckets"][le] = value
        elif suffix == "sum":
            entry(base, labels)["sum"] += value
        else:
            entry(base, labels)["count"] += int(value)
    return out


def parse_counters(text: str, names: Iterable[str]) -> dict:
    """The named counters (or gauges) out of one /metrics render:
    {(name, labelitems): {"labels", "value"}} — the counter sibling of
    `parse_histograms`, for the cost-accounting merge."""
    wanted = set(names)
    out: dict = {}
    for name, labels, value in _prom_samples(text):
        if name not in wanted:
            continue
        key = (name, tuple(sorted(labels.items())))
        entry = out.setdefault(key, {"labels": labels, "value": 0.0})
        entry["value"] += value
    return out


def merge_counters(parsed: Iterable[dict]) -> dict:
    """Sum per-replica counter parses (cumulative counts sum exactly,
    like the histogram merge)."""
    merged: dict = {}
    for one in parsed:
        for key, series in one.items():
            into = merged.setdefault(key, {
                "labels": dict(series["labels"]), "value": 0.0,
            })
            into["value"] += series["value"]
    return merged


def tier_report(parsed: list) -> dict:
    """The /fleet "store_tiers" section from each replica's tier
    metrics: per-tier hit counts merged by SUM (every replica's reads
    are distinct events) with fleet-wide hit ratios, promotion/demotion
    move counts likewise, and per-tier bytes merged by MAX — the gauge
    reports SHARED store state, so summing replicas would multiply one
    disk by the fleet size."""
    tiers: dict = {}
    for counters in parsed:
        for (name, _), entry in counters.items():
            tier = entry["labels"].get("tier", "?")
            t = tiers.setdefault(tier, {
                "hits": 0, "promotions": 0, "demotions": 0, "bytes": 0,
            })
            value = entry["value"]
            if name == "chain_store_tier_hits_total":
                t["hits"] += int(value)
            elif name == "chain_store_tier_promotions_total":
                t["promotions"] += int(value)
            elif name == "chain_store_tier_demotions_total":
                t["demotions"] += int(value)
            elif name == "chain_store_tier_bytes":
                t["bytes"] = max(t["bytes"], int(value))
    total_hits = sum(t["hits"] for t in tiers.values())
    for t in tiers.values():
        t["hit_ratio"] = (
            round(t["hits"] / total_hits, 4) if total_hits else 0.0)
    return {"tiers": tiers, "hits_total": total_hits}


def mesh_report(parsed: list) -> dict:
    """The /fleet "mesh" section from each replica's chain_mesh_*
    counters (parallel/meshobs.py): per geometry bucket, fleet-summed
    wave counts, the valid/pad slot split with the derived waste
    fraction, and the compile ledger (every replica compiles its own
    steps, so recompiles sum too). Empty buckets dict when no replica
    has dispatched a wave."""
    buckets: dict = {}
    for counters in parsed:
        for (name, _), entry in counters.items():
            bucket = entry["labels"].get("bucket", "?")
            b = buckets.setdefault(bucket, {
                "waves": 0, "valid": 0, "padded": 0,
                "recompiles": 0, "compile_s": 0.0,
            })
            value = entry["value"]
            if name == "chain_mesh_waves_total":
                b["waves"] += int(value)
            elif name == "chain_mesh_wave_slots_total":
                if entry["labels"].get("kind") == "valid":
                    b["valid"] += int(value)
                else:
                    b["padded"] += int(value)
            elif name == "chain_mesh_recompiles_total":
                b["recompiles"] += int(value)
            elif name == "chain_mesh_compile_seconds_total":
                b["compile_s"] = round(b["compile_s"] + value, 4)
    for b in buckets.values():
        total = b["valid"] + b["padded"]
        b["waste_fraction"] = (
            round(b["padded"] / total, 4) if total else 0.0)
    return {
        "buckets": buckets,
        "waves": sum(b["waves"] for b in buckets.values()),
        "recompiles": sum(b["recompiles"] for b in buckets.values()),
    }


def cost_report(counters: dict, error_hist: dict) -> dict:
    """The /fleet "cost" section from merged counters + the merged
    observed/predicted ratio histogram: per-tenant predicted/observed
    seconds, admission refusals by reason, and the model-error
    estimate. Empty sub-dicts when the fleet has no cost traffic."""
    tenants: dict = {}
    rejected: dict = {}
    for (name, _), series in sorted(counters.items()):
        if name == "chain_serve_cost_rejected_total":
            reason = series["labels"].get("reason", "?")
            rejected[reason] = rejected.get(reason, 0) \
                + int(series["value"])
            continue
        tenant = series["labels"].get("tenant", "")
        entry = tenants.setdefault(
            tenant, {"predicted_s": 0.0, "observed_s": 0.0}
        )
        if name == "chain_serve_cost_predicted_seconds_total":
            entry["predicted_s"] = round(
                entry["predicted_s"] + series["value"], 3)
        elif name == "chain_serve_cost_observed_seconds_total":
            entry["observed_s"] = round(
                entry["observed_s"] + series["value"], 3)
    error: Optional[dict] = None
    for (name, _), series in error_hist.items():
        if name != COST_ERROR_METRIC or not series["count"]:
            continue
        error = {
            "n": series["count"],
            "ratio_p50": percentile_from_buckets(series["buckets"], 0.50),
            "ratio_p95": percentile_from_buckets(series["buckets"], 0.95),
        }
    return {"tenants": tenants, "rejected": rejected,
            "model_error": error}


def merge_histograms(parsed: Iterable[dict]) -> dict:
    """Sum per-replica histogram parses (same shape in and out).
    Cumulative bucket counts sum to cumulative bucket counts, so no
    rebinning is needed — the replicas share one bucket layout by
    construction (the registry's defaults)."""
    merged: dict = {}
    for one in parsed:
        for key, series in one.items():
            into = merged.setdefault(key, {
                "labels": dict(series["labels"]),
                "buckets": {}, "sum": 0.0, "count": 0,
            })
            for le, c in series["buckets"].items():
                into["buckets"][le] = into["buckets"].get(le, 0.0) + c
            into["sum"] += series["sum"]
            into["count"] += series["count"]
    return merged


def percentile_exact(values: list, frac: float) -> Optional[float]:
    """Order-statistic percentile over RAW samples — the one formula
    the soak/chaos harnesses share (`percentile_from_buckets` below is
    the merged-histogram estimate; two private copies of this already
    drifted once). None on empty input."""
    if not values:
        return None
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(len(ordered) * frac))]


def _le_key(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def percentile_from_buckets(buckets: dict, frac: float) -> Optional[float]:
    """Upper-bound estimate of one quantile from cumulative bucket
    counts: the smallest bucket bound whose cumulative count covers
    `frac` of the observations. None when the histogram is empty; the
    largest FINITE bound stands in for +Inf (the estimate is then a
    floor, which is the honest direction for an SLO breach check)."""
    if not buckets:
        return None
    ordered = sorted(buckets.items(), key=lambda kv: _le_key(kv[0]))
    total = ordered[-1][1]
    if total <= 0:
        return None
    target = frac * total
    finite = [le for le, _ in ordered if le != "+Inf"]
    for le, cum in ordered:
        if cum >= target:
            if le == "+Inf":
                return _le_key(finite[-1]) if finite else None
            return _le_key(le)
    return _le_key(finite[-1]) if finite else None


def band_fraction(buckets: dict, band_s: float) -> Optional[float]:
    """Fraction of observations at or under `band_s`, estimated from
    the cumulative count of the first bucket bound ≥ the band (an
    over-estimate by at most one bucket width — documented next to the
    SLO tables)."""
    if not buckets:
        return None
    ordered = sorted(buckets.items(), key=lambda kv: _le_key(kv[0]))
    total = ordered[-1][1]
    if total <= 0:
        return None
    for le, cum in ordered:
        if _le_key(le) >= band_s:
            return cum / total
    return 1.0


def slo_report(merged: dict) -> dict:
    """Grade the merged phase histograms against catalog.SLO_BANDS.
    Returns {tenant: {priority: {phase: {count, p50, p95, p99, band_s,
    within_band, ok}}}} — `ok` is None when no band is declared for
    the flow's priority class."""
    report: dict = {}
    for (name, _), series in sorted(merged.items()):
        phase = next(
            (p for p, metric in PHASE_METRICS.items() if metric == name),
            None,
        )
        if phase is None:
            continue
        labels = series["labels"]
        tenant = labels.get("tenant", "")
        priority = labels.get("priority", "")
        cell: dict = {"count": series["count"]}
        for frac in PERCENTILES:
            est = percentile_from_buckets(series["buckets"], frac)
            cell[f"p{int(frac * 100)}"] = \
                round(est, 6) if est is not None else None
        band_s = catalog.SLO_BANDS.get(phase, {}).get(priority)
        cell["band_s"] = band_s
        if band_s is None:
            cell["within_band"] = None
            cell["ok"] = None
        else:
            within = band_fraction(series["buckets"], band_s)
            cell["within_band"] = \
                round(within, 4) if within is not None else None
            cell["ok"] = (
                None if within is None
                else within >= catalog.SLO_TARGET_FRACTION
            )
        report.setdefault(tenant, {}).setdefault(priority, {})[phase] = cell
    return report


def read_slo_report(merged: dict) -> dict:
    """slo_report's read-path sibling: grade the merged artifact-read
    histograms against catalog.READ_SLO_BANDS. Returns {tenant:
    {size_class: {phase: cell}}} with the same cell shape, so the
    fleet-top renderer formats both reports through one code path."""
    report: dict = {}
    for (name, _), series in sorted(merged.items()):
        phase = next(
            (p for p, metric in READ_PHASE_METRICS.items()
             if metric == name),
            None,
        )
        if phase is None:
            continue
        labels = series["labels"]
        tenant = labels.get("tenant", "")
        size_class = labels.get("size_class", "")
        cell: dict = {"count": series["count"]}
        for frac in PERCENTILES:
            est = percentile_from_buckets(series["buckets"], frac)
            cell[f"p{int(frac * 100)}"] = \
                round(est, 6) if est is not None else None
        band_s = catalog.READ_SLO_BANDS.get(phase, {}).get(size_class)
        cell["band_s"] = band_s
        if band_s is None:
            cell["within_band"] = None
            cell["ok"] = None
        else:
            within = band_fraction(series["buckets"], band_s)
            cell["within_band"] = \
                round(within, 4) if within is not None else None
            cell["ok"] = (
                None if within is None
                else within >= catalog.SLO_TARGET_FRACTION
            )
        report.setdefault(tenant, {}).setdefault(
            size_class, {})[phase] = cell
    return report


# ------------------------------------------------------- durable truth


def _counts_from_dir(path: str, state_key: str) -> dict:
    counts: dict = {}
    try:
        names = os.listdir(path)
    except OSError:
        return counts
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(path, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        state = doc.get(state_key, "?")
        counts[state] = counts.get(state, 0) + 1
    return counts


def queue_counts(root: str) -> dict:
    return _counts_from_dir(os.path.join(root, "queue", "jobs"), "state")


def request_counts(root: str) -> dict:
    return _counts_from_dir(os.path.join(root, "requests"), "state")


# ----------------------------------------------------------- fleet view


def fleet_view(root: str, timeout_s: float = 2.0) -> dict:
    """The merged fleet document `/fleet` serves and `tools fleet-top`
    renders. Probes every discovered replica; dead ones are reported
    with `alive: false` and the rest of the view still builds from the
    shared durable state."""
    root = os.path.abspath(root)
    replicas: list[dict] = []
    parsed: list[dict] = []
    parsed_counters: list[dict] = []
    parsed_tiers: list[dict] = []
    parsed_mesh: list[dict] = []
    infos = discover_replicas(root)
    for info in infos:
        entry = {
            "replica": info.get("replica"),
            "replica_epoch": info.get("replica_epoch"),
            "pid": info.get("pid"),
            "url": info.get("url"),
            "info_file": info.get("info_file"),
            "alive": False,
        }
        raw = _fetch(info["url"].rstrip("/") + "/status", timeout_s)
        if raw is not None:
            try:
                status = json.loads(raw.decode())
            except ValueError:
                status = None
            if status is not None:
                entry["alive"] = True
                entry["status"] = "ok"
                serve = status.get("serve", {})
                entry["replica_epoch"] = serve.get(
                    "replica_epoch", entry["replica_epoch"])
                entry["pid"] = serve.get("pid", entry["pid"])
                entry["queue"] = serve.get("queue", {})
                entry["requests"] = serve.get("requests", {})
                entry["executor"] = serve.get("executor")
                entry["uptime_s"] = status.get("uptime_s")
                entry["stalls"] = serve.get("stalls") or []
                entry["cost_calibration"] = (
                    serve.get("cost") or {}
                ).get("calibration")
                rss = (status.get("resources") or {}).get("rss_bytes")
                if rss:
                    entry["rss_bytes"] = rss
        if entry["alive"]:
            text = _fetch(info["url"].rstrip("/") + "/metrics", timeout_s)
            if text is not None:
                rendered = text.decode(errors="replace")
                parsed.append(parse_histograms(
                    rendered,
                    [*PHASE_METRICS.values(),
                     *READ_PHASE_METRICS.values(), COST_ERROR_METRIC],
                ))
                parsed_counters.append(
                    parse_counters(rendered, COST_COUNTERS)
                )
                parsed_tiers.append(
                    parse_counters(rendered, TIER_METRICS)
                )
                parsed_mesh.append(
                    parse_counters(rendered, MESH_METRICS)
                )
        else:
            # a journal directory that exists while its process stopped
            # answering is not "silently absent" — it is STALE, graded
            # with its last-seen age (the serve-info's mtime) so
            # alerting can tell "quiet" from "gone"
            # (catalog.ALERT_RULES fleet_replica_stale)
            entry["error"] = "unreachable"
            entry["status"] = "stale"
            mtime = info.get("info_mtime")
            if mtime:
                entry["last_seen_s"] = round(
                    max(0.0, time.time() - float(mtime)), 1)
        replicas.append(entry)
    merged_hists = merge_histograms(parsed)
    # the store root each replica declared in its serve-info (the serve
    # daemon may be pointed at a shared store outside the serve root);
    # newest registration wins, default to the conventional layout
    store_root = os.path.join(root, "store")
    for info in sorted(infos, key=lambda d: d.get("info_mtime", 0.0)):
        if info.get("store"):
            store_root = info["store"]
    return {
        "schema": 1,
        "generated_at": round(time.time(), 3),
        "root": root,
        "replicas": replicas,
        "alive": sum(1 for r in replicas if r["alive"]),
        "queue": queue_counts(root),
        "requests": request_counts(root),
        "slo": slo_report(merged_hists),
        "slo_bands": catalog.SLO_BANDS,
        # artifact read-path grades per (tenant × size class) — the
        # TTFB/full-stream histograms of serve/service.py's
        # /v1/artifacts handler, merged like the phase histograms
        "read_slo": read_slo_report(merged_hists),
        "read_slo_bands": catalog.READ_SLO_BANDS,
        # tail-sampled heat-ledger summary (store/heat.py): read/304/
        # regret/eviction counts over the fleet's journals
        "heat": store_heat.journal_stats(store_heat.heat_dir(store_root)),
        # per-tier placement: fleet-merged hit counts/ratios and move
        # totals (store/tiers.py; docs/STORE.md "Tier hierarchy") —
        # empty tiers dict for single-tier fleets
        "store_tiers": tier_report(parsed_tiers),
        # device-plane wave occupancy/waste/compile ledger, summed over
        # live replicas (parallel/meshobs.py; docs/PERF.md "My waves
        # are wasteful") — empty buckets dict until a wave dispatches
        "mesh": mesh_report(parsed_mesh),
        # per-tenant predicted/observed seconds + admission refusals,
        # merged across replicas (serve/cost.py)
        "cost": {
            **cost_report(merge_counters(parsed_counters), merged_hists),
            # per-replica prediction-scale calibration (serve/cost.py
            # --cost-calibrate): per host, never merged — each replica
            # runs its own hardware
            "calibration": {
                r["replica"]: r["cost_calibration"]
                for r in replicas if r.get("cost_calibration")
            },
        },
        # tail-sampled on purpose: the journals are unbounded
        # append-only history and /fleet refreshes every few seconds
        "spans": serve_spans.journal_stats(
            os.path.join(root, "queue", "spans")),
        # active watchdog stall episodes, labelled with the replica
        # that reported each (telemetry/watchdog.py active_stalls —
        # satellite of the alerting plane: a stalled task is visible
        # fleet-wide, not just in its own process)
        "stalls": [
            {**stall, "replica": r["replica"]}
            for r in replicas for stall in r.get("stalls") or []
        ],
        # burn-rate alerts still firing (telemetry/alerts.py) — the
        # full lifecycle lives at /fleet/alerts; this is the summary
        # fleet-top renders and the control loop's own engines read
        "alerts": {
            "active": alerts_mod.active_alerts(root),
            "journal": alerts_mod.journal_stats(
                alerts_mod.alerts_dir(root)),
        },
        # the newest journaled autoscale recommendation
        # (serve/autoscale.py; live signal at /fleet/scale-signal)
        "scale": alerts_mod.latest_scale(root),
    }


# -------------------------------------------------------- trace stitch


def _load_request_doc(root: str, request_id: str) -> Optional[dict]:
    path = os.path.join(root, "requests", request_id + ".json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def resolve_request_ids(root: str, ref: str) -> list[str]:
    """`ref` may be a request id or a trace id; returns EVERY matching
    request id, submit-ordered. More than one is legitimate: a
    client-supplied gateway trace can ride several POSTs, and showing
    only an arbitrary one would claim 'COMPLETE' while hiding the
    rest — the trace of a shared id is all of its requests."""
    if _load_request_doc(root, ref) is not None:
        return [ref]
    req_dir = os.path.join(root, "requests")
    try:
        names = os.listdir(req_dir)
    except OSError:
        return []
    matches: list[tuple] = []
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(req_dir, name)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("trace") == ref:
            matches.append((doc.get("created_at", 0.0),
                            doc.get("request")))
    return [req for _, req in sorted(matches) if req]


def assemble_trace(root: str, request_id: str) -> dict:
    """The cross-replica timeline of one request: its doc, every span
    that names it (merged over all replica journals), the per-job
    chains, and the gapless-completeness verdict for terminal jobs.
    Works from durable state only — no replica needs to be alive."""
    root = os.path.abspath(root)
    doc = _load_request_doc(root, request_id)
    all_spans = serve_spans.read_journals(
        os.path.join(root, "queue", "spans"))
    # which JOBS answer this request: any span naming it (enqueue,
    # attach, or a later transition carrying the merged request list)
    # OR a durable record listing it — then take each such job's FULL
    # chain. A singleflight attach joins a record mid-flight, so the
    # spans from before the join (its enqueue, an earlier claim) do
    # not name this request yet they ARE its history.
    job_ids = {s.get("job", "?")
               for s in serve_spans.spans_for_request(all_spans,
                                                      request_id)}
    records: dict[str, dict] = {}
    jobs_dir = os.path.join(root, "queue", "jobs")
    try:
        names = os.listdir(jobs_dir)
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".json"):
            continue  # lease sentinels (*.json.inprogress) included
        try:
            with open(os.path.join(jobs_dir, name)) as f:
                record = json.load(f)
        except (OSError, ValueError):
            continue
        if record.get("job") in job_ids or \
                request_id in (record.get("requests") or ()):
            records[record["job"]] = record
            job_ids.add(record["job"])
    jobs: dict[str, list] = {j: [] for j in job_ids}
    for span in all_spans:
        if span.get("job") in job_ids:
            jobs[span["job"]].append(span)
    for chain in jobs.values():
        chain.sort(key=lambda s: (s.get("ts", 0.0), s.get("seq", 0)))
    violations: list[str] = []
    for job_id, chain in sorted(jobs.items()):
        record = records.get(job_id)
        if record is not None:
            violations.extend(serve_spans.verify_chain(
                [s for s in chain if s.get("phase") != "fenced"], record))
    units: dict[str, dict] = {}
    warm_units = 0
    if doc:
        span_plans = {s.get("plan") for chain in jobs.values()
                      for s in chain}
        for pvs_id, unit in (doc.get("units") or {}).items():
            entry = {"plan": unit.get("plan")}
            if unit.get("plan") not in span_plans:
                # no queue traffic at all: answered warm at submit
                entry["warm"] = True
                warm_units += 1
            units[pvs_id] = entry
    t0 = min((s.get("ts", 0.0) for chain in jobs.values() for s in chain),
             default=(doc or {}).get("created_at", 0.0))
    return {
        "request": request_id,
        "trace": (doc or {}).get("trace"),
        "found": doc is not None or bool(jobs),
        "state": (doc or {}).get("state"),
        "tenant": (doc or {}).get("tenant"),
        "priority": (doc or {}).get("priority"),
        "created_at": (doc or {}).get("created_at"),
        "done_at": (doc or {}).get("done_at"),
        "latency_ms": (doc or {}).get("latency_ms"),
        "t0": t0,
        "units": units,
        "warm_units": warm_units,
        "jobs": jobs,
        "records": {j: {"state": r.get("state"),
                        "epoch": r.get("epoch"),
                        "settledEpoch": r.get("settledEpoch"),
                        "owner": r.get("owner"),
                        "unit": (r.get("unit") or {}).get("pvs_id")}
                    for j, r in records.items()},
        "complete": not violations,
        "violations": violations,
    }


class _TraceSpan:
    """profiling.build_chrome_trace's span shape (name/thread/start/
    duration/meta), synthesized from journal intervals."""

    __slots__ = ("name", "thread", "start", "duration", "meta")

    def __init__(self, name: str, thread: str, start: float,
                 duration: float, meta: Optional[dict] = None) -> None:
        self.name = name
        self.thread = thread
        self.start = start
        self.duration = duration
        self.meta = meta or {}


def chrome_trace(trace: dict) -> dict:
    """One request's stitched timeline as Chrome-trace JSON, through
    the SAME builder the profiler uses (telemetry/profiling.
    build_chrome_trace) so the clock/format conventions stay single-
    sourced. Replicas render as threads; claim→settle intervals are
    complete spans; enqueue/steal/fenced show as zero-width marks."""
    from .profiling import build_chrome_trace

    t0 = trace.get("t0", 0.0)
    spans: list[_TraceSpan] = []
    for job_id, chain in sorted(trace.get("jobs", {}).items()):
        unit = (trace.get("records", {}).get(job_id) or {}).get("unit") \
            or job_id
        open_claim: Optional[dict] = None
        for span in chain:
            ts = span.get("ts", t0) - t0
            phase = span.get("phase")
            replica = span.get("replica", "?")
            if phase == "claim":
                open_claim = span
                continue
            if phase in ("complete", "fail", "quarantine", "requeue",
                         "revert") and open_claim is not None:
                start = open_claim.get("ts", t0) - t0
                spans.append(_TraceSpan(
                    name=f"{unit} [e{span.get('epoch')}] {phase}",
                    thread=replica, start=start,
                    duration=max(1e-6, ts - start),
                    meta={"job": job_id, "phase": phase,
                          "epoch": span.get("epoch", 0)},
                ))
                open_claim = None
                continue
            spans.append(_TraceSpan(
                name=f"{unit} {phase}", thread=replica, start=ts,
                duration=1e-6,
                meta={"job": job_id, "phase": phase or "?",
                      "epoch": span.get("epoch", 0)},
            ))
        if open_claim is not None:
            # claim with no observed end: the owner died mid-wave and
            # nothing has stolen it yet — render the open interval
            start = open_claim.get("ts", t0) - t0
            spans.append(_TraceSpan(
                name=f"{unit} [e{open_claim.get('epoch')}] unsettled",
                thread=open_claim.get("replica", "?"), start=start,
                duration=1e-6,
                meta={"job": job_id, "phase": "claim-open",
                      "epoch": open_claim.get("epoch", 0)},
            ))
    doc = build_chrome_trace(spans)
    doc["otherData"] = {
        "producer": "processing_chain_tpu tools trace",
        "request": trace.get("request"),
        "trace": trace.get("trace"),
    }
    return doc
