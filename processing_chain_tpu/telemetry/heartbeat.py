"""Heartbeat registry: the live in-flight view of a running chain.

PR 1's metrics/events answer "what happened" after a run persists them;
this module answers "what is happening NOW". Every in-flight unit of
work — ParallelRunner tasks, engine Jobs, prefetch workers, jitted
device steps, the distributed barrier — registers a `Heartbeat` carrying
label / kind / start time / last-beat time / progress (units done ÷
planned). The watchdog (telemetry/watchdog.py) scans beat ages for
stalls, and the live endpoint / status file (telemetry/live.py) renders
the snapshot for operators.

Semantics that matter:

  * `beat()` means PROGRESS, not mere liveness. Waiting loops (the
    distributed barrier, a blocked queue put) deliberately do NOT beat
    while stuck, so their beat age grows and the watchdog can see them.
    Work loops beat once per unit (chunk, task, poll that advanced).
  * EWMA rate: each beat that advances units folds `d_units/d_t` into an
    exponentially-weighted moving rate, from which `eta_s` extrapolates
    remaining work. Per-stage ETA comes from the stage-level heartbeat
    `telemetry.stage_span` registers (units = jobs done / jobs planned).
  * Cancellation: the watchdog's hard timeout sets `cancelled`;
    cooperative loops call `check_cancelled()` (or poll `.cancelled`)
    and abort with `TaskCancelled` instead of hanging forever.

Same enablement contract as the rest of telemetry: disabled, `register`
returns a shared no-op heartbeat and every method is one attribute check.
"""

from __future__ import annotations

import itertools
import time
from contextlib import contextmanager
from typing import Iterator, Optional

from ..utils import lockdebug
from .events import emit

#: EWMA smoothing: ~the last ten beats dominate the rate estimate.
_EWMA_ALPHA = 0.2
#: Finished tasks kept for the status view's "recent" list.
_RECENT_KEEP = 32


class TaskCancelled(RuntimeError):
    """Raised by cooperative wait loops after a watchdog hard timeout."""


class Heartbeat:
    """One in-flight unit of work. Thread-safe through the registry lock
    (mutations are per-unit — per chunk/task/poll — never per frame)."""

    __slots__ = (
        "id", "label", "kind", "stage", "t_start", "t_beat",
        "units_done", "units_planned", "status", "cancelled",
        "stall_flagged", "_rate", "_registry",
    )

    def __init__(self, registry: "HeartbeatRegistry", label: str, kind: str,
                 stage: Optional[str], planned: Optional[float],
                 now: float) -> None:
        self._registry = registry
        self.id = next(registry._ids)
        self.label = label
        self.kind = kind
        self.stage = stage
        self.t_start = now
        self.t_beat = now  # guarded-by: _lock
        self.units_done = 0.0  # guarded-by: _lock
        self.units_planned = planned  # guarded-by: _lock
        self.status = "running"  # guarded-by: _lock
        # deliberately NOT lock-guarded: a lock-free flag polled by
        # cooperative wait loops (barrier, prefetch puts) — bool
        # store/load is GIL-atomic and staleness only delays the abort
        # by one poll
        self.cancelled = False
        self.stall_flagged = False  # guarded-by: _lock
        self._rate = 0.0  # EWMA units/s  # guarded-by: _lock

    # ------------------------------------------------------------ mutation

    def beat(self, advance: float = 0.0, done: Optional[float] = None) -> None:
        """Record liveness + progress. `advance` adds units; `done` sets
        the absolute units-done count (the barrier knows peers-arrived,
        not a delta)."""
        registry = self._registry
        if not registry.enabled:
            return
        with registry._lock:
            now = registry._clock()
            dt = now - self.t_beat
            if done is not None:
                advance = max(0.0, done - self.units_done)
            if advance > 0.0:
                self.units_done += advance
                if dt > 1e-9:
                    sample = advance / dt
                    self._rate = (
                        sample if self._rate == 0.0
                        else _EWMA_ALPHA * sample + (1 - _EWMA_ALPHA) * self._rate
                    )
            self.t_beat = now
            was_flagged = self.stall_flagged
            self.stall_flagged = False
        if was_flagged:
            # stage rides along (like task_stalled's) so fleet-wide
            # consumers can pair recoveries with the stalls they end
            emit("task_recovered", task=self.label, kind=self.kind,
                 stage=self.stage)

    def set_planned(self, planned: Optional[float]) -> None:
        if not self._registry.enabled:
            return
        with self._registry._lock:
            self.units_planned = planned

    def add_planned(self, extra: float) -> None:
        if not self._registry.enabled:
            return
        with self._registry._lock:
            self.units_planned = (self.units_planned or 0.0) + extra

    def finish(self, status: str = "ok") -> None:
        self._registry._finish(self, status)

    def check_cancelled(self) -> None:
        """Cooperative cancellation point for wait loops."""
        if self.cancelled:
            raise TaskCancelled(
                f"{self.kind} '{self.label}' cancelled by the watchdog "
                "hard timeout (see task_hard_timeout event for forensics)"
            )

    # ------------------------------------------------- views (lock held)

    # holds-lock: _lock
    def progress(self) -> Optional[float]:
        if not self.units_planned:
            return None
        return min(1.0, self.units_done / self.units_planned)

    # holds-lock: _lock
    def eta_s(self) -> Optional[float]:
        """EWMA-extrapolated seconds to completion; None while the rate
        or the plan is unknown."""
        if not self.units_planned or self._rate <= 0.0:
            return None
        remaining = self.units_planned - self.units_done
        if remaining <= 0.0:
            return 0.0
        return remaining / self._rate

    # holds-lock: _lock
    def as_dict(self, now: float) -> dict:
        d = {
            "label": self.label,
            "kind": self.kind,
            "age_s": round(now - self.t_start, 3),
            "beat_age_s": round(now - self.t_beat, 3),
            "units_done": self.units_done,
            "status": self.status,
        }
        if self.stage:
            d["stage"] = self.stage
        if self.units_planned is not None:
            d["units_planned"] = self.units_planned
        progress = self.progress()
        if progress is not None:
            d["progress"] = round(progress, 4)
        eta = self.eta_s()
        if eta is not None:
            d["eta_s"] = round(eta, 1)
        if self.stall_flagged:
            d["stalled"] = True
        if self.cancelled:
            d["cancelled"] = True
        return d


class _NullHeartbeat:
    """Shared no-op returned while the registry is disabled: call sites
    keep one code path and a disabled run pays an attribute check."""

    __slots__ = ()
    label = kind = status = ""
    stage = units_planned = None
    cancelled = stall_flagged = False
    units_done = t_start = t_beat = 0.0

    def beat(self, advance: float = 0.0, done: Optional[float] = None) -> None:
        pass

    def set_planned(self, planned: Optional[float]) -> None:
        pass

    def add_planned(self, extra: float) -> None:
        pass

    def finish(self, status: str = "ok") -> None:
        pass

    def check_cancelled(self) -> None:
        pass

    def progress(self) -> Optional[float]:
        return None

    def eta_s(self) -> Optional[float]:
        return None


NULL_HEARTBEAT = _NullHeartbeat()


class HeartbeatRegistry:
    """Process-wide set of live heartbeats + a bounded recently-finished
    tail. `clock` is injectable (monotonic) so the watchdog tests can
    age tasks without sleeping."""

    def __init__(self, clock=time.monotonic) -> None:
        self._lock = lockdebug.make_lock("heartbeat")
        self._clock = clock
        self._ids = itertools.count(1)
        self._live: dict[int, Heartbeat] = {}  # guarded-by: _lock
        self._recent: list[Heartbeat] = []  # guarded-by: _lock
        self._stages: dict[str, dict] = {}  # guarded-by: _lock
        self._current_stage: Optional[str] = None  # guarded-by: _lock
        self.enabled = False

    # --------------------------------------------------------- lifecycle

    def register(self, label: str, kind: str = "task",
                 planned: Optional[float] = None):
        """New in-flight unit of work; inherits the current stage (set by
        `telemetry.stage_span`) so the status view can group by stage."""
        if not self.enabled:
            return NULL_HEARTBEAT
        with self._lock:
            hb = Heartbeat(
                self, label, kind, self._current_stage, planned, self._clock()
            )
            self._live[hb.id] = hb
            return hb

    def _finish(self, hb: Heartbeat, status: str) -> None:
        if isinstance(hb, _NullHeartbeat):
            return
        with self._lock:
            if self._live.pop(hb.id, None) is None:
                return  # already finished (e.g. watchdog timed it out)
            hb.status = status
            hb.t_beat = self._clock()
            self._recent.append(hb)
            del self._recent[:-_RECENT_KEEP]

    @contextmanager
    def task(self, label: str, kind: str = "task",
             planned: Optional[float] = None) -> Iterator:
        hb = self.register(label, kind, planned)
        try:
            yield hb
        except BaseException:
            hb.finish("fail")
            raise
        else:
            hb.finish("ok")

    # ------------------------------------------------------------- stages

    def stage_begin(self, stage: str):
        """Stage-level heartbeat: units are JOBS (planned by JobRunner.add
        via `stage_add_planned`, advanced by Job completion via
        `stage_advance`), which makes progress self-consistent even when
        a stage runs several job phases (p03 wo_buffer + stalling)."""
        hb = self.register(stage, kind="stage")
        if self.enabled:
            with self._lock:
                self._current_stage = stage
                self._stages[stage] = {"hb": hb, "items": None}
        return hb

    def stage_end(self, stage: str, status: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            entry = self._stages.get(stage)
            self._current_stage = None
        if entry is not None:
            entry["hb"].finish(status)

    def stage_items(self, stage: str, items: float) -> None:
        """Advisory work-item count (the STAGE_ITEMS gauge's live twin)."""
        if not self.enabled:
            return
        with self._lock:
            entry = self._stages.get(stage)
            if entry is not None:
                entry["items"] = items

    def _stage_hb(self, stage: Optional[str]):
        with self._lock:
            entry = self._stages.get(stage or self._current_stage or "")
        return entry["hb"] if entry is not None else None

    def stage_add_planned(self, n: float = 1.0,
                          stage: Optional[str] = None) -> None:
        if not self.enabled:
            return
        hb = self._stage_hb(stage)
        if hb is not None:
            hb.add_planned(n)

    def stage_advance(self, n: float = 1.0,
                      stage: Optional[str] = None) -> None:
        if not self.enabled:
            return
        hb = self._stage_hb(stage)
        if hb is not None:
            hb.beat(advance=n)

    # -------------------------------------------------------------- views

    def live(self) -> list[Heartbeat]:
        with self._lock:
            return list(self._live.values())

    def snapshot(self) -> dict:
        """JSON-able live view: per-stage progress/ETA + every in-flight
        task with ages, plus the recently-finished tail.

        The whole view is materialized UNDER the registry lock: the
        previous copy-then-read shape let `/status` render a heartbeat
        whose `units_done` had advanced but whose `t_beat`/`_rate` had
        not (a torn progress/ETA pair) while worker threads beat
        concurrently — exactly the class chainlint's lock-guard rule
        now rejects. Snapshot cadence is operator-poll (~1 Hz), so
        holding the lock for the render costs nothing measurable."""
        with self._lock:
            now = self._clock()
            live = sorted(self._live.values(), key=lambda h: h.t_start)
            stage_view = {}
            current = self._current_stage
            for stage, entry in self._stages.items():
                hb = entry["hb"]
                d = {
                    "state": hb.status if hb.status != "running" else (
                        "running" if stage == current else "done"
                    ),
                    "jobs_done": hb.units_done,
                    "wall_s": round(
                        (hb.t_beat if hb.status != "running" else now)
                        - hb.t_start, 3,
                    ),
                }
                if hb.units_planned is not None:
                    d["jobs_planned"] = hb.units_planned
                progress = hb.progress()
                if progress is not None:
                    d["progress"] = round(progress, 4)
                eta = hb.eta_s()
                if eta is not None and hb.status == "running":
                    d["eta_s"] = round(eta, 1)
                if entry["items"] is not None:
                    d["items"] = entry["items"]
                stage_view[stage] = d
            return {
                "stages": stage_view,
                "current_stage": current,
                "tasks": [
                    h.as_dict(now) for h in live if h.kind != "stage"
                ],
                "recent": [
                    h.as_dict(now) for h in reversed(self._recent)
                ],
            }

    def reset(self) -> None:
        with self._lock:
            self._live.clear()
            self._recent.clear()
            self._stages.clear()
            self._current_stage = None


HEARTBEATS = HeartbeatRegistry()
