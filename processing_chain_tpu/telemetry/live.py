"""Live status surface: HTTP endpoint + atomically-rewritten status file.

`LiveServer` is a stdlib `ThreadingHTTPServer` (no new dependencies)
exposing three read-only endpoints while a run is in flight:

    /healthz   liveness: {"status": "ok", "uptime_s": ...}
    /metrics   MetricsRegistry.render_prometheus(), LIVE — the same
               format the post-run metrics_<ts>.prom persists
    /status    JSON: per-stage progress + ETA, in-flight tasks with
               beat ages, chain counters (schema below)

`StatusFileWriter` rewrites the same /status JSON to a file every
`interval_s` via tmp + os.replace, so a reader (tools chain-top, a
cron probe) never observes a torn write — the headless twin of the
endpoint for batch hosts with no reachable port.

Status document schema (docs/TELEMETRY.md "Live monitoring"):

    {"schema": 1, "pid": ..., "generated_at": epoch, "uptime_s": ...,
     "run": {...},                        # run_meta set by the CLI
     "stages": {stage: {state, jobs_done, jobs_planned?, progress?,
                        eta_s?, wall_s, items?}},
     "current_stage": ..., "tasks": [...], "recent": [...],
     "counters": {frames_decoded, frames_encoded, bytes_encoded}}

Binding defaults to 127.0.0.1 (an operator forwarding the port owns the
exposure decision); PC_LIVE_HOST overrides.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .heartbeat import HEARTBEATS
from .metrics import REGISTRY

_T0 = time.monotonic()

#: Mutable run metadata merged into /status (the CLI sets name/argv).
RUN_META: dict = {}


def build_status() -> dict:
    """One JSON-able status document from the live registries."""
    doc = {
        "schema": 1,
        "pid": os.getpid(),
        "generated_at": round(time.time(), 3),
        "uptime_s": round(time.monotonic() - _T0, 3),
        "run": dict(RUN_META),
    }
    doc.update(HEARTBEATS.snapshot())
    from . import BYTES_ENCODED, FRAMES_DECODED, FRAMES_ENCODED

    doc["counters"] = {
        "frames_decoded": FRAMES_DECODED.get(),
        "frames_encoded": FRAMES_ENCODED.get(),
        "bytes_encoded": BYTES_ENCODED.get(),
    }
    # current resources (RSS, pool bytes, queue depths) ride every status
    # document even when the full --profile monitor is off, so chain-top
    # can show memory on any live run; one cheap /proc + stats() sweep
    try:
        from . import profiling

        doc["resources"] = profiling.sample_resources()
    except Exception:  # noqa: BLE001 - /status must render on every platform
        pass
    return doc


class _Handler(BaseHTTPRequestHandler):
    server_version = "chain-live/1"

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            self._reply(200, "application/json", json.dumps({
                "status": "ok",
                "pid": os.getpid(),
                "uptime_s": round(time.monotonic() - _T0, 3),
            }))
        elif path == "/metrics":
            self._reply(
                200, "text/plain; version=0.0.4",
                REGISTRY.render_prometheus(),
            )
        elif path == "/status":
            self._reply(200, "application/json", json.dumps(build_status()))
        else:
            self._reply(404, "text/plain", "not found: try /healthz /metrics /status\n")

    def _reply(self, code: int, ctype: str, body: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):  # impatient curl
            pass

    def log_message(self, fmt: str, *args) -> None:  # noqa: A003
        pass  # never spam the chain's console per scrape


class LiveServer:
    """Threaded HTTP server on a daemon thread. Port 0 binds an
    ephemeral port; `.port` is the bound one either way."""

    def __init__(self, port: int, host: Optional[str] = None) -> None:
        self.host = host or os.environ.get("PC_LIVE_HOST", "127.0.0.1")
        self._server = ThreadingHTTPServer((self.host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LiveServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="chain-live-http", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def __enter__(self) -> "LiveServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def write_status_file(path: str) -> str:
    """One atomic rewrite: readers see the old document or the new one,
    never a torn half-write (tmp is thread/process-unique)."""
    tmp = f"{path}.{os.getpid()}.{threading.get_ident()}.tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w") as f:
        json.dump(build_status(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


class StatusFileWriter:
    """Periodic atomic status-file rewriter for headless runs (no port
    reachable). `stop()` writes one final snapshot so the file's last
    state reflects the run's end, not its second-to-last tick."""

    def __init__(self, path: str, interval_s: float = 2.0) -> None:
        self.path = path
        self.interval_s = max(0.2, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                write_status_file(self.path)
            except OSError:  # a transiently-full disk must not kill the run
                pass

    def start(self) -> "StatusFileWriter":
        if self._thread is None:
            write_status_file(self.path)  # visible immediately, not at t+interval
            self._thread = threading.Thread(
                target=self._loop, name="chain-status-file", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            write_status_file(self.path)
        except OSError:
            pass
