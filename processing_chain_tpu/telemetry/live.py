"""Live status surface: HTTP endpoint + atomically-rewritten status file.

`LiveServer` is a stdlib `ThreadingHTTPServer` (no new dependencies)
exposing read-only endpoints while a run is in flight — and, since the
serve daemon (processing_chain_tpu/serve), a *route registry* so every
HTTP surface of the chain shares this one server:

    /healthz   liveness: {"status": "ok", "uptime_s": ...}
    /metrics   MetricsRegistry.render_prometheus(), LIVE — the same
               format the post-run metrics_<ts>.prom persists
    /status    JSON: per-stage progress + ETA, in-flight tasks with
               beat ages, chain counters (schema below)

Additional routes (e.g. chain-serve's `/v1/requests`,
`/v1/artifacts/<key>`) register on a `RouteRegistry` — exact paths or
prefixes, per-method — instead of forking a second server with its own
port, thread and shutdown story. Handlers receive a `WebRequest`
(method/path/query/body) and return `(code, content_type, body)` where
body may be `str` or `bytes`.

`StatusFileWriter` rewrites the same /status JSON to a file every
`interval_s` atomically (utils/fsio), so a reader (tools chain-top, a
cron probe) never observes a torn write — the headless twin of the
endpoint for batch hosts with no reachable port.

Status document schema (docs/TELEMETRY.md "Live monitoring"):

    {"schema": 1, "pid": ..., "generated_at": epoch, "uptime_s": ...,
     "run": {...},                        # run_meta set by the CLI
     "stages": {stage: {state, jobs_done, jobs_planned?, progress?,
                        eta_s?, wall_s, items?}},
     "current_stage": ..., "tasks": [...], "recent": [...],
     "counters": {frames_decoded, frames_encoded, bytes_encoded}}

Subsystems can contribute their own top-level sections through
`STATUS_PROVIDERS` (name -> callable(query) -> dict): chain-serve adds a
"serve" section, scopable per request via `/status?request=<id>`.

Binding defaults to 127.0.0.1 (an operator forwarding the port owns the
exposure decision); PC_LIVE_HOST overrides.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import BinaryIO, Callable, Dict, Optional, Tuple, Union
from urllib.parse import parse_qsl, urlsplit

from ..utils import lockdebug
from ..utils.fsio import atomic_write_json
from ..utils.log import get_logger
from .heartbeat import HEARTBEATS
from .metrics import REGISTRY

_T0 = time.monotonic()

#: Mutable run metadata merged into /status. Guarded by _RUN_META_LOCK:
#: the CLI replaces it via set_run_meta() while a StatusFileWriter tick
#: or an HTTP /status handler may be snapshotting it from another
#: thread — an unlocked dict(RUN_META) during the mutation is exactly
#: the serialize-a-shared-doc race the atomic-write discipline exists
#: to prevent (docs/LINT.md "atomic-write").
RUN_META: dict = {}
_RUN_META_LOCK = threading.Lock()


def set_run_meta(**meta) -> None:
    """Replace the run metadata atomically (the CLI's entry point)."""
    with _RUN_META_LOCK:
        RUN_META.clear()
        RUN_META.update(meta)


def _run_meta_snapshot() -> dict:
    with _RUN_META_LOCK:
        return dict(RUN_META)

#: Extra /status sections: name -> callable(query: dict) -> dict | None.
#: A provider that raises or returns None is skipped — /status must
#: render on every platform no matter what a subsystem is doing.
STATUS_PROVIDERS: Dict[str, Callable[[dict], Optional[dict]]] = {}

#: POST bodies past this are refused (413): every legitimate request
#: document is a few KB of IDs; anything bigger is a mistake or abuse.
_MAX_BODY = 1 << 20


def build_status(query: Optional[dict] = None) -> dict:
    """One JSON-able status document from the live registries."""
    doc = {
        "schema": 1,
        "pid": os.getpid(),
        "generated_at": round(time.time(), 3),
        "uptime_s": round(time.monotonic() - _T0, 3),
        "run": _run_meta_snapshot(),
    }
    doc.update(HEARTBEATS.snapshot())
    from . import BYTES_ENCODED, FRAMES_DECODED, FRAMES_ENCODED

    doc["counters"] = {
        "frames_decoded": FRAMES_DECODED.get(),
        "frames_encoded": FRAMES_ENCODED.get(),
        "bytes_encoded": BYTES_ENCODED.get(),
    }
    # current resources (RSS, pool bytes, queue depths) ride every status
    # document even when the full --profile monitor is off, so chain-top
    # can show memory on any live run; one cheap /proc + stats() sweep
    try:
        from . import profiling

        doc["resources"] = profiling.sample_resources()
    except Exception:  # noqa: BLE001 - /status must render on every platform
        pass
    for name, provider in list(STATUS_PROVIDERS.items()):
        try:
            section = provider(query or {})
        except Exception:  # noqa: BLE001 - a broken provider must not kill /status
            continue
        if section is not None:
            doc[name] = section
    return doc


# --------------------------------------------------------------- routing


@dataclass
class WebRequest:
    """What a route handler sees: enough to act, nothing http.server."""

    method: str
    path: str                     # decoded path, query stripped
    query: dict = field(default_factory=dict)
    body: bytes = b""
    headers: dict = field(default_factory=dict)  # lowercased names


@dataclass
class FileBody:
    """A response body streamed from disk in chunks instead of being
    materialized in memory — artifact downloads are video-scale, and an
    always-on daemon answering several concurrent multi-GB GETs with
    f.read() would OOM on exactly the load it exists to serve.

    Handlers that race a deleter (the serve GC pressure hook can evict
    an artifact between the handler's check and the reply's streaming
    loop) should open the file themselves and pass `fileobj`: the open
    descriptor keeps the bytes alive for the whole response even if the
    path is unlinked mid-stream. `_reply` closes it either way.

    `on_first_byte` fires after the response headers are on the wire —
    the closest observable to the client's TTFB without kernel help —
    and `on_complete(sent_bytes, ok)` fires exactly once when the
    stream ends, with `ok=False` on a disconnect or disk failure.
    Callback exceptions are swallowed: observability hooks must never
    break the stream they time."""

    path: str
    fileobj: Optional[BinaryIO] = None
    #: single-range serving (RFC 9110 `Range: bytes=…` → 206): seek to
    #: `offset` and stream exactly `length` bytes. Defaults stream the
    #: whole file; `length` also serves as the Content-Length when set,
    #: so handlers can bound a stream without a second fstat
    offset: int = 0
    length: Optional[int] = None
    on_first_byte: Optional[Callable[[], None]] = None
    on_complete: Optional[Callable[[int, bool], None]] = None


#: handler signature: WebRequest -> (status code, content type, body)
#: or (code, content type, body, extra-headers dict) — the 4-tuple form
#: lets a handler attach response headers (ETag, Cache-Control) without
#: the registry growing a second dispatch path
Handler = Callable[[WebRequest], Tuple[int, str, Union[str, bytes, FileBody]]]


class RouteRegistry:
    """Exact-path and prefix routes with per-method dispatch. Thread-safe:
    subsystems register while the server is already answering scrapes."""

    def __init__(self) -> None:
        self._lock = lockdebug.make_lock("live_routes")
        self._exact: dict[str, dict[str, Handler]] = {}  # guarded-by: _lock
        #: longest-prefix-first [(prefix, {method: handler})]
        self._prefix: list[tuple[str, dict[str, Handler]]] = []  # guarded-by: _lock

    def add(self, path: str, handler: Handler,
            methods: tuple = ("GET",)) -> None:
        with self._lock:
            entry = self._exact.setdefault(path, {})
            for m in methods:
                entry[m.upper()] = handler

    def add_prefix(self, prefix: str, handler: Handler,
                   methods: tuple = ("GET",)) -> None:
        with self._lock:
            for p, entry in self._prefix:
                if p == prefix:
                    for m in methods:
                        entry[m.upper()] = handler
                    return
            self._prefix.append((prefix, {m.upper(): handler for m in methods}))
            self._prefix.sort(key=lambda e: -len(e[0]))

    def resolve(self, method: str, path: str
                ) -> tuple[Optional[Handler], Optional[set]]:
        """(handler, None) on a match; (None, allowed-methods) when the
        path exists under another method (405); (None, None) for 404."""
        with self._lock:
            entry = self._exact.get(path)
            if entry is None:
                for prefix, e in self._prefix:
                    if path.startswith(prefix):
                        entry = e
                        break
        if entry is None:
            return None, None
        handler = entry.get(method.upper())
        if handler is None:
            return None, set(entry)
        return handler, None

    def paths(self) -> list[str]:
        with self._lock:
            return sorted(self._exact) + sorted(
                p + "…" for p, _ in self._prefix
            )


def _healthz(req: WebRequest):
    return 200, "application/json", json.dumps({
        "status": "ok",
        "pid": os.getpid(),
        "uptime_s": round(time.monotonic() - _T0, 3),
    })


def _metrics(req: WebRequest):
    return 200, "text/plain; version=0.0.4", REGISTRY.render_prometheus()


def _status(req: WebRequest):
    return 200, "application/json", json.dumps(build_status(req.query))


def default_routes() -> RouteRegistry:
    """A fresh registry holding the built-in observability endpoints —
    the base every LiveServer (batch run or serve daemon) starts from."""
    routes = RouteRegistry()
    routes.add("/healthz", _healthz)
    routes.add("/metrics", _metrics)
    routes.add("/status", _status)
    return routes


class _Server(ThreadingHTTPServer):
    """ThreadingHTTPServer carrying the route registry for its handlers."""

    daemon_threads = True

    def __init__(self, addr, routes: RouteRegistry) -> None:
        super().__init__(addr, _Handler)
        self.routes = routes

    def handle_error(self, request, client_address) -> None:
        # in-flight handlers racing stop() hit closed sockets; that is a
        # shutdown artifact, not a report — never traceback-spam stderr
        pass


class _Handler(BaseHTTPRequestHandler):
    server_version = "chain-live/2"

    def _dispatch(self, method: str) -> None:
        split = urlsplit(self.path)
        path = split.path
        handler, allowed = self.server.routes.resolve(method, path)
        if handler is None:
            if allowed:
                self.send_response(405)
                self.send_header("Allow", ", ".join(sorted(allowed)))
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self._reply(404, "text/plain",
                        "not found: try /healthz /metrics /status\n")
            return
        body = b""
        if method == "POST":
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            if length > _MAX_BODY:
                self._reply(413, "application/json",
                            json.dumps({"error": "body too large"}))
                return
            body = self.rfile.read(length) if length else b""
        req = WebRequest(
            method=method, path=path,
            query=dict(parse_qsl(split.query)), body=body,
            headers={k.lower(): v for k, v in self.headers.items()},
        )
        extra: Optional[dict] = None
        try:
            result = handler(req)
            if len(result) == 4:
                code, ctype, payload, extra = result
            else:
                code, ctype, payload = result
        except Exception as exc:  # noqa: BLE001 - one bad handler must not kill the surface
            code, ctype, payload = 500, "application/json", json.dumps(
                {"error": repr(exc)[:300]}
            )
        self._reply(code, ctype, payload, extra)

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        self._dispatch("GET")

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        self._dispatch("POST")

    def do_DELETE(self) -> None:  # noqa: N802 - http.server API
        self._dispatch("DELETE")

    @staticmethod
    def _fire(cb, *args) -> None:
        # FileBody callbacks are observability hooks (read-path SLO
        # timers, the heat ledger); a broken one must not truncate the
        # stream it is supposed to time
        if cb is None:
            return
        try:
            cb(*args)
        except Exception:  # noqa: BLE001
            get_logger().warning("live: body callback failed",
                                 exc_info=True)

    def _reply(self, code: int, ctype: str,
               body: Union[str, bytes, FileBody],
               extra: Optional[dict] = None) -> None:
        try:
            if isinstance(body, FileBody):
                sent = 0
                ok = False
                f = body.fileobj
                try:
                    if f is None:
                        f = open(body.path, "rb")
                    if body.length is not None:
                        size = body.length
                    else:
                        size = max(
                            0,
                            os.fstat(f.fileno()).st_size - body.offset)
                    if body.offset:
                        f.seek(body.offset)
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(size))
                    for name, value in (extra or {}).items():
                        self.send_header(name, value)
                    self.end_headers()
                    self._fire(body.on_first_byte)
                    remaining = size
                    while remaining > 0:
                        chunk = f.read(min(1 << 20, remaining))
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        sent += len(chunk)
                        remaining -= len(chunk)
                    ok = remaining == 0
                finally:
                    if f is not None:
                        f.close()
                    self._fire(body.on_complete, sent, ok)
                return
            data = body.encode() if isinstance(body, str) else body
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for name, value in (extra or {}).items():
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            # impatient curl, or a handler racing stop()'s socket close
            pass
        except OSError:
            # NOT a client disconnect: disk trouble mid-stream, or a
            # FileBody path deleted before the handler pinned an fd —
            # the client got a truncated/empty response; say so.
            get_logger().warning(
                "live: reply for %s failed mid-stream", self.path,
                exc_info=True,
            )

    def log_message(self, fmt: str, *args) -> None:  # noqa: A003
        pass  # never spam the chain's console per scrape


class LiveServer:
    """Threaded HTTP server on a daemon thread. Port 0 binds an
    ephemeral port; `.port` is the bound one either way. `routes`
    defaults to the built-in observability endpoints; callers that need
    more (the serve daemon) pass `default_routes()` plus their own."""

    def __init__(self, port: int, host: Optional[str] = None,
                 routes: Optional[RouteRegistry] = None) -> None:
        self.host = host or os.environ.get("PC_LIVE_HOST", "127.0.0.1")
        self.routes = routes if routes is not None else default_routes()
        self._server = _Server((self.host, port), self.routes)
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LiveServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="chain-live-http", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            # shutdown() blocks on the serve_forever loop acknowledging;
            # only meaningful (or safe) when the loop is actually running
            self._server.shutdown()
            self._server.server_close()
            self._thread.join(timeout=2.0)
            self._thread = None
        else:
            self._server.server_close()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def __enter__(self) -> "LiveServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def write_status_file(path: str) -> str:
    """One atomic rewrite (utils/fsio): readers see the old document or
    the new one, never a torn half-write — and a failing json.dump can
    no longer strand its temp file (the previous hand-rolled tmp+replace
    leaked the .tmp when the dump raised mid-write)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    atomic_write_json(path, build_status(), sort_keys=True)
    return path


class StatusFileWriter:
    """Periodic atomic status-file rewriter for headless runs (no port
    reachable). `stop()` writes one final snapshot so the file's last
    state reflects the run's end, not its second-to-last tick."""

    def __init__(self, path: str, interval_s: float = 2.0) -> None:
        self.path = path
        self.interval_s = max(0.2, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                write_status_file(self.path)
            except OSError:  # a transiently-full disk must not kill the run
                pass

    def start(self) -> "StatusFileWriter":
        if self._thread is None:
            write_status_file(self.path)  # visible immediately, not at t+interval
            self._thread = threading.Thread(
                target=self._loop, name="chain-status-file", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            write_status_file(self.path)
        except OSError:
            pass
