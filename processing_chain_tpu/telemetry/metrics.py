"""Process-wide metrics registry: counters, gauges, histograms with labels.

The chain's quantitative observability layer (docs/TELEMETRY.md). Design
constraints, in order:

  1. Zero hot-path cost when telemetry is off. Every mutation method
     starts with a plain attribute check on the shared registry — no
     dict, tuple, or string allocation happens for a disabled metric.
     Hot loops (prefetch chunks, writer chunks) bind a labeled child
     ONCE outside the loop (`metric.labels(queue="decode")`) and call
     `inc`/`observe` on the bound handle.
  2. Thread-safe like `utils.tracing.Tracer`: producers are the decode /
     encode / pool worker threads; one registry lock serializes updates
     (mutation frequency is per-chunk, not per-frame, so a coarse lock
     costs nothing measurable).
  3. Self-describing exports: `snapshot()` (JSON-able dict, written by
     `--telemetry` as metrics_<ts>.json) and `render_prometheus()` (the
     node_exporter textfile-collector format, for scraping).

Metric names follow Prometheus conventions: `chain_<noun>_<unit>_total`
for counters, `_seconds` histograms for latencies.
"""

from __future__ import annotations

import json
import os
from bisect import bisect_left
from typing import Iterable, Optional, Sequence
from ..utils import lockdebug

DEFAULT_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0,
)
DEFAULT_DEPTH_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)


class MetricError(ValueError):
    """Registration/usage contract violation (kind or label mismatch)."""


class _Bound:
    """A metric narrowed to one label-value tuple. Mutations check the
    registry's `enabled` flag first so a disabled chain pays one
    attribute load + branch, nothing else."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "_Metric", key: tuple) -> None:
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        metric = self._metric
        # kind check BEFORE the enabled check (like set/observe): a wrong
        # call site must fail in telemetry-off CI runs, not only on the
        # first production --telemetry run
        if metric.kind == "histogram":
            raise MetricError(f"{metric.name}: inc() on a histogram")
        if not metric._registry.enabled:
            return
        with metric._registry._lock:
            metric._values[self._key] = metric._values.get(self._key, 0.0) + amount

    def dec(self, amount: float = 1.0) -> None:
        if self._metric.kind != "gauge":
            raise MetricError(f"{self._metric.name}: dec() on a {self._metric.kind}")
        self.inc(-amount)

    def set(self, value: float) -> None:
        metric = self._metric
        if metric.kind != "gauge":
            raise MetricError(f"{metric.name}: set() on a {metric.kind}")
        if not metric._registry.enabled:
            return
        with metric._registry._lock:
            metric._values[self._key] = float(value)

    def observe(self, value: float) -> None:
        metric = self._metric
        if metric.kind != "histogram":
            raise MetricError(f"{metric.name}: observe() on a {metric.kind}")
        if not metric._registry.enabled:
            return
        with metric._registry._lock:
            state = metric._values.get(self._key)
            if state is None:
                state = [0] * (len(metric.buckets) + 1), [0.0, 0]
                metric._values[self._key] = state
            counts, agg = state
            counts[bisect_left(metric.buckets, value)] += 1
            agg[0] += value
            agg[1] += 1

    def get(self) -> float:
        """Current value (counter/gauge) — 0.0 when never touched."""
        metric = self._metric
        with metric._registry._lock:
            if metric.kind == "histogram":
                state = metric._values.get(self._key)
                return float(state[1][0]) if state else 0.0
            return float(metric._values.get(self._key, 0.0))


class _Metric:
    def __init__(
        self,
        registry: "MetricsRegistry",
        name: str,
        help_: str,
        kind: str,
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> None:
        self._registry = registry
        self.name = name
        self.help = help_
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(buckets)) if kind == "histogram" else ()
        # counter/gauge: {label values: float}
        # histogram:     {label values: ([bucket counts..., +inf count], [sum, n])}
        self._values: dict = {}  # guarded-by: _registry._lock
        self._bound: dict[tuple, _Bound] = {}  # guarded-by: _registry._lock
        self._nolabels = _Bound(self, ())

    def labels(self, **labels: str) -> _Bound:
        """Bound child for one label-value combination; cached, so hot
        paths can call this once and keep the handle."""
        if tuple(sorted(labels)) != tuple(sorted(self.labelnames)):
            raise MetricError(
                f"{self.name}: expected labels {self.labelnames}, got "
                f"{tuple(sorted(labels))}"
            )
        key = tuple(str(labels[n]) for n in self.labelnames)
        # chainlint: disable=lock-guard (deliberate lock-free fast path: dict.get is GIL-atomic and a miss falls through to the locked setdefault below — hot loops bind once, never see a torn entry)
        bound = self._bound.get(key)
        if bound is None:
            with self._registry._lock:
                bound = self._bound.setdefault(key, _Bound(self, key))
        return bound

    # unlabeled convenience passthroughs
    def inc(self, amount: float = 1.0) -> None:
        self._nolabels.inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._nolabels.dec(amount)

    def set(self, value: float) -> None:
        self._nolabels.set(value)

    def observe(self, value: float) -> None:
        self._nolabels.observe(value)

    def get(self) -> float:
        return self._nolabels.get()


class MetricsRegistry:
    """Get-or-create registry. Creating the same metric twice returns the
    first instance; re-creating under a different kind/labelset raises
    (two modules silently disagreeing on a metric is always a bug)."""

    def __init__(self) -> None:
        self._lock = lockdebug.make_lock("metrics")
        self._metrics: dict[str, _Metric] = {}  # guarded-by: _lock
        self.enabled = False

    def _get_or_create(
        self, name: str, help_: str, kind: str,
        labelnames: Sequence[str], buckets: Optional[Sequence[float]],
    ) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind or existing.labelnames != tuple(labelnames):
                    raise MetricError(
                        f"metric {name!r} re-registered as {kind}"
                        f"{tuple(labelnames)} but exists as {existing.kind}"
                        f"{existing.labelnames}"
                    )
                return existing
            metric = _Metric(self, name, help_, kind, labelnames, buckets)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help_: str = "", labelnames: Sequence[str] = ()) -> _Metric:
        return self._get_or_create(name, help_, "counter", labelnames, None)

    def gauge(self, name: str, help_: str = "", labelnames: Sequence[str] = ()) -> _Metric:
        return self._get_or_create(name, help_, "gauge", labelnames, None)

    def histogram(
        self, name: str, help_: str = "", labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
    ) -> _Metric:
        return self._get_or_create(name, help_, "histogram", labelnames, buckets)

    def reset(self) -> None:
        """Zero every series (registrations survive — module-level bound
        handles must stay valid across runs in one process)."""
        with self._lock:
            for metric in self._metrics.values():
                metric._values.clear()

    def sum_series(self, name: str, labels: Optional[dict] = None) -> Optional[float]:
        """Sum of the current values (counter/gauge) or observation sums
        (histogram) across one metric's series matching `labels` (all
        series when None). Returns None when NO matching series has ever
        recorded — callers that must distinguish 'never measured' from
        'measured zero' (the attribution engine) need exactly that, and
        a full snapshot() of every metric to read one name would stall
        concurrent updates for nothing."""
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                return None
            total, found = 0.0, False
            for key, value in metric._values.items():
                if labels is not None and dict(zip(metric.labelnames, key)) != {
                    k: str(v) for k, v in labels.items()
                }:
                    continue
                found = True
                total += (
                    float(value[1][0]) if metric.kind == "histogram"
                    else float(value)
                )
            return total if found else None

    def snapshot(self) -> dict:
        """JSON-able view of every series."""
        out: dict = {}
        with self._lock:
            for name, metric in sorted(self._metrics.items()):
                series = []
                for key in sorted(metric._values):
                    labels = dict(zip(metric.labelnames, key))
                    if metric.kind == "histogram":
                        counts, (total, n) = metric._values[key]
                        series.append({
                            "labels": labels,
                            "count": n,
                            "sum": round(total, 6),
                            "buckets": {
                                ("+Inf" if i == len(metric.buckets) else repr(metric.buckets[i])): c
                                for i, c in enumerate(counts)
                            },
                        })
                    else:
                        series.append({
                            "labels": labels,
                            "value": round(float(metric._values[key]), 6),
                        })
                out[name] = {
                    "kind": metric.kind,
                    "help": metric.help,
                    "labelnames": list(metric.labelnames),
                    "series": series,
                }
        return out

    def write_json(self, path: str) -> str:
        from ..utils.fsio import atomic_write_json

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write_json(path, self.snapshot(), sort_keys=True)
        return path

    def render_prometheus(self) -> str:
        """node_exporter textfile-collector format."""
        def fmt_labels(labels: dict, extra: Optional[tuple] = None) -> str:
            items = list(labels.items()) + ([extra] if extra else [])
            if not items:
                return ""
            body = ",".join(f'{k}="{_escape(v)}"' for k, v in items)
            return "{" + body + "}"

        lines: list[str] = []
        snap = self.snapshot()
        for name, data in snap.items():
            if data["help"]:
                lines.append(f"# HELP {name} {data['help']}")
            lines.append(f"# TYPE {name} {data['kind']}")
            for s in data["series"]:
                if data["kind"] == "histogram":
                    cum = 0
                    for le, c in s["buckets"].items():
                        cum += c
                        lines.append(
                            f"{name}_bucket{fmt_labels(s['labels'], ('le', le))} {cum}"
                        )
                    lines.append(f"{name}_sum{fmt_labels(s['labels'])} {s['sum']}")
                    lines.append(f"{name}_count{fmt_labels(s['labels'])} {s['count']}")
                else:
                    lines.append(f"{name}{fmt_labels(s['labels'])} {_num(s['value'])}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(self, path: str) -> str:
        from ..utils.fsio import atomic_write_text

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write_text(path, self.render_prometheus())
        return path


def _escape(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _num(value: float) -> str:
    return repr(int(value)) if float(value).is_integer() else repr(value)


REGISTRY = MetricsRegistry()


def counter(name: str, help_: str = "", labelnames: Iterable[str] = ()) -> _Metric:
    return REGISTRY.counter(name, help_, tuple(labelnames))


def gauge(name: str, help_: str = "", labelnames: Iterable[str] = ()) -> _Metric:
    return REGISTRY.gauge(name, help_, tuple(labelnames))


def histogram(
    name: str, help_: str = "", labelnames: Iterable[str] = (),
    buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
) -> _Metric:
    return REGISTRY.histogram(name, help_, tuple(labelnames), buckets)
