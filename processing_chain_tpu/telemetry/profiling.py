"""Profiling & performance attribution layer (docs/TELEMETRY.md).

Three answers the metrics/events layers cannot give on their own:

1. **Where did the resources go while the run was alive?** —
   `ResourceMonitor`, a low-overhead sampling thread (RSS, open fds,
   CPU%, BufferPool free/outstanding bytes, live prefetch queue depths,
   and — when a device backend is already live — jax device memory)
   recorded as a bounded timeseries, mirrored into resource gauges so
   `/metrics`, the Prometheus export, and `/status` carry the current
   values.

2. **What was each execution resource doing WHEN?** — `build_chrome_trace`
   merges the host span recorder (`utils/tracing.Tracer`: jobs, stage
   spans, prefetch/writeback chunks, device_put/get, and the
   `device:<step>` spans `parallel/pipeline._instrument_step` records
   around each blocking jitted call) with the structured event log into
   ONE Chrome-trace JSON (`chrome://tracing` / Perfetto). Host and
   device-step events share the tracer's `perf_counter` clock domain by
   construction; `jax.profiler` capture is attempted on accelerator
   backends for kernel-level depth, with a graceful host-only fallback
   on CPU.

3. **Why was the run slow?** — the attribution engine reduces the
   component seconds the chain already measures (consumer blocked time =
   starved by decode, producer blocked time = backed up behind encode,
   device transfer seconds, device step seconds) into a per-stage
   verdict: `decode_bound | transfer_bound | compute_bound |
   encode_bound | balanced`, with contributor percentages.
   `telemetry.stage_span` embeds the per-stage component deltas in each
   stage_end event; `classify_components` is the pure classifier the
   report and `tools chain-profile` render.

Enablement: `--profile DIR` on any stage CLI (implies telemetry). The
`active()` flag gates the extra per-chunk spans in engine/prefetch and
parallel/p03_batch so ordinary runs record nothing new.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Iterable, Optional, Sequence

from .metrics import REGISTRY, gauge
from ..utils import lockdebug

# --------------------------------------------------------------- gauges
# Mirrored from every ResourceMonitor sample (and any sample_resources
# call) so the live /metrics render and the post-run Prometheus export
# carry the latest values without a second collection path.

_RSS = gauge("chain_resource_rss_bytes", "resident set size of the chain process")
_FDS = gauge("chain_resource_open_fds", "open file descriptors of the chain process")
_CPU = gauge(
    "chain_resource_cpu_percent",
    "process CPU usage over the last sampling interval (100 = one core)",
)
_POOL_FREE = gauge(
    "chain_bufpool_free_bytes", "bytes parked on the buffer pool's free lists"
)
_POOL_OUT = gauge(
    "chain_bufpool_outstanding_bytes",
    "bytes of pool blocks currently owned by the pipeline",
)
_QDEPTH = gauge(
    "chain_resource_queue_depth",
    "current depth of the live bounded pipeline queues (summed per name)",
    ("queue",),
)
_DEVMEM = gauge(
    "chain_device_memory_bytes",
    "jax device memory stats per local device (device=\"all\" carries "
    "the fleet-of-devices sum)",
    ("device", "kind"),
)

#: Verdicts the attribution engine can return.
VERDICTS = (
    "decode_bound", "transfer_bound", "compute_bound", "encode_bound",
    "balanced", "fragmentation_bound",
)

#: a "balanced" run whose mesh waves padded away at least this fraction
#: of their dispatched frame-slots is reclassified fragmentation_bound —
#: no single component dominates because the device time itself is spent
#: on padding, and "balanced" would hide the one thing to fix
#: (docs/PERF.md "my waves are wasteful")
FRAGMENTATION_WASTE_THRESHOLD = 0.25

#: component -> (metric name, label filter) — the measured seconds each
#: verdict is grounded in. "decode" and "encode" are the BLOCKED times of
#: the pipeline (a starved consumer is waiting on decode; a blocked
#: producer is backed up behind encode) — the directly-attributable cost
#: of those phases to the critical path, not their raw busy time.
COMPONENT_METRICS = {
    "decode": ("chain_pipeline_wait_seconds_total", {"side": "consumer"}),
    "encode": ("chain_pipeline_wait_seconds_total", {"side": "producer"}),
    "transfer": ("chain_device_transfer_seconds_total", None),
    "compute": ("chain_device_step_seconds", None),
}

_ACTIVE = False


def active() -> bool:
    """Whether a `--profile` capture is in flight (gates the per-chunk
    prefetch/writeback/transfer spans — one module-flag check)."""
    return _ACTIVE


def maybe_span(name: str, **meta):
    """A tracer span while a `--profile` capture is active, else a no-op
    context — THE gate for the per-chunk lane spans, expressed once so a
    future change (e.g. a sampling rate) has one home. `meta` rides the
    span into the merged Chrome trace as `args` (the wave spans carry
    their valid/pad slot breakdown this way)."""
    if not _ACTIVE:
        from contextlib import nullcontext

        return nullcontext()
    from ..utils import tracing

    return tracing.span(name, **meta)


# ---------------------------------------------------------------- sampling


_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096
_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100


def _read_rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return None


def _read_open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def _read_cpu_ticks() -> Optional[float]:
    try:
        with open("/proc/self/stat") as f:
            parts = f.read().rsplit(")", 1)[1].split()
        # fields 14/15 of /proc/<pid>/stat (utime, stime) land at index
        # 11/12 after the comm field is stripped
        return float(int(parts[11]) + int(parts[12]))
    except (OSError, ValueError, IndexError):
        return None


class _CpuTracker:
    """CPU% between consecutive calls on ONE tracker. Each consumer owns
    its own (the monitor loop, the shared /status default) — a shared
    baseline would let any caller shrink every other caller's interval
    to milliseconds, where utime+stime quantize to whole scheduler ticks
    and read as 0% or thousand-percent spikes."""

    #: below this the tick granularity (1/_CLK_TCK) dominates the signal
    MIN_INTERVAL_S = 0.2

    def __init__(self) -> None:
        self._lock = lockdebug.make_lock("resource_monitor")
        self._last: Optional[tuple[float, float]] = None  # (perf_counter, ticks)

    def percent(self) -> Optional[float]:
        ticks = _read_cpu_ticks()
        if ticks is None:
            return None
        now = time.perf_counter()
        with self._lock:
            last = self._last
            if last is not None and now - last[0] < self.MIN_INTERVAL_S:
                # keep the old baseline: a fast re-poll must not destroy
                # the interval the next honest call will measure over
                return None
            self._last = (now, ticks)
        if last is None:
            return None
        return 100.0 * (ticks - last[1]) / _CLK_TCK / (now - last[0])


#: default tracker for one-shot callers (/status, ad-hoc samples)
_SHARED_CPU = _CpuTracker()


def _device_memory() -> tuple[dict[str, float], dict[str, dict]]:
    """(summed totals, per-device stats) of jax device memory — ONLY
    when a backend already exists (sampling must never trigger backend
    init, which can block on a remote tunnel). Per-device entries are
    keyed "<platform>:<id>" — the `device` label of
    chain_device_memory_bytes."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return {}, {}
    try:
        from jax._src import xla_bridge as xb

        if not getattr(xb, "_backends", None):
            return {}, {}
        totals: dict[str, float] = {}
        per_device: dict[str, dict] = {}
        for dev in jax_mod.local_devices():
            stats = dev.memory_stats() or {}
            entry: dict = {}
            for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if key in stats:
                    totals[key] = totals.get(key, 0.0) + float(stats[key])
                    entry[key] = float(stats[key])
            if entry:
                per_device[f"{dev.platform}:{dev.id}"] = entry
        return totals, per_device
    except Exception:  # noqa: BLE001 - best-effort on every backend/runtime
        return {}, {}


def sample_resources(
    include_device: bool = True, cpu: Optional[_CpuTracker] = None,
) -> dict:
    """One cheap resource snapshot (also the `/status` `resources`
    section, so it must stay safe to call with the full profiler off).
    Mirrors current values into the resource gauges when telemetry is
    enabled. Periodic callers pass their own `cpu` tracker so their
    CPU%% interval is theirs alone."""
    from ..engine import prefetch as _prefetch
    from ..io import bufpool as _bufpool

    pool = _bufpool.DEFAULT_POOL.stats()
    queues = _prefetch.live_queue_depths()
    sample: dict = {
        "rss_bytes": _read_rss_bytes(),
        "open_fds": _read_open_fds(),
        "cpu_percent": (cpu or _SHARED_CPU).percent(),
        "pool_free_bytes": pool["free_bytes"],
        "pool_outstanding_bytes": pool["outstanding_bytes"],
        "pool_free_blocks": pool["free_blocks"],
        "pool_outstanding_blocks": pool["outstanding"],
        "queues": {name: entry["depth"] for name, entry in queues.items()},
    }
    if include_device:
        devmem, per_device = _device_memory()
        if devmem:
            sample["device_memory"] = devmem
        if per_device:
            sample["device_memory_by_device"] = per_device
    if REGISTRY.enabled:
        if sample["rss_bytes"] is not None:
            _RSS.set(sample["rss_bytes"])
        if sample["open_fds"] is not None:
            _FDS.set(sample["open_fds"])
        if sample["cpu_percent"] is not None:
            _CPU.set(round(sample["cpu_percent"], 2))
        _POOL_FREE.set(sample["pool_free_bytes"])
        _POOL_OUT.set(sample["pool_outstanding_bytes"])
        for name, depth in sample["queues"].items():
            _QDEPTH.labels(queue=name).set(depth)
        # a queue that died since the last sample must read 0, not stay
        # latched at its final depth in /metrics and the end-of-run
        # snapshot (a phantom full queue reads as a stall)
        with _SEEN_QUEUES_LOCK:
            gone = _SEEN_QUEUES - set(sample["queues"])
            _SEEN_QUEUES.update(sample["queues"])
        for name in gone:
            _QDEPTH.labels(queue=name).set(0)
        for kind, val in sample.get("device_memory", {}).items():
            _DEVMEM.labels(device="all", kind=kind).set(val)
        for dev_label, stats in sample.get(
                "device_memory_by_device", {}).items():
            for kind, val in stats.items():
                _DEVMEM.labels(device=dev_label, kind=kind).set(val)
    return sample


_SEEN_QUEUES: set = set()
_SEEN_QUEUES_LOCK = lockdebug.make_lock("seen_queues")


def format_resource_peaks(peaks: dict) -> list[str]:
    """The shared one-line-per-peak rendering both surfaces (run-report's
    resources section, chain-profile) print — one home so a new peak
    field cannot appear on one surface and silently drop from the other."""
    lines = []
    if peaks.get("rss_bytes"):
        lines.append(f"peak rss: {peaks['rss_bytes'] / 1e6:.0f} MB")
    if peaks.get("pool_outstanding_bytes"):
        lines.append(
            "peak pool outstanding: "
            f"{peaks['pool_outstanding_bytes'] / 1e6:.0f} MB"
        )
    for q, d in sorted(peaks.get("queue_depths", {}).items()):
        lines.append(f"peak queue depth {q}: {int(d)}")
    if peaks.get("device_memory_bytes"):
        lines.append(
            f"peak device memory: {peaks['device_memory_bytes'] / 1e6:.0f} MB"
        )
    for dev_label, val in sorted(
            peaks.get("device_memory_by_device", {}).items()):
        lines.append(
            f"peak device memory {dev_label}: {val / 1e6:.0f} MB")
    return lines


def resource_peaks(timeseries: dict) -> dict:
    """Peaks of a resource timeseries (a loaded resources_<ts>.json or a
    raw {"samples": [...]}). Stored peak fields are preferred, samples
    are the fallback — the single home both renderers (report's
    resources section, chain-profile) draw from."""
    samples = timeseries.get("samples", [])
    peaks: dict = {}
    rss = timeseries.get("peak_rss_bytes") or max(
        (s.get("rss_bytes") or 0 for s in samples), default=0
    )
    if rss:
        peaks["rss_bytes"] = rss
    pool = timeseries.get("peak_pool_outstanding_bytes")
    if pool is None:
        pool = max(
            (s.get("pool_outstanding_bytes", 0) for s in samples), default=0
        )
    if pool:
        peaks["pool_outstanding_bytes"] = pool
    queues = timeseries.get("peak_queue_depths")
    if queues is None:
        queues = {}
        for s in samples:
            for q, d in s.get("queues", {}).items():
                queues[q] = max(queues.get(q, 0), d)
    if queues:
        peaks["queue_depths"] = dict(queues)
    dev = max(
        (s.get("device_memory", {}).get("peak_bytes_in_use", 0)
         for s in samples), default=0,
    )
    if dev:
        peaks["device_memory_bytes"] = dev
    per_device: dict = {}
    for s in samples:
        for dev_label, stats in s.get("device_memory_by_device",
                                      {}).items():
            per_device[dev_label] = max(
                per_device.get(dev_label, 0),
                stats.get("peak_bytes_in_use", 0))
    if per_device:
        peaks["device_memory_by_device"] = per_device
    return peaks


class ResourceMonitor:
    """Sampling thread recording `sample_resources()` as a bounded
    timeseries. `max_samples` caps host memory (a week-long run keeps
    the most recent window, and the gauges always carry the current
    values); `interval_s` is clamped to >= 0.05 so a typo cannot turn
    the monitor into a busy loop."""

    def __init__(self, interval_s: float = 1.0, max_samples: int = 7200) -> None:
        self.interval_s = max(0.05, float(interval_s))
        self._samples: deque = deque(maxlen=max(1, int(max_samples)))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0_perf = time.perf_counter()
        self._cpu = _CpuTracker()  # private interval, immune to /status polls

    def _sample_once(self) -> None:
        now = time.perf_counter()
        try:
            sample = sample_resources(cpu=self._cpu)
        except Exception:  # noqa: BLE001 - monitoring must never kill a run
            return
        sample["t"] = round(now - self._t0_perf, 3)
        sample["t_perf"] = now
        self._samples.append(sample)

    def start(self) -> "ResourceMonitor":
        if self._thread is None:
            self._stop.clear()
            self._t0_perf = time.perf_counter()
            self._sample_once()  # a run shorter than one interval still records

            def loop() -> None:
                while not self._stop.wait(self.interval_s):
                    self._sample_once()

            self._thread = threading.Thread(
                target=loop, name="chain-resource-monitor", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
            self._sample_once()  # final snapshot: how the run ended

    def samples(self) -> list[dict]:
        return list(self._samples)

    def to_timeseries(self) -> dict:
        samples = self.samples()
        out = {
            "schema": 1,
            "interval_s": self.interval_s,
            "n_samples": len(samples),
            "samples": [
                {k: v for k, v in s.items() if k != "t_perf"} for s in samples
            ],
        }
        peaks = resource_peaks({"samples": samples})
        if "rss_bytes" in peaks:
            out["peak_rss_bytes"] = peaks["rss_bytes"]
        if "pool_outstanding_bytes" in peaks:
            out["peak_pool_outstanding_bytes"] = peaks["pool_outstanding_bytes"]
        if "queue_depths" in peaks:
            out["peak_queue_depths"] = peaks["queue_depths"]
        return out

    def write_json(self, path: str) -> str:
        from ..utils.fsio import atomic_write

        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        doc = self.to_timeseries()

        def write(tmp: str) -> None:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)

        atomic_write(path, write)  # a teardown SIGKILL must not leave a torn file
        return path

    def __enter__(self) -> "ResourceMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# --------------------------------------------------------- merged timeline

#: event kinds worth a timeline marker (the queue-depth sampler alone
#: could contribute thousands of records that say nothing a counter
#: track doesn't)
_TRACE_EVENT_KINDS = (
    "stage_start", "stage_end", "job_start", "job_end", "device_step",
    "task_stalled", "task_hard_timeout", "task_recovered", "barrier_wait",
    "mesh_compile", "dist_init", "dist_collective",
)


def _span_lane(name: str) -> tuple[str, str]:
    """(category, display name) for one span. Device-step and transfer
    spans get their own lanes so the timeline reads decode | compute |
    transfer | encode at a glance."""
    for prefix, cat in (
        ("device:", "device"),
        ("transfer:", "transfer"),
        ("prefetch:", "decode"),
        ("writeback:", "encode"),
    ):
        if name.startswith(prefix):
            return cat, name[len(prefix):]
    return "host", name


def build_chrome_trace(
    spans: Sequence,
    events: Iterable[dict] = (),
    resources: Iterable[dict] = (),
    events_offset_s: float = 0.0,
    tracer_t0_perf: Optional[float] = None,
) -> dict:
    """Merge host spans (`utils.tracing.Span` objects — device-step spans
    included, same perf_counter clock), selected event-log records, and
    resource samples into one Chrome-trace document.

    `events_offset_s` maps event timestamps (relative to the event log's
    t0) onto the tracer clock: `EVENTS t0_perf - tracer t0_perf`.
    Resource samples carry an absolute `t_perf`; `tracer_t0_perf` maps
    them the same way. All timestamps clamp at 0 (an event emitted
    before the tracer was reset cannot produce a negative tick)."""
    pid = os.getpid()
    trace_events: list[dict] = []
    tids: dict[str, int] = {}

    def tid_for(thread: str, cat: str) -> int:
        # device/transfer lanes render as their own pseudo-threads so the
        # viewer shows host rows and device rows separately even though
        # the recording thread is a host thread
        key = f"{cat}:{thread}" if cat in ("device", "transfer") else thread
        if key not in tids:
            tids[key] = len(tids) + 1
            trace_events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tids[key], "args": {"name": key},
            })
        return tids[key]

    for span in spans:
        cat, name = _span_lane(span.name)
        ev = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "pid": pid,
            "tid": tid_for(span.thread, cat),
            "ts": max(0, int(span.start * 1e6)),
            "dur": max(1, int(span.duration * 1e6)),
        }
        if span.meta:
            # same primitive filter as event args: span(**meta) accepts
            # arbitrary values, and one Path/ndarray must not make the
            # whole document unserializable at run teardown
            args = {
                k: v for k, v in span.meta.items()
                if isinstance(v, (str, int, float, bool))
            }
            if args:
                ev["args"] = args
        trace_events.append(ev)

    for rec in events:
        kind = rec.get("event")
        if kind not in _TRACE_EVENT_KINDS:
            continue
        ts = max(0.0, float(rec.get("t", 0.0)) + events_offset_s)
        args = {
            k: v for k, v in rec.items()
            if k not in ("event", "t") and isinstance(v, (str, int, float, bool))
        }
        trace_events.append({
            "name": kind, "cat": "events", "ph": "i", "s": "p",
            "pid": pid, "tid": tid_for("events", "events"),
            "ts": int(ts * 1e6), "args": args,
        })

    counter_tid = None
    for sample in resources:
        t_perf = sample.get("t_perf")
        if t_perf is None or tracer_t0_perf is None:
            continue
        ts = max(0, int((t_perf - tracer_t0_perf) * 1e6))
        if counter_tid is None:
            counter_tid = tid_for("resources", "resources")
        counters = {
            "rss_mb": round((sample.get("rss_bytes") or 0) / 1e6, 1),
            "pool_outstanding_mb": round(
                sample.get("pool_outstanding_bytes", 0) / 1e6, 1
            ),
        }
        for queue, depth in sample.get("queues", {}).items():
            counters[f"queue_{queue}"] = depth
        for name, value in counters.items():
            trace_events.append({
                "name": name, "cat": "resources", "ph": "C",
                "pid": pid, "tid": counter_tid, "ts": ts,
                "args": {"value": value},
            })

    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"producer": "processing_chain_tpu --profile"},
    }


def device_annotation(name: str):
    """`jax.profiler.TraceAnnotation` when available (so a live
    jax.profiler capture labels the dispatch), else a no-op context."""
    from contextlib import nullcontext

    if not _ACTIVE:
        return nullcontext()
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # noqa: BLE001 - annotation is decoration, never load-bearing
        return nullcontext()


# ------------------------------------------------------------- attribution


def components_from_metrics(metrics: dict) -> tuple[dict[str, float], list[str]]:
    """(component seconds, missing components) from a metrics snapshot
    (the live `REGISTRY.snapshot()` or a loaded metrics_<ts>.json — same
    shape). A component is MISSING when its metric has no series at all
    (e.g. no device ever dispatched); a present metric at 0.0 is a real
    measurement."""
    def series(name: str) -> list[dict]:
        return metrics.get(name, {}).get("series", [])

    def total(name: str, labels: Optional[dict]) -> float:
        out = 0.0
        for s in series(name):
            if labels is None or s.get("labels", {}) == labels:
                out += float(s.get("value", s.get("sum", 0.0)))
        return out

    components: dict[str, float] = {}
    missing: list[str] = []
    for comp, (metric, labels) in COMPONENT_METRICS.items():
        has = any(
            labels is None or s.get("labels", {}) == labels
            for s in series(metric)
        )
        if has:
            components[comp] = round(total(metric, labels), 4)
        else:
            missing.append(comp)
    return components, missing


def components_from_live() -> tuple[dict[str, float], list[str]]:
    """Current component seconds straight from the live registry
    (targeted per-metric reads — never a full snapshot under the
    registry lock). Components whose metric has no series are in the
    missing list, same contract as `components_from_metrics` —
    `telemetry.stage_span` diffs this across a stage so stage_end
    events carry measured deltas only, and never-recorded components
    stay distinguishable as *unmeasured* per stage."""
    components: dict[str, float] = {}
    missing: list[str] = []
    for comp, (metric, labels) in COMPONENT_METRICS.items():
        total = REGISTRY.sum_series(metric, labels)
        if total is None:
            missing.append(comp)
        else:
            components[comp] = round(total, 4)
    return components, missing


def classify_components(
    components: dict[str, Optional[float]],
    missing: Iterable[str] = (),
    min_total_s: float = 0.05,
    dominance: float = 0.4,
    lead: float = 1.5,
) -> dict:
    """Pure bottleneck classifier. `components` maps component name ->
    measured seconds (None entries are treated as missing). The verdict
    is `<top>_bound` when the top contributor holds >= `dominance` of
    the measured total AND leads the runner-up by `lead`x; anything
    flatter is `balanced`. A measured total under `min_total_s` is
    `balanced` with `insufficient_data` set — there is nothing to
    attribute, and the report says so instead of inventing a verdict."""
    present = {
        k: max(0.0, float(v)) for k, v in components.items() if v is not None
    }
    missing = sorted(set(missing) | (set(components) - set(present)))
    total = sum(present.values())
    contributors = sorted(present.items(), key=lambda kv: -kv[1])
    out = {
        "components_s": {k: round(v, 4) for k, v in present.items()},
        "missing": missing,
        "total_s": round(total, 4),
    }
    pct = [
        {"component": name, "seconds": round(sec, 4),
         "pct": round(100.0 * sec / total, 1)}
        for name, sec in contributors
    ] if total > 1e-9 else []
    out["contributors"] = pct
    if total < min_total_s or not contributors:
        # nothing substantial to attribute: the percentages (if any) are
        # still reported, but no *_bound verdict is invented from noise
        out["verdict"] = "balanced"
        out["insufficient_data"] = True
        return out
    top_name, top_sec = contributors[0]
    runner_up = contributors[1][1] if len(contributors) > 1 else 0.0
    if top_sec / total >= dominance and top_sec >= lead * max(runner_up, 1e-9):
        out["verdict"] = f"{top_name}_bound"
    else:
        out["verdict"] = "balanced"
    return out


def attribute_run(metrics: dict, events: Sequence[dict]) -> dict[str, dict]:
    """Per-stage verdicts for one run. Prefers the per-stage component
    deltas `stage_span` embeds in stage_end events; a run without them
    (older artifacts, single-layer runs) degrades to ONE whole-run
    verdict from the global metrics under the pseudo-stage "run"."""
    verdicts: dict[str, dict] = {}
    for rec in events:
        if rec.get("event") != "stage_end":
            continue
        comps = rec.get("components")
        if not isinstance(comps, dict):
            continue
        stage = rec.get("stage", "?")
        # components absent from the event were unmeasured for the whole
        # stage (no series existed) — report them as such, not as zeros
        reattributed = False
        if rec.get("decoder_opens") == 0 and comps.get("decode"):
            # consumer-blocked seconds in a stage that opened ZERO
            # decoders cannot be decode time: the stage consumed
            # in-memory streams (the fused p04 fan-out renders CPVS
            # from device-resident frames) and the waits are pipeline
            # plumbing. Without this gate a fused run's p03/p04 stages
            # could report decode_bound on a decode that never happened
            # — the exact verdict the fusion exists to retire.
            comps = dict(comps, decode=0.0)
            reattributed = True
        result = classify_components(
            comps, missing=set(COMPONENT_METRICS) - set(comps)
        )
        if reattributed:
            result["decode_reattributed"] = True
        result["wall_s"] = rec.get("duration_s")
        verdicts[stage] = result
    if not verdicts and metrics:
        components, missing = components_from_metrics(metrics)
        verdicts["run"] = classify_components(components, missing)
    # bucket-fragmentation input (parallel/meshobs.py): a run whose
    # device time is mostly padding has no dominant component to blame —
    # the flat profile IS the symptom, and "balanced" would bury it
    waste = mesh_waste_from_metrics(metrics) if metrics else None
    if waste is not None:
        for result in verdicts.values():
            result["mesh_waste_fraction"] = waste
            if (result.get("verdict") == "balanced"
                    and not result.get("insufficient_data")
                    and waste >= FRAGMENTATION_WASTE_THRESHOLD):
                result["verdict"] = "fragmentation_bound"
    return verdicts


def mesh_waste_from_metrics(metrics: dict) -> Optional[float]:
    """Padded-slot fraction of all dispatched wave slots, from the
    chain_mesh_wave_slots_total series of a metrics snapshot. None when
    the wave driver never dispatched (no series) — absence of evidence,
    not a 0.0 measurement."""
    series = metrics.get("chain_mesh_wave_slots_total",
                         {}).get("series", [])
    valid = padded = 0.0
    for s in series:
        kind = s.get("labels", {}).get("kind")
        value = float(s.get("value", 0.0))
        if kind == "valid":
            valid += value
        elif kind:
            padded += value
    total = valid + padded
    if total <= 0:
        return None
    return round(padded / total, 4)


# ------------------------------------------------------------ orchestration


class Profiler:
    """`--profile DIR` driver: resource monitor + best-effort jax.profiler
    capture while the run is in flight; `stop(stamp)` persists

        profile_<stamp>.trace.json    merged Chrome trace (host + device)
        resources_<stamp>.json        the resource timeseries

    into DIR, plus whatever jax.profiler wrote under DIR/device_<stamp>
    on accelerator backends. Start/stop are idempotent and never raise:
    profiling is diagnosis, not a new way to fail a run."""

    def __init__(
        self, out_dir: str, interval_s: float = 1.0,
        device_trace: Optional[bool] = None,
    ) -> None:
        self.out_dir = out_dir
        self.monitor = ResourceMonitor(interval_s=interval_s)
        self._jax_trace_dir: Optional[str] = None
        self._started = False
        #: None = auto (accelerator backends only); False = never — the
        #: CLI passes False when `--trace DIR` already owns the single
        #: process-wide jax.profiler session (two start_trace calls
        #: collide, and the operator asked for the capture THERE)
        self._device_trace = device_trace

    def _want_device_trace(self) -> bool:
        if self._device_trace is not None:
            return self._device_trace
        forced = os.environ.get("PC_PROFILE_DEVICE", "").strip().lower()
        if forced in ("1", "on", "true"):
            return True
        if forced in ("0", "off", "false"):
            return False
        # default: only where there is device activity worth the capture
        # overhead — CPU runs take the host-only fallback
        jax_mod = sys.modules.get("jax")
        try:
            return jax_mod is not None and any(
                d.platform not in ("cpu",) for d in jax_mod.local_devices()
            )
        except Exception:  # noqa: BLE001 - backend probing must not break start
            return False

    def start(self, stamp: str) -> "Profiler":
        global _ACTIVE
        if self._started:
            return self
        self._started = True
        _ACTIVE = True
        os.makedirs(self.out_dir, exist_ok=True)
        self.monitor.start()
        if self._want_device_trace():
            trace_dir = os.path.join(self.out_dir, f"device_{stamp}")
            try:
                import jax

                jax.profiler.start_trace(trace_dir)
                self._jax_trace_dir = trace_dir
            except Exception as exc:  # noqa: BLE001 - host-only fallback
                from ..utils.log import get_logger

                get_logger().warning(
                    "jax.profiler unavailable (%s) — host-only profile", exc
                )
        return self

    def stop(self, stamp: str) -> dict[str, str]:
        global _ACTIVE
        if not self._started:
            return {}
        self._started = False
        _ACTIVE = False
        self.monitor.stop()
        if self._jax_trace_dir is not None:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
        paths: dict[str, str] = {}
        try:
            paths["resources"] = self.monitor.write_json(
                os.path.join(self.out_dir, f"resources_{stamp}.json")
            )
        except OSError:
            pass
        try:
            from ..utils import tracing

            from .events import EVENTS

            tracer = tracing.get_tracer()
            doc = build_chrome_trace(
                tracer.spans(),
                events=EVENTS.records(),
                resources=self.monitor.samples(),
                events_offset_s=EVENTS._t0_perf - tracer._t0,
                tracer_t0_perf=tracer._t0,
            )
            from ..utils.fsio import atomic_write

            path = os.path.join(self.out_dir, f"profile_{stamp}.trace.json")

            def write(tmp: str) -> None:
                with open(tmp, "w") as f:
                    json.dump(doc, f)

            # atomic: a torn trace under the LATEST stamp would break
            # chain-profile's default-stamp path even with older intact
            # captures present
            atomic_write(path, write)
            paths["trace"] = path
        except (OSError, TypeError, ValueError):
            # the never-raise contract: a teardown serialization surprise
            # must not replace the run's own outcome
            pass
        if self._jax_trace_dir is not None:
            paths["device_trace_dir"] = self._jax_trace_dir
            self._jax_trace_dir = None
        return paths
