"""Aggregate one run's telemetry artifacts into a human-readable report.

Joins the three `--telemetry DIR` outputs (metrics_<ts>.json,
events_<ts>.jsonl, metrics_<ts>.prom) with the span report
(trace_<ts>.json) under the same stamp and renders:

  * run header (stage selection, status, wall time),
  * per-stage throughput table (frames decoded/encoded, frames/sec, MB/s),
  * job accounting per runner (planned / skipped / deduped / failed / redone),
  * top wall-time spans,
  * pipeline stall diagnosis from queue-depth samples + blocked-time
    counters (starved consumer vs. backed-up producer).

Entry point: tools/run_report.py (repo root) or
`python -m processing_chain_tpu.telemetry.report DIR`.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Optional, Sequence

from .events import read_jsonl

_STAMP_RE = re.compile(r"metrics_(?P<stamp>.+)\.json$")
_EVENTS_STAMP_RE = re.compile(r"events_(?P<stamp>.+)\.jsonl$")


@dataclass
class RunData:
    directory: str
    stamp: str
    metrics: dict = field(default_factory=dict)
    events: list = field(default_factory=list)
    trace: dict = field(default_factory=dict)
    #: resources_<ts>.json timeseries when the run carried --profile
    resources: dict = field(default_factory=dict)
    #: events JSONL present but no metrics snapshot: the run crashed (or
    #: is still in flight) before telemetry.write_outputs persisted it
    partial: bool = False


class ReportError(ValueError):
    """Raised when a run directory has no loadable telemetry artifacts."""


def list_stamps(directory: str) -> list[str]:
    """Run stamps in the directory, oldest first. Ordered by artifact
    mtime, not stamp text: stamps embed an unpadded pid/sequence, so a
    lexicographic sort could call an older run 'latest'. Stamps with
    only a (streamed) events file — a run still in flight, or one that
    crashed before its metrics snapshot — are included: run-report must
    be able to answer for exactly those runs."""
    entries = []
    seen = set()
    for pattern, regex in (
        ("metrics_*.json", _STAMP_RE),
        ("events_*.jsonl", _EVENTS_STAMP_RE),
    ):
        for path in glob.glob(os.path.join(directory, pattern)):
            m = regex.search(os.path.basename(path))
            if m and m.group("stamp") not in seen:
                try:
                    mtime = os.path.getmtime(path)
                except OSError:
                    continue
                seen.add(m.group("stamp"))
                entries.append((mtime, m.group("stamp")))
    return [stamp for _, stamp in sorted(entries)]


def load_run(directory: str, stamp: Optional[str] = None) -> RunData:
    """Load the artifacts of one run (latest stamp unless given). A
    stamp whose metrics snapshot is absent but whose events JSONL exists
    loads as a PARTIAL run (crashed or still in flight) instead of
    raising — the events are exactly the forensics an operator needs."""
    if not os.path.isdir(directory):
        raise ReportError(f"not a directory: {directory}")
    stamps = list_stamps(directory)
    if stamp is None:
        if not stamps:
            raise ReportError(
                f"no metrics_<ts>.json (or events_<ts>.jsonl) in "
                f"{directory} — was the run started with --telemetry?"
            )
        stamp = stamps[-1]
    elif stamp not in stamps:
        raise ReportError(f"no metrics_{stamp}.json in {directory}")
    run = RunData(directory=directory, stamp=stamp)
    metrics_path = os.path.join(directory, f"metrics_{stamp}.json")
    events_path = os.path.join(directory, f"events_{stamp}.jsonl")
    if os.path.isfile(metrics_path):
        with open(metrics_path) as f:
            run.metrics = json.load(f)
    elif os.path.isfile(events_path):
        run.partial = True
    else:
        raise ReportError(f"no artifacts for stamp {stamp} in {directory}")
    if os.path.isfile(events_path):
        run.events = read_jsonl(events_path)
    trace_path = os.path.join(directory, f"trace_{stamp}.json")
    if os.path.isfile(trace_path):
        with open(trace_path) as f:
            run.trace = json.load(f)
    resources_path = os.path.join(directory, f"resources_{stamp}.json")
    if os.path.isfile(resources_path):
        try:
            with open(resources_path) as f:
                run.resources = json.load(f)
        except (OSError, ValueError):
            pass  # a torn/unreadable profile sidecar must not sink the report
    return run


# ------------------------------------------------------------- accessors


def _series(run: RunData, name: str) -> list[dict]:
    return run.metrics.get(name, {}).get("series", [])


def _value(run: RunData, name: str, **labels) -> float:
    for s in _series(run, name):
        if s.get("labels", {}) == labels or not labels:
            return float(s.get("value", s.get("sum", 0.0)))
    return 0.0


def _by_label(run: RunData, name: str, label: str) -> dict[str, dict]:
    out: dict[str, dict] = {}
    for s in _series(run, name):
        out[s["labels"].get(label, "")] = s
    return out


def _events(run: RunData, kind: str) -> list[dict]:
    return [e for e in run.events if e.get("event") == kind]


# -------------------------------------------------------------- sections


def _fmt_table(header: Sequence[str], rows: list[Sequence[str]]) -> list[str]:
    widths = [len(h) for h in header]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()
    out = [line(header), line("-" * w for w in widths)]
    out.extend(line(r) for r in rows)
    return out


def _header_section(run: RunData) -> list[str]:
    lines = [f"run {run.stamp}  ({run.directory})"]
    starts = _events(run, "run_start")
    ends = _events(run, "run_end")
    if starts:
        s = starts[0]
        lines.append(
            f"  command: {s.get('name', '?')}  argv: {' '.join(s.get('argv', []))}"
        )
    if ends:
        e = ends[-1]
        lines.append(
            f"  status: {e.get('status', '?')}  wall: {e.get('duration_s', '?')}s"
        )
    elif run.partial:
        last_t = run.events[-1].get("t", "?") if run.events else "?"
        lines.append(
            "  status: RUN DID NOT COMPLETE (events streamed, no metrics "
            f"snapshot) — crashed or still in flight; last event at "
            f"t={last_t}s"
        )
    return lines


def _partial_section(run: RunData) -> list[str]:
    """Forensics for a run without an end: which jobs started but never
    ended, and any watchdog incidents the stream captured."""
    started = {e.get("job"): e for e in _events(run, "job_start")}
    ended = {e.get("job") for e in _events(run, "job_end")}
    open_jobs = [j for j in started if j not in ended]
    lines = []
    if open_jobs:
        last_t = run.events[-1].get("t", 0.0) if run.events else 0.0
        lines.append(f"jobs started but never finished ({len(open_jobs)}):")
        for job in open_jobs[:10]:
            t_start = started[job].get("t", 0.0)
            lines.append(
                f"  {job}  (started t={t_start}s, "
                f"{float(last_t) - float(t_start):.1f}s before the stream ended)"
            )
    incidents = (
        _events(run, "task_stalled") + _events(run, "task_hard_timeout")
        + _events(run, "barrier_wait")
    )
    if incidents:
        lines.append(f"watchdog/barrier incidents ({len(incidents)}):")
        for e in incidents[:10]:
            desc = e.get("task") or f"missing {e.get('missing')}"
            lines.append(
                f"  t={e.get('t')}s {e['event']}: {desc} "
                f"(no progress for {e.get('beat_age_s', e.get('waited_s', '?'))}s)"
            )
        lines.append(
            "  (full stack dumps are in the task_stalled/task_hard_timeout "
            "event records)"
        )
    if not lines:
        lines.append("no in-flight jobs captured before the stream ended")
    return lines


def _stage_section(run: RunData) -> list[str]:
    stage_ends = _events(run, "stage_end")
    if not stage_ends:
        starts = _events(run, "stage_start")
        if starts and run.partial:
            return [
                f"stage {s.get('stage', '?')} started at t={s.get('t')}s "
                "and never ended" for s in starts
            ]
        return ["no stage_end events (single-layer run?)"]
    rows = []
    for e in stage_ends:
        wall = float(e.get("duration_s", 0.0)) or 1e-9
        frames = float(e.get("frames_encoded", 0.0))
        dec = float(e.get("frames_decoded", 0.0))
        mb = float(e.get("bytes_encoded", 0.0)) / 1e6
        rows.append((
            e.get("stage", "?"),
            e.get("status", "?"),
            f"{wall:.2f}",
            f"{int(dec)}",
            f"{int(frames)}",
            f"{frames / wall:.1f}",
            f"{mb / wall:.1f}",
        ))
    return _fmt_table(
        ("stage", "status", "wall_s", "frames_dec", "frames_enc",
         "frames/s", "MB/s"),
        rows,
    )


def _jobs_section(run: RunData) -> list[str]:
    names = {
        "planned": "chain_jobs_planned_total",
        "skipped": "chain_jobs_skipped_total",
        "deduped": "chain_jobs_deduped_total",
        "failed": "chain_jobs_failed_total",
    }
    per_runner: dict[str, dict[str, int]] = {}
    for col, metric in names.items():
        for runner, s in _by_label(run, metric, "runner").items():
            per_runner.setdefault(runner, {})[col] = int(s.get("value", 0))
    # chain-wide (the redo decision predates runner attribution)
    redone = int(_value(run, "chain_jobs_redone_total"))
    if not per_runner and not redone:
        return ["no job counters recorded"]
    rows = [
        (runner, *(per_runner[runner].get(c, 0) for c in names))
        for runner in sorted(per_runner)
    ]
    lines = _fmt_table(("runner", *names), rows) if rows else []
    if redone:
        lines.append(f"redone over crash sentinels (chain-wide): {redone}")
    return lines


def _spans_section(run: RunData, top: int = 10) -> list[str]:
    summary = run.trace.get("summary", {})
    if not summary:
        return ["no span report (trace_<ts>.json missing)"]
    items = sorted(summary.items(), key=lambda kv: -kv[1]["total_s"])[:top]
    rows = [
        (name[:56], e["count"], f"{e['total_s']:.3f}", f"{e['max_s']:.3f}")
        for name, e in items
    ]
    return _fmt_table(("span", "count", "total_s", "max_s"), rows)


def _serve_section(run: RunData, top: int = 15) -> list[str]:
    """Serve requests with their trace context: `serve_request` joined
    to `serve_request_done` by request id, trace id included so `tools
    trace show <trace-id>` picks up exactly where the report leaves
    off (docs/TELEMETRY.md "Fleet observability & tracing")."""
    accepted = _events(run, "serve_request")
    done = {e.get("request"): e
            for e in _events(run, "serve_request_done")}
    if not accepted and not done:
        return []
    rows = []
    for e in accepted[-top:]:
        req = e.get("request", "?")
        end = done.get(req, {})
        outcome = end.get("status", "in-flight")
        if end.get("warm"):
            outcome += " (warm)"
        dur = end.get("duration_s")
        rows.append((
            req, e.get("trace_id", "-") or "-",
            f"{e.get('tenant', '?')}/{e.get('priority', '?')}",
            e.get("units", "?"), outcome,
            f"{dur:.3f}" if dur is not None else "-",
        ))
    lines = _fmt_table(
        ("request", "trace", "tenant/priority", "units", "outcome", "s"),
        rows,
    )
    unmatched = sorted(set(done) - {e.get("request") for e in accepted})
    if unmatched:
        lines.append(f"settled without an accept event in this log "
                     f"(peer-replica executions): {len(unmatched)}")
    return lines


def _queue_stats(run: RunData) -> dict[str, dict]:
    """{queue: {samples, mean_depth}} from the depth histogram."""
    out = {}
    for queue, s in _by_label(run, "chain_queue_depth", "queue").items():
        n = int(s.get("count", 0))
        out[queue] = {
            "samples": n,
            "mean_depth": (float(s.get("sum", 0.0)) / n) if n else 0.0,
        }
    return out


def _stall_section(run: RunData) -> list[str]:
    queues = _queue_stats(run)
    waits = {
        side: float(s.get("value", 0.0))
        for side, s in _by_label(
            run, "chain_pipeline_wait_seconds_total", "side"
        ).items()
    }
    if not queues and not waits:
        return ["no pipeline samples (no prefetch activity in this run)"]
    lines = []
    for queue, st in sorted(queues.items()):
        lines.append(
            f"  queue {queue}: {st['samples']} samples, "
            f"mean depth {st['mean_depth']:.2f}"
        )
    for side, total in sorted(waits.items()):
        lines.append(f"  blocked on {side}: {total:.2f}s total")
    # diagnosis: a consumer repeatedly finding its decode queue empty is
    # starved (decode-bound run); a producer blocked pushing into a full
    # encode queue means writeback can't keep up (encode-bound run).
    consumer_wait = waits.get("consumer", 0.0)
    producer_wait = waits.get("producer", 0.0)
    decode_depth = queues.get("decode", {}).get("mean_depth")
    encode_depth = queues.get("encode", {}).get("mean_depth")
    if decode_depth is not None and decode_depth < 0.5 and consumer_wait > max(
        1.0, 2 * producer_wait
    ):
        lines.append(
            "  diagnosis: consumer starved (decode queue mostly empty, "
            "device/compute waiting on decode) — raise decode workers or "
            "prefetch depth"
        )
    elif encode_depth is not None and encode_depth >= 2.0 and producer_wait > max(
        1.0, 2 * consumer_wait
    ):
        lines.append(
            "  diagnosis: producer blocked (encode queue full, writeback "
            "can't keep up) — raise FFV1 workers or writer depth"
        )
    else:
        lines.append("  diagnosis: no stall signature (pipeline balanced)")
    return lines


def _host_path_section(run: RunData) -> list[str]:
    """The PR 4 host frame path: buffer-pool recycling, chunk-granular
    native I/O crossings, and host<->device transfer volume — the
    metrics that explain whether the batched path was actually engaged."""
    hits = _value(run, "chain_bufpool_hits_total")
    misses = _value(run, "chain_bufpool_misses_total")
    recycled = _value(run, "chain_bufpool_recycled_bytes_total")
    io_calls = _by_label(run, "chain_io_batch_calls_total", "op")
    xfer_s = _by_label(run, "chain_device_transfer_seconds_total", "direction")
    xfer_b = _by_label(run, "chain_device_transfer_bytes_total", "direction")
    if not (hits or misses or io_calls or xfer_s):
        return []
    lines = []
    if hits or misses:
        rate = hits / max(1.0, hits + misses)
        lines.append(
            f"  buffer pool: {int(hits)} hits / {int(misses)} misses "
            f"(hit rate {rate:.2f}), {recycled / 1e6:.1f} MB recycled"
        )
        if rate < 0.25 and hits + misses >= 8:
            lines.append(
                "    note: low hit rate — chunk geometries churn faster "
                "than the free lists recycle (mixed resolutions?)"
            )
    decoded = _value(run, "chain_frames_decoded_total")
    encoded = _value(run, "chain_frames_encoded_total")
    for op, s in sorted(io_calls.items()):
        calls = float(s.get("value", 0.0))
        if not calls:
            continue
        frames = decoded if op == "decode" else encoded
        lines.append(
            f"  native {op} crossings: {int(calls)} "
            f"(~{frames / calls:.1f} frames per GIL release)"
        )
    if not io_calls and (decoded or encoded):
        lines.append(
            "  no batched native I/O crossings — per-frame fallback "
            "(PC_HOST_BATCH=0 or a non-batch reader/writer)"
        )
    for direction, s in sorted(xfer_s.items()):
        seconds = float(s.get("value", 0.0))
        mb = float(xfer_b.get(direction, {}).get("value", 0.0)) / 1e6
        if seconds or mb:
            lines.append(
                f"  device {direction}: {mb:.1f} MB in {seconds:.2f}s"
                + (f" ({mb / seconds:.0f} MB/s)" if seconds > 1e-9 else "")
            )
    return lines


def _attribution_section(run: RunData) -> list[str]:
    """Per-stage bottleneck verdicts from the attribution engine
    (telemetry/profiling.py): stage_end component deltas when present,
    else one whole-run verdict from the global metrics."""
    from .profiling import attribute_run

    verdicts = attribute_run(run.metrics, run.events)
    if not verdicts:
        return []
    lines = []
    for stage, v in verdicts.items():
        contributors = ", ".join(
            f"{c['component']} {c['pct']}% ({c['seconds']:.2f}s)"
            for c in v["contributors"]
        )
        if v.get("insufficient_data"):
            lines.append(
                f"  {stage}: balanced (insufficient data — measured "
                f"components total {v['total_s']:.3f}s"
                + (f"; {contributors}" if contributors else "") + ")"
            )
        else:
            line = f"  {stage}: {v['verdict']} — {contributors}"
            if v["verdict"] == "fragmentation_bound":
                line += (f" (mesh waste "
                         f"{v.get('mesh_waste_fraction', 0.0):.1%} — "
                         "see the mesh efficiency section / "
                         "`tools mesh-top`)")
            lines.append(line)
        if v.get("missing"):
            lines.append(
                f"    unmeasured: {', '.join(v['missing'])} (no series "
                "recorded — component idle or instrumentation not on this "
                "path)"
            )
    return lines


def _resources_section(run: RunData) -> list[str]:
    """Peaks from the --profile resource timeseries when present, else
    the last-known resource gauges from the metrics snapshot."""
    lines = []
    res = run.resources
    if res:
        from .profiling import format_resource_peaks, resource_peaks

        lines.append(
            f"  {res.get('n_samples', 0)} samples @ "
            f"{res.get('interval_s', '?')}s"
        )
        lines.extend(f"  {l}" for l in format_resource_peaks(resource_peaks(res)))
        return lines
    rss = _value(run, "chain_resource_rss_bytes")
    if rss:
        lines.append(f"  last rss: {rss / 1e6:.0f} MB")
        pool_out = _value(run, "chain_bufpool_outstanding_bytes")
        pool_free = _value(run, "chain_bufpool_free_bytes")
        if pool_out or pool_free:
            lines.append(
                f"  pool bytes: {pool_out / 1e6:.0f} MB outstanding, "
                f"{pool_free / 1e6:.0f} MB free"
            )
    return lines


def _device_section(run: RunData) -> list[str]:
    compiles = _events(run, "device_step")
    steps = _by_label(run, "chain_device_step_seconds", "step")
    if not compiles and not steps:
        return []
    lines = ["device steps:"]
    for step, s in sorted(steps.items()):
        n = int(s.get("count", 0))
        if n:
            lines.append(
                f"  {step}: {n} dispatches, {float(s['sum']):.3f}s total"
            )
    for e in compiles:
        if e.get("first"):
            lines.append(
                f"  {e.get('step', '?')}: first dispatch (incl. compile) "
                f"{e.get('duration_s', '?')}s"
            )
    return lines


def _mesh_section(run: RunData) -> list[str]:
    """Mesh efficiency (parallel/meshobs.py): per-bucket wave occupancy,
    padding waste and the compile ledger. The run's wave journal
    (`meshobs_<stamp>/`, written alongside the event stream) is the
    preferred source — it survives crashes and carries the lane→wave
    schedule; the chain_mesh_* series are the fallback for runs whose
    journal was moved or pruned."""
    journal_dir = os.path.join(run.directory, f"meshobs_{run.stamp}")
    if os.path.isdir(journal_dir):
        # lazy: meshobs itself is jax-free, but its package pulls jax —
        # only pay that when a wave journal actually exists
        from ..parallel import meshobs

        agg = meshobs.aggregate(journal_dir)
        if agg["buckets"]:
            lines = []
            for bucket, a in sorted(agg["buckets"].items()):
                lines.append(
                    f"  {bucket}: {a['waves']} wave(s), {a['valid']} valid"
                    f" + {a['pad_tail']} tail / {a['pad_exhausted']} "
                    f"exhausted / {a['pad_mesh']} mesh pad slots — waste "
                    f"{a['waste_fraction']:.1%}, {a['recompiles']} "
                    f"compile(s) ({a['compile_s']:.2f}s)"
                )
            tot = agg["totals"]
            if len(agg["buckets"]) > 1:
                lines.append(
                    f"  total: waste {tot['waste_fraction']:.1%} over "
                    f"{tot['dispatched']} dispatched slots, "
                    f"{tot['recompiles']} compile(s)"
                )
            if agg["invariant_violations"]:
                lines.append(
                    f"  !! {agg['invariant_violations']} wave record(s) "
                    "broke valid+pad == dispatched (driver accounting bug)"
                )
            lines.append(f"  journal: {journal_dir}")
            return lines
    slots = _by_label(run, "chain_mesh_wave_slots_total", "bucket")
    if not slots:
        return []
    waves = _by_label(run, "chain_mesh_waves_total", "bucket")
    recompiles = _by_label(run, "chain_mesh_recompiles_total", "bucket")
    lines = []
    for bucket in sorted(waves):
        valid = _value(run, "chain_mesh_wave_slots_total",
                       bucket=bucket, kind="valid")
        padded = sum(
            _value(run, "chain_mesh_wave_slots_total",
                   bucket=bucket, kind=kind)
            for kind in ("pad_tail", "pad_exhausted", "pad_mesh")
        )
        total = valid + padded
        waste = padded / total if total else 0.0
        n_compiles = int(float(
            recompiles.get(bucket, {}).get("value", 0)))
        lines.append(
            f"  {bucket}: "
            f"{int(float(waves[bucket].get('value', 0)))} wave(s), "
            f"{int(valid)} valid + {int(padded)} pad slots — waste "
            f"{waste:.1%}, {n_compiles} compile(s)"
        )
    return lines


def render_report(run: RunData) -> str:
    parts = [
        "\n".join(_header_section(run)),
    ]
    if run.partial:
        parts.append(
            "partial run:\n" + "\n".join(f"  {l}" for l in _partial_section(run))
        )
    parts += [
        "stage throughput:\n" + "\n".join(f"  {l}" for l in _stage_section(run)),
        "jobs:\n" + "\n".join(f"  {l}" for l in _jobs_section(run)),
        "top spans:\n" + "\n".join(f"  {l}" for l in _spans_section(run)),
        "pipeline:\n" + "\n".join(_stall_section(run)),
    ]
    serve = _serve_section(run)
    if serve:
        parts.append("serve requests:\n" + "\n".join(
            f"  {l}" for l in serve))
    attribution = _attribution_section(run)
    if attribution:
        parts.append("bottleneck attribution:\n" + "\n".join(attribution))
    host_path = _host_path_section(run)
    if host_path:
        parts.append("host frame path:\n" + "\n".join(host_path))
    resources = _resources_section(run)
    if resources:
        parts.append("resources:\n" + "\n".join(resources))
    device = _device_section(run)
    if device:
        parts.append("\n".join(device))
    mesh = _mesh_section(run)
    if mesh:
        parts.append("mesh efficiency:\n" + "\n".join(mesh))
    warnings = [
        e for e in _events(run, "log")
        if e.get("level") in ("WARNING", "ERROR", "CRITICAL")
    ]
    if warnings:
        parts.append(
            f"log anomalies ({len(warnings)}):\n" + "\n".join(
                f"  [{e['level']}] {e.get('message', '')[:100]}"
                for e in warnings[:15]
            )
        )
    return "\n\n".join(parts) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Render a human-readable report from a --telemetry DIR"
    )
    parser.add_argument("directory", help="directory holding metrics_<ts>.json etc.")
    parser.add_argument(
        "--stamp", default=None,
        help="specific run stamp (default: latest in the directory)",
    )
    parser.add_argument(
        "--list", action="store_true", help="list run stamps and exit"
    )
    args = parser.parse_args(argv)
    if args.list:
        for stamp in list_stamps(args.directory):
            print(stamp)
        return 0
    try:
        run = load_run(args.directory, args.stamp)
    except ReportError as exc:
        print(f"run-report: {exc}")
        return 1
    print(render_report(run), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
