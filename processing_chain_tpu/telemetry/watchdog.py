"""Stall watchdog: a daemon thread over the heartbeat registry.

Two thresholds, scanned every `poll_s`:

  * **soft** (default 300 s) — a task whose beat age exceeds it is
    flagged: one structured `task_stalled` event carrying an all-thread
    stack dump (the forensics that distinguish "blocked on a queue put"
    from "stuck in a native decode") plus a console warning. The flag
    re-arms when the task beats again (`task_recovered` event), so a
    task that stalls twice is reported twice.
  * **hard** (opt-in, off by default) — past it the task is *marked
    failed with forensics instead of hanging forever*: a
    `task_hard_timeout` event with the stack dump, the heartbeat is
    removed from the live set with status "timeout", and its
    `cancelled` flag is set so cooperative wait loops (the distributed
    barrier, prefetch queue puts) abort with `TaskCancelled` at their
    next check. Python cannot kill a hung native call, so cancellation
    is cooperative by design — the event log still records WHERE it
    hung either way.

Beat age measures time since the last *progress* beat (see
telemetry/heartbeat.py), so slow-but-flowing pipelines stay quiet and
genuinely wedged ones surface within one soft threshold.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Optional

from .events import emit
from .heartbeat import HEARTBEATS, HeartbeatRegistry
from ..utils import lockdebug

#: Stack dumps are bounded so one stalled scan can't blow the event
#: log's memory cap (events are capped in count, not record size).
_MAX_STACK_CHARS = 8000

DEFAULT_SOFT_S = 300.0

#: Kinds whose wait loops poll `cancelled` and abort: these the hard
#: timeout genuinely terminates, so their heartbeat is finished as
#: "timeout". Execution wrappers (job/task/device_step/runner) wrap
#: uninterruptible work — Python cannot kill it — so for those the hard
#: timeout records the same forensics and sets `cancelled`, but leaves
#: the heartbeat live: if the work does eventually finish, its real
#: outcome is recorded instead of a false "timeout" verdict.
CANCELLABLE_KINDS = frozenset({"barrier", "prefetch", "writeback"})


def dump_all_stacks(limit: int = _MAX_STACK_CHARS) -> str:
    """All-thread stack dump, bounded; names threads for readability."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        parts.append(
            f"--- thread {names.get(ident, '?')} ({ident}) ---\n"
            + "".join(traceback.format_stack(frame))
        )
    text = "\n".join(parts)
    if len(text) > limit:
        text = text[:limit] + "\n... [stack dump truncated]"
    return text


class Watchdog:
    """Daemon scanning thread. `start()`/`stop()` are idempotent; `scan()`
    is callable directly (the tests drive it with an injected clock)."""

    def __init__(self, soft_s: float = DEFAULT_SOFT_S,
                 hard_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 registry: HeartbeatRegistry = HEARTBEATS) -> None:
        self.soft_s = float(soft_s)
        self.hard_s = float(hard_s) if hard_s else None
        # scan often enough that a stall is seen well inside one soft
        # threshold, but never busier than 1 Hz
        self.poll_s = float(poll_s) if poll_s else max(1.0, self.soft_s / 10.0)
        self._registry = registry
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "Watchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="chain-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.scan()
            except Exception:  # pragma: no cover - the watchdog must
                pass  # never take the run down with it

    # --------------------------------------------------------------- scan

    def scan(self) -> list[dict]:
        """One pass over the live heartbeats; returns the incidents found
        ({"task", "incident": "stalled"|"hard_timeout", ...})."""
        from ..utils.log import get_logger

        registry = self._registry
        incidents: list[dict] = []
        # Flag decisions happen UNDER the registry lock: the previous
        # lock-free pass could set `stall_flagged` the instant after a
        # beat() cleared it (ghost-stalling a just-recovered task) and
        # read a `t_beat`/`units_done` pair mid-update. The expensive
        # work — stack dumps, events, logging — stays outside the lock.
        flagged: list[tuple] = []  # (incident, hb, age, units_done)
        with registry._lock:
            now = registry._clock()
            for hb in registry._live.values():
                if hb.kind == "stage":
                    continue  # stages stall iff their jobs do; report those
                age = now - hb.t_beat
                if self.hard_s is not None and age > self.hard_s:
                    if hb.cancelled:
                        continue  # already killed; its loop will see it
                    hb.cancelled = True
                    flagged.append(("hard_timeout", hb, age, hb.units_done))
                elif age > self.soft_s and not hb.stall_flagged:
                    hb.stall_flagged = True
                    flagged.append(("stalled", hb, age, hb.units_done))
        for incident, hb, age, units_done in flagged:
            stacks = dump_all_stacks()
            if incident == "hard_timeout":
                emit(
                    "task_hard_timeout", task=hb.label, kind=hb.kind,
                    stage=hb.stage, beat_age_s=round(age, 1),
                    units_done=units_done, hard_s=self.hard_s,
                    stacks=stacks,
                )
                if hb.kind in CANCELLABLE_KINDS:
                    registry._finish(hb, "timeout")
                    get_logger().error(
                        "watchdog: %s '%s' exceeded the hard timeout "
                        "(%.0fs without progress > %.0fs); cancelled, "
                        "forensics in the event log",
                        hb.kind, hb.label, age, self.hard_s,
                    )
                else:
                    get_logger().error(
                        "watchdog: %s '%s' exceeded the hard timeout "
                        "(%.0fs without progress > %.0fs); cannot be "
                        "interrupted — forensics recorded, left running",
                        hb.kind, hb.label, age, self.hard_s,
                    )
            else:
                emit(
                    "task_stalled", task=hb.label, kind=hb.kind,
                    stage=hb.stage, beat_age_s=round(age, 1),
                    units_done=units_done, soft_s=self.soft_s,
                    stacks=stacks,
                )
                get_logger().warning(
                    "watchdog: %s '%s' has made no progress for %.0fs "
                    "(soft threshold %.0fs) — stack dump in the event log",
                    hb.kind, hb.label, age, self.soft_s,
                )
            incidents.append({
                "task": hb.label, "incident": incident,
                "beat_age_s": age,
            })
        return incidents


def active_stalls(registry: HeartbeatRegistry = HEARTBEATS) -> list[dict]:
    """The live stall/hard-timeout episodes, with stage/task labels —
    what the serve /status section and the fleet view surface so a
    stalled replica is visible beyond its own process (the stack-dump
    events stay replica-local; this list travels). Hard-timeout
    episodes of cancellable kinds finish their heartbeat and leave the
    list; uninterruptible ones stay until the work really ends."""
    out: list[dict] = []
    with registry._lock:
        now = registry._clock()
        for hb in registry._live.values():
            if hb.kind == "stage":
                continue
            if not (hb.stall_flagged or hb.cancelled):
                continue
            out.append({
                "task": hb.label,
                "kind": hb.kind,
                "stage": hb.stage,
                "beat_age_s": round(now - hb.t_beat, 1),
                "units_done": hb.units_done,
                "incident": "hard_timeout" if hb.cancelled
                else "stalled",
            })
    out.sort(key=lambda s: -s["beat_age_s"])
    return out


_ACTIVE: Optional[Watchdog] = None  # guarded-by: _ACTIVE_LOCK
_ACTIVE_LOCK = lockdebug.make_lock("watchdog_slot")


def start_watchdog(soft_s: float = DEFAULT_SOFT_S,
                   hard_s: Optional[float] = None) -> Watchdog:
    """Process-wide watchdog slot (the CLI's entry point). Restarting
    with new thresholds replaces the previous instance."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.stop()
        _ACTIVE = Watchdog(soft_s=soft_s, hard_s=hard_s).start()
        return _ACTIVE


def stop_watchdog() -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            _ACTIVE.stop()
            _ACTIVE = None
