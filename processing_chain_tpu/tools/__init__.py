"""Standalone analysis & plotting tools (reference util/ directory).

Each module doubles as a library (importable functions) and a CLI
(`python -m processing_chain_tpu tools <name> …`):

  * src_analysis — md5 + .yaml info sidecars for SRC files
    (reference util/SRC_analysis.py)
  * complexity — CRF-23 proxy encode → complexity classes CSV
    (reference util/complexity_classification.py)
  * plots — HRC timeline / bitrate-resolution design plots
    (reference util/plot_config_{long,short}.py)
  * chain_top — refreshing terminal view of a live run's --live-port
    endpoint or --status-file (docs/TELEMETRY.md "Live monitoring")
"""
