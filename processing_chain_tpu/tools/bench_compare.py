"""bench-compare: the bench-regression guard (docs/PERF.md).

    python -m processing_chain_tpu tools bench-compare [--baseline PATH]
    python tools/bench_compare.py --from measured.json      # offline diff

Measures the host frame path fresh (`bench.py --host-bench`, the tracked
e2e-gap metric), folds in the cached kernel number (BENCH_LIVE.json —
the last measured-on-TPU figure this code reproduced), and diffs the
flat measurement set against a committed baseline (BENCH_BASELINE.json)
with per-metric tolerance bands. Exits nonzero on any regression, so CI
can refuse a PR that silently gives back the PR 4/PR 5 wins.

Band kinds (each baseline entry picks one):

  floor_frac  pass while measured >= value * (1 - tolerance) — the fps
              family; tolerances are generous because shared CI runners
              jitter, and the gate exists for collapses, not noise
  ceil_frac   pass while measured <= value * (1 + tolerance) — for
              lower-is-better metrics (seconds, bytes)
  floor_abs   pass while measured >= tolerance (absolute floor — e.g.
              the pool must actually recycle)
  exact       measured must equal value — the parity booleans

Entries with "required": false are skipped with a note when the metric
is absent (the kernel number needs a TPU-measured cache; a fresh CI
checkout has none). `--update` rewrites the baseline's values from the
current measurement, keeping every band.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from ..utils.fsio import atomic_write_json, last_json_line
from ..utils.runner import ChainError, shell

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_BASELINE = os.path.join(_REPO, "BENCH_BASELINE.json")

#: host-bench JSON fields folded into the flat measurement set
_HOST_FIELDS = (
    "decode_fps", "decode_batch_fps", "encode_fps", "encode_batch_fps",
    "decode_parity", "encode_parity", "pool_hit_rate",
)


class BenchCompareError(ValueError):
    """Unusable baseline/measurement input."""


def measure(timeout_s: float = 600.0) -> dict[str, object]:
    """Fresh flat measurement set: `bench.py --host-bench` in a child
    (pinned to the CPU backend — the host path is a host metric) plus
    the cached kernel numbers when a live TPU capture exists."""
    out: dict[str, object] = {}
    bench = os.path.join(_REPO, "bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = shell(
        [sys.executable, bench, "--host-bench"],
        check=False, timeout=timeout_s, env=env, cwd=_REPO,
    )
    host = last_json_line(proc.stdout)
    if proc.returncode != 0 or host is None:
        raise BenchCompareError(
            "bench.py --host-bench failed "
            f"(rc={proc.returncode}): {(proc.stderr or '')[-400:]}"
        )
    for field in _HOST_FIELDS:
        if field in host:
            out[f"host.{field}"] = host[field]
    # complexity: proxy re-encode vs codec priors (docs/PRIORS.md). The
    # band is optional in the baseline — a host whose libx264/native
    # boundary cannot run the bench just skips it — but when the bench
    # runs, a silent no-op (non-finite complexity) must not pass as a
    # huge speedup, so the ratio only folds in with both paths finite.
    proc = shell(
        [sys.executable, bench, "--complexity-bench"],
        check=False, timeout=timeout_s, env=env, cwd=_REPO,
    )
    cx = last_json_line(proc.stdout)
    if proc.returncode == 0 and cx is not None and cx.get("both_finite"):
        out["complexity.priors_vs_proxy"] = cx["priors_vs_proxy"]
    # fused vs staged p03+p04 (docs/PERF.md "single-decode chain"):
    # floor ≈ 1 — the fused path must not regress below the staged one
    proc = shell(
        [sys.executable, bench, "--fused-bench"],
        check=False, timeout=timeout_s, env=env, cwd=_REPO,
    )
    fb = last_json_line(proc.stdout)
    if proc.returncode == 0 and fb is not None and "fused_vs_unfused" in fb:
        out["e2e.fused_vs_unfused"] = fb["fused_vs_unfused"]
    # shared packet scan vs the separate demux passes it replaced
    # (docs/PERF.md "one shared packet scan"): floor ≈ 1 — sharing must
    # at least match paying each consumer's own pass
    proc = shell(
        [sys.executable, bench, "--sharedscan-bench"],
        check=False, timeout=timeout_s, env=env, cwd=_REPO,
    )
    sb = last_json_line(proc.stdout)
    if (proc.returncode == 0 and sb is not None
            and "sharedscan_vs_separate" in sb):
        out["e2e.sharedscan_vs_separate"] = sb["sharedscan_vs_separate"]
    # full-chain e2e vs the pinned single-core reference model
    # (`bench.py --e2e`): a real p03 run, minutes not seconds, so it
    # folds in only when the caller asks (PC_BENCH_COMPARE_E2E=1 — the
    # CI fused-smoke job does); the band stays optional for plain runs
    if os.environ.get("PC_BENCH_COMPARE_E2E"):
        proc = shell(
            [sys.executable, bench, "--e2e"],
            check=False, timeout=timeout_s, env=env, cwd=_REPO,
        )
        eb = last_json_line(proc.stdout)
        if (proc.returncode == 0 and eb is not None
                and "e2e_vs_baseline_1core" in eb):
            out["e2e.vs_baseline_1core"] = eb["e2e_vs_baseline_1core"]
    live_path = os.environ.get(
        "PC_BENCH_LIVE_FILE", os.path.join(_REPO, "BENCH_LIVE.json")
    )
    try:
        with open(live_path) as f:
            live = json.load(f)
        # the live cache stores the raw per-step time; fps and the
        # vs-baseline ratio derive exactly as bench.py main() does
        if live.get("platform") == "tpu" and float(live.get("per_step", 0)) > 0:
            fps = float(live.get("t", 8)) / float(live["per_step"])
            out["kernel.fps_per_chip"] = round(fps, 2)
            base_path = os.environ.get(
                "PC_BASELINE_FILE", os.path.join(_REPO, "BASELINE_MEASURED.json")
            )
            with open(base_path) as f:
                base8 = float(json.load(f)["baseline_8core_fps"])
            if base8 > 0:
                out["kernel.vs_baseline"] = round(fps / base8, 2)
    except (OSError, ValueError, KeyError):
        pass  # no cached kernel measurement on this host — optional metrics
    return out


def compare_one(spec: dict, measured: object) -> tuple[bool, str]:
    """(passed, band description) for one metric against its baseline
    entry. Raises on a malformed spec — a broken gate must fail loudly,
    not pass silently."""
    kind = spec.get("kind", "floor_frac")
    value = spec.get("value")
    tol = float(spec.get("tolerance", 0.0))
    if kind == "exact":
        return measured == value, f"== {value!r}"
    m = float(measured)  # bool parity never reaches here
    if kind == "floor_frac":
        floor = float(value) * (1.0 - tol)
        return m >= floor, f">= {floor:.4g} ({value} -{tol * 100:.0f}%)"
    if kind == "ceil_frac":
        ceil = float(value) * (1.0 + tol)
        return m <= ceil, f"<= {ceil:.4g} ({value} +{tol * 100:.0f}%)"
    if kind == "floor_abs":
        return m >= tol, f">= {tol:.4g} (absolute)"
    raise BenchCompareError(f"unknown band kind {kind!r}")


def compare(baseline: dict, measured: dict) -> dict:
    """Full diff: {rows: [...], failures: n, skipped: n, checked: n}."""
    metrics = baseline.get("metrics")
    if not isinstance(metrics, dict) or not metrics:
        raise BenchCompareError("baseline has no metrics section")
    rows = []
    failures = skipped = gated = 0
    for name in sorted(metrics):
        spec = metrics[name]
        if name not in measured:
            if spec.get("required", True):
                gated += 1
                failures += 1
                rows.append((name, spec.get("value"), "MISSING", "-", "FAIL"))
            else:
                skipped += 1
                rows.append((name, spec.get("value"), "absent", "-", "skip"))
            continue
        got = measured[name]
        try:
            ok, band = compare_one(spec, got)
        except (TypeError, ValueError) as exc:
            raise BenchCompareError(f"metric {name}: {exc}") from exc
        gated += 1
        if not ok:
            failures += 1
        rows.append((name, spec.get("value"), got, band, "ok" if ok else "FAIL"))
    return {
        "rows": rows, "failures": failures, "skipped": skipped,
        "checked": gated - failures, "gated": gated,
    }


def render(result: dict) -> str:
    header = ("metric", "baseline", "measured", "band", "status")
    rows = [tuple(str(c) for c in r) for r in result["rows"]]
    widths = [len(h) for h in header]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))

    def line(cells):
        return "  ".join(c.ljust(w) for c, w in zip(cells, widths)).rstrip()

    out = [line(header), line(tuple("-" * w for w in widths))]
    out.extend(line(r) for r in rows)
    verdict = (
        f"bench-compare: REGRESSION — {result['failures']} of "
        f"{result['gated']} gated metrics out of band"
        if result["failures"]
        else f"bench-compare: OK ({result['gated']} metrics in band, "
        f"{result['skipped']} optional skipped)"
    )
    out.append(verdict)
    return "\n".join(out) + "\n"


def update_baseline(baseline: dict, measured: dict) -> dict:
    """New baseline document: measured values swapped in, bands kept."""
    out = json.loads(json.dumps(baseline))  # deep copy
    for name, spec in out.get("metrics", {}).items():
        if name in measured:
            spec["value"] = measured[name]
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Diff a fresh bench measurement against the committed "
        "baseline; exit nonzero on regression"
    )
    parser.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="baseline JSON with per-metric tolerance bands",
    )
    parser.add_argument(
        "--from", dest="from_file", default=None, metavar="FILE",
        help="compare a pre-measured flat JSON instead of benching now "
        "(offline diffs, the CI injected-regression self-test)",
    )
    parser.add_argument(
        "--save", default=None, metavar="FILE",
        help="also write the flat measurement set to FILE",
    )
    parser.add_argument(
        "--update", action="store_true",
        help="rewrite the baseline's values from this measurement "
        "(bands kept) instead of gating",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable result instead of the table",
    )
    args = parser.parse_args(argv)

    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except (OSError, ValueError) as exc:
        print(f"bench-compare: cannot load baseline {args.baseline}: {exc}")
        return 2
    try:
        if args.from_file:
            with open(args.from_file) as f:
                measured = json.load(f)
        else:
            measured = measure()
    except (OSError, ValueError, ChainError, BenchCompareError) as exc:
        print(f"bench-compare: measurement failed: {exc}")
        return 2
    if args.save:
        atomic_write_json(args.save, measured, sort_keys=True)
    if args.update:
        doc = update_baseline(baseline, measured)
        atomic_write_json(args.baseline, doc, sort_keys=True)
        print(f"bench-compare: baseline {args.baseline} updated")
        return 0
    try:
        result = compare(baseline, measured)
    except BenchCompareError as exc:
        print(f"bench-compare: {exc}")
        return 2
    if args.as_json:
        print(json.dumps(result, indent=1, default=str))
    else:
        print(render(result), end="")
    return 1 if result["failures"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
