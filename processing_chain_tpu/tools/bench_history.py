"""bench-history: the perf trend across committed bench artifacts.

Every PR commits its bench evidence as `BENCH_r<NN>.json` (the raw
`bench.py` capture: command, exit code, last parsed JSON line).
bench-compare gates ONE fresh measurement against the committed bands;
this tool reads the whole committed series and renders metric ×
revision, so a slow slide that never trips a single gate is still
visible in one table — and flags every cell against the same
`BENCH_BASELINE.json` bands bench-compare enforces.

    python -m processing_chain_tpu tools bench-history
    python -m processing_chain_tpu tools bench-history --dir REPO --json

Cells render as the measured value, suffixed `!` when the value sits
outside its baseline band (tools/bench_compare.py `compare_one`); `-`
marks a revision that did not measure that metric (a capture from a
host without the TPU cache, or a metric that did not exist yet).
Exit is 0 unless `--gate-latest` is given and the NEWEST revision of
any banded metric is out of band.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
from typing import Optional, Sequence

from .bench_compare import DEFAULT_BASELINE, _REPO, compare_one

_BENCH_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: parsed-payload field -> flat bench-compare metric name. Only fields
#: with a committed band are mapped; everything else stays visible via
#: --json but never renders a misleading `!`.
_FIELD_METRICS = (
    ("fused_vs_unfused", "e2e.fused_vs_unfused"),
    ("sharedscan_vs_separate", "e2e.sharedscan_vs_separate"),
    ("e2e_vs_baseline_1core", "e2e.vs_baseline_1core"),
    ("priors_vs_proxy", "complexity.priors_vs_proxy"),
)


def extract(doc: dict) -> dict:
    """The flat {metric: value} set one BENCH_r capture carries."""
    parsed = doc.get("parsed") or {}
    if not isinstance(parsed, dict):
        return {}
    out: dict = {}
    # the kernel line reports per-chip fps only when it really ran on
    # a TPU — a cpu/none capture's 0.34 is not a kernel regression
    if parsed.get("platform") == "tpu" and parsed.get("value"):
        out["kernel.fps_per_chip"] = parsed["value"]
        if parsed.get("vs_baseline"):
            out["kernel.vs_baseline"] = parsed["vs_baseline"]
    for field, metric in _FIELD_METRICS:
        if parsed.get(field) is not None:
            out[metric] = parsed[field]
    return out


def load_history(repo_dir: str) -> list[dict]:
    """Every committed BENCH_r capture, ordered by revision number:
    [{revision, path, rc, metrics}]."""
    rows = []
    for path in glob.glob(os.path.join(repo_dir, "BENCH_r*.json")):
        m = _BENCH_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        rows.append({
            "revision": int(m.group(1)),
            "path": os.path.basename(path),
            "rc": doc.get("rc"),
            "metrics": extract(doc),
        })
    rows.sort(key=lambda r: r["revision"])
    return rows


def history_table(rows: list, baseline: dict) -> dict:
    """The metric × revision table plus band verdicts: {metrics:
    {name: {r<NN>: {value, in_band}}}, latest_out_of_band: [...]}."""
    bands = (baseline or {}).get("metrics", {})
    table: dict = {}
    for row in rows:
        for name, value in row["metrics"].items():
            cell: dict = {"value": value}
            spec = bands.get(name)
            if spec is not None:
                try:
                    ok, band = compare_one(spec, value)
                except (TypeError, ValueError):
                    ok, band = None, "?"
                cell["in_band"] = ok
                cell["band"] = band
            table.setdefault(name, {})[f"r{row['revision']:02d}"] = cell
    latest_out = []
    for name, cells in sorted(table.items()):
        last = cells[max(cells)]
        if last.get("in_band") is False:
            latest_out.append(name)
    return {"metrics": table, "latest_out_of_band": latest_out,
            "revisions": [f"r{r['revision']:02d}" for r in rows]}


def render(result: dict) -> str:
    revisions = result["revisions"]
    header = ("metric",) + tuple(revisions)
    rows = []
    for name, cells in sorted(result["metrics"].items()):
        line = [name]
        for rev in revisions:
            cell = cells.get(rev)
            if cell is None:
                line.append("-")
                continue
            value = cell["value"]
            txt = f"{value:g}" if isinstance(value, (int, float)) \
                else str(value)
            if cell.get("in_band") is False:
                txt += "!"
            line.append(txt)
        rows.append(tuple(line))
    widths = [len(h) for h in header]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(cell))

    def fmt(cells):
        return "  ".join(c.ljust(w)
                         for c, w in zip(cells, widths)).rstrip()

    out = [fmt(header), fmt(tuple("-" * w for w in widths))]
    out.extend(fmt(r) for r in rows)
    if result["latest_out_of_band"]:
        out.append(
            "bench-history: latest revision OUT OF BAND for "
            + ", ".join(result["latest_out_of_band"]))
    else:
        out.append(f"bench-history: {len(result['metrics'])} metrics "
                   f"over {len(revisions)} revisions, latest in band")
    return "\n".join(out) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools bench-history",
        description="metric × revision table over the committed "
                    "BENCH_r*.json series (docs/PERF.md)",
    )
    parser.add_argument("--dir", default=_REPO,
                        help="directory holding BENCH_r*.json "
                             "(default: the repo root)")
    parser.add_argument("--baseline", default=None,
                        help="band file (default: DIR/BENCH_BASELINE"
                             ".json, falling back to the repo's)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable result instead of the "
                             "table")
    parser.add_argument("--gate-latest", action="store_true",
                        help="exit 1 when the newest revision of any "
                             "banded metric is out of band")
    args = parser.parse_args(list(argv) if argv is not None else None)

    baseline_path = args.baseline or os.path.join(
        args.dir, "BENCH_BASELINE.json")
    if not os.path.exists(baseline_path):
        baseline_path = DEFAULT_BASELINE
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except (OSError, ValueError):
        baseline = {}
    rows = load_history(args.dir)
    if not rows:
        print(f"bench-history: no BENCH_r*.json under {args.dir}")
        return 2
    result = history_table(rows, baseline)
    if args.as_json:
        print(json.dumps(result, indent=1, sort_keys=True))
    else:
        print(render(result), end="")
    if args.gate_latest and result["latest_out_of_band"]:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
