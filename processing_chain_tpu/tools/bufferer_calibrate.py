"""Calibrate the stalling renderer against a real `bufferer` output.

The reference chain shells out to the pip package `bufferer` for its
stalling pass (reference p03_generateAvPvs.py:242-243). Our device-side
re-implementation (ops/overlay.py) pins the parts of bufferer's behavior
that its CLI contract does not fix — spinner angular rate, rotation
direction, phase continuity across events — as documented assumptions.
This tool measures those quantities from an actual bufferer-produced clip,
so any environment that CAN run bufferer (this build environment cannot:
no network, package absent) can verify or replace the pinned constants:

    bufferer -i in.avi -o ref.avi -b "[[2.0,1.5]]" --force-framerate \
        --black-frame -v ffv1 -a pcm_s16le -x yuv420p -s spinner.png
    python -m processing_chain_tpu.tools.bufferer_calibrate \
        ref.avi --events "[[2.0,1.5]]" --input-frames N_IN

Reports, per stall event and overall:
  * inserted frame count vs the planner's round(duration*fps);
  * whether stall backgrounds are black (--black-frame semantics);
  * estimated spinner revolutions/second + direction + fit residual
    (ops/overlay.estimate_spinner_rps);
  * whether rotation phase is continuous across events (compares the
    per-event fit intercepts under one global rate).
"""

from __future__ import annotations

import argparse
import json
from typing import Optional, Sequence

import numpy as np

from ..io.video import VideoReader
from ..ops import overlay as ov


def _stall_spans(events: list, fps: float, n_in: int) -> list[tuple[int, int]]:
    """Output-frame [start, end) of each stall, per the planner's math."""
    plan = ov.plan_stalling(n_in, fps, events, skipping=False)
    spans = []
    k = 0
    while k < plan.n_out:
        if plan.stall_mask[k]:
            j = k
            while j < plan.n_out and plan.stall_mask[j]:
                j += 1
            spans.append((k, j))
            k = j
        else:
            k += 1
    return spans


def estimate_spinner_kinematics(
    frames: np.ndarray, fps: float
) -> tuple[float, float, float]:
    """(rps, phase0_rad, residual): like ops/overlay.estimate_spinner_rps
    but also recovering the spinner's angular PHASE at the clip's first
    frame — the quantity needed to verify phase continuity across stall
    events (the third ASSUMED kinematic constant). Same
    luminance-centroid method; phase0 is the linear fit's intercept,
    wrapped to (-pi, pi]. (The ~20 fit lines are deliberately duplicated
    from the ops/ estimator rather than refactored into it: calibration
    is host-tool surface, and ops/ is the device-kernel layer whose
    sources gate the live-bench cache hash.)"""
    t = frames.shape[0]
    if t < 3:
        raise ValueError("need at least 3 stall frames to estimate a rate")
    h, w = frames.shape[1:]
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
    cx, cy = (w - 1) / 2.0, (h - 1) / 2.0
    angles = np.empty(t)
    for k, f in enumerate(np.asarray(frames, np.float64)):
        wgt = np.clip(f - f.min(), 0, None)
        s = wgt.sum()
        if s <= 0:
            raise ValueError(f"stall frame {k} is uniform; cannot locate spinner")
        angles[k] = np.arctan2(
            (wgt * yy).sum() / s - cy, (wgt * xx).sum() / s - cx
        )
    ang = np.unwrap(angles)
    n = np.arange(t)
    slope, intercept = np.polyfit(n, ang, 1)
    resid = float(np.sqrt(np.mean((ang - (slope * n + intercept)) ** 2)))
    phase0 = float((intercept + np.pi) % (2.0 * np.pi) - np.pi)
    return float(slope * fps / (2.0 * np.pi)), phase0, resid


def _wrapped_diff(a: float, b: float) -> float:
    """|a - b| on the circle, in radians."""
    d = (a - b + np.pi) % (2.0 * np.pi) - np.pi
    return abs(float(d))


def calibrate(
    rendered_path: str,
    events: list,
    n_input_frames: int,
    crop: Optional[int] = None,
) -> dict:
    with VideoReader(rendered_path) as r:
        fps = r.fps
        planes, _ = r.read_all()
    luma = planes[0]  # always planar: the reader deinterleaves packed clips
    n_out = luma.shape[0]
    expected_inserted = sum(int(round(float(d) * fps)) for _, d in events)
    report: dict = {
        "fps": fps,
        "n_output_frames": n_out,
        "n_input_frames": n_input_frames,
        "inserted_frames": n_out - n_input_frames,
        "expected_inserted": expected_inserted,
        "insertion_matches_plan": (n_out - n_input_frames) == expected_inserted,
        "events": [],
    }
    spans = _stall_spans(events, fps, n_input_frames)
    h, w = luma.shape[1:]
    if crop is None:
        crop = min(h, w) // 2
    y0, x0 = (h - crop) // 2, (w - crop) // 2
    rates = []
    fits = []  # (span, rps, phase0) per measurable event
    for (a, b), (t, d) in zip(spans, sorted(map(tuple, events))):
        seg = luma[a:b, y0: y0 + crop, x0: x0 + crop]
        # background blackness: corners of the full frame, away from the
        # spinner (BT.601 limited-range black = 16)
        corners = luma[a:b, : h // 8, : w // 8]
        ev: dict = {
            "media_time": float(t),
            "duration": float(d),
            "frames": int(b - a),
            "background_black": bool(np.median(corners) <= 20),
        }
        if b - a >= 3:
            rps, phase0, resid = estimate_spinner_kinematics(seg, fps)
            ev["spinner_rps"] = round(rps, 4)
            ev["phase0_rad"] = round(phase0, 4)
            ev["fit_residual_rad"] = round(resid, 4)
            rates.append(rps)
            fits.append(((a, b), rps, phase0))
        report["events"].append(ev)
    if len(fits) >= 2:
        # phase continuity (third ASSUMED constant): under our model the
        # spinner advances only DURING stall frames, so event k+1's first
        # frame continues one step past event k's last. Compare measured
        # phase0 of each later event against the previous fit extrapolated
        # by its stall-frame count, on the circle.
        omega = 2.0 * np.pi * float(np.mean(rates)) / fps  # rad/frame
        ok = True
        deltas = []
        for ((a1, b1), _r1, p1), ((_a2, _b2), _r2, p2) in zip(fits, fits[1:]):
            expected = p1 + omega * (b1 - a1)
            deltas.append(round(_wrapped_diff(p2, expected), 4))
            ok = ok and deltas[-1] < 0.35  # ~1/18 rev tolerance
        report["phase_continuity_deltas_rad"] = deltas
        report["phase_continuous_across_events"] = bool(ok)
    if rates:
        report["spinner_rps_mean"] = round(float(np.mean(rates)), 4)
        report["spinner_direction"] = (
            "clockwise" if np.mean(rates) > 0 else "counterclockwise"
        )
        report["rate_consistent_across_events"] = bool(
            np.max(np.abs(np.asarray(rates) - np.mean(rates))) < 0.1
        )
    return report


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Measure bufferer's spinner/stall behavior from a "
        "rendered clip; prints a JSON report."
    )
    ap.add_argument("rendered", help="bufferer output clip (e.g. ref.avi)")
    ap.add_argument(
        "--events", required=True,
        help='stall events as JSON, e.g. "[[2.0, 1.5]]"',
    )
    ap.add_argument(
        "--input-frames", type=int, required=True,
        help="frame count of the clip BEFORE stalling insertion",
    )
    ap.add_argument(
        "--crop", type=int, default=None,
        help="center-crop size for the spinner region (default: half frame)",
    )
    args = ap.parse_args(argv)
    report = calibrate(
        args.rendered, json.loads(args.events), args.input_frames, args.crop
    )
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
