"""chain-profile: summarize one `--profile DIR` capture in the terminal.

    python -m processing_chain_tpu tools chain-profile DIR [--stamp S] [--list]

Reads the merged Chrome trace (profile_<ts>.trace.json) and the resource
timeseries (resources_<ts>.json) the profiler wrote, and renders:

  * per-lane busy seconds (host / decode / device / transfer / encode) —
    where the wall time went, by execution resource,
  * the top spans per lane by total time,
  * resource peaks (RSS, pool bytes, queue depths, device memory),
  * bottleneck verdicts per stage when the run also carried
    `--telemetry DIR` (metrics + events under the same stamp).

The trace itself opens in chrome://tracing or https://ui.perfetto.dev;
this summary is the part an operator reads over ssh.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
from typing import Optional, Sequence

_TRACE_RE = re.compile(r"profile_(?P<stamp>.+)\.trace\.json$")


class ProfileError(ValueError):
    """No loadable profile artifacts in the directory."""


def list_stamps(directory: str) -> list[str]:
    """Capture stamps, oldest first by artifact mtime (stamps embed an
    unpadded pid/seq — lexicographic order lies, same as report.py)."""
    entries = []
    for path in glob.glob(os.path.join(directory, "profile_*.trace.json")):
        m = _TRACE_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            entries.append((os.path.getmtime(path), m.group("stamp")))
        except OSError:
            continue
    return [stamp for _, stamp in sorted(entries)]


def load_profile(directory: str, stamp: Optional[str] = None) -> dict:
    """{stamp, trace, resources?, metrics?, events_path?} for one capture."""
    if not os.path.isdir(directory):
        raise ProfileError(f"not a directory: {directory}")
    stamps = list_stamps(directory)
    if stamp is None:
        if not stamps:
            raise ProfileError(
                f"no profile_<ts>.trace.json in {directory} — was the run "
                "started with --profile?"
            )
        stamp = stamps[-1]
    elif stamp not in stamps:
        raise ProfileError(f"no profile_{stamp}.trace.json in {directory}")
    out: dict = {"stamp": stamp, "directory": directory}
    trace_path = os.path.join(directory, f"profile_{stamp}.trace.json")
    try:
        with open(trace_path) as f:
            out["trace"] = json.load(f)
    except (OSError, ValueError) as exc:
        # a torn write (SIGKILL mid-dump, full disk) gets the clean
        # error path, not a raw traceback
        raise ProfileError(f"cannot load {trace_path}: {exc}") from exc
    # sidecar artifacts are optional AND tolerated when torn — the trace
    # summary must still render (same stance as report.load_run)
    for key, fname in (("resources", f"resources_{stamp}.json"),
                       ("metrics", f"metrics_{stamp}.json")):
        path = os.path.join(directory, fname)
        if os.path.isfile(path):
            try:
                with open(path) as f:
                    out[key] = json.load(f)
            except (OSError, ValueError):
                pass
    events_path = os.path.join(directory, f"events_{stamp}.jsonl")
    if os.path.isfile(events_path):
        out["events_path"] = events_path
    return out


def lane_summary(trace: dict) -> dict[str, dict]:
    """{lane: {busy_s, spans, top: [(name, total_s, count)]}} from the
    trace's complete ("X") events."""
    lanes: dict[str, dict] = {}
    for ev in trace.get("traceEvents", []):
        if ev.get("ph") != "X":
            continue
        cat = ev.get("cat", "host")
        lane = lanes.setdefault(cat, {"busy_s": 0.0, "spans": 0, "by_name": {}})
        dur_s = float(ev.get("dur", 0)) / 1e6
        lane["busy_s"] += dur_s
        lane["spans"] += 1
        entry = lane["by_name"].setdefault(ev.get("name", "?"), [0.0, 0])
        entry[0] += dur_s
        entry[1] += 1
    for lane in lanes.values():
        lane["top"] = sorted(
            ((name, t, n) for name, (t, n) in lane["by_name"].items()),
            key=lambda item: -item[1],
        )[:8]
        del lane["by_name"]
    return lanes


def render(profile: dict) -> str:
    lines = [f"chain-profile {profile['stamp']}  ({profile['directory']})"]
    lanes = lane_summary(profile["trace"])
    if lanes:
        lines.append("")
        lines.append("lanes (busy seconds by execution resource):")
        order = ("host", "decode", "device", "transfer", "encode", "events")
        for lane in sorted(lanes, key=lambda c: (
            order.index(c) if c in order else len(order), c
        )):
            if lane == "events":
                continue
            info = lanes[lane]
            lines.append(
                f"  {lane:<9} {info['busy_s']:9.3f}s over {info['spans']} spans"
            )
            for name, total, count in info["top"][:4]:
                lines.append(f"      {name[:52]:<52} {total:8.3f}s  x{count}")
    else:
        lines.append("  (trace has no complete spans)")

    res = profile.get("resources")
    if res:
        from ..telemetry.profiling import format_resource_peaks, resource_peaks

        lines.append("")
        lines.append(
            f"resources ({res.get('n_samples', 0)} samples @ "
            f"{res.get('interval_s', '?')}s):"
        )
        lines.extend(
            f"  {l}" for l in format_resource_peaks(resource_peaks(res))
        )

    if profile.get("metrics") is not None:
        from ..telemetry.events import read_jsonl
        from ..telemetry.profiling import attribute_run

        events = (
            read_jsonl(profile["events_path"])
            if profile.get("events_path") else []
        )
        verdicts = attribute_run(profile["metrics"], events)
        if verdicts:
            lines.append("")
            lines.append("bottleneck verdicts:")
            for stage, v in verdicts.items():
                contributors = ", ".join(
                    f"{c['component']} {c['pct']}%" for c in v["contributors"]
                ) or "no measured contributors"
                note = "  (insufficient data)" if v.get("insufficient_data") else ""
                lines.append(f"  {stage}: {v['verdict']}{note} — {contributors}")
                if v.get("missing"):
                    lines.append(
                        f"      unmeasured components: {', '.join(v['missing'])}"
                    )
    else:
        lines.append("")
        lines.append(
            "(no metrics_<ts>.json under this stamp — run with "
            "`--telemetry DIR --profile DIR` for bottleneck verdicts)"
        )
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Summarize a --profile DIR capture "
        "(merged trace + resources + verdicts)"
    )
    parser.add_argument("directory", help="the run's --profile DIR")
    parser.add_argument(
        "--stamp", default=None,
        help="specific capture stamp (default: latest in the directory)",
    )
    parser.add_argument(
        "--list", action="store_true", help="list capture stamps and exit"
    )
    args = parser.parse_args(argv)
    if args.list:
        for stamp in list_stamps(args.directory):
            print(stamp)
        return 0
    try:
        profile = load_profile(args.directory, args.stamp)
    except ProfileError as exc:
        print(f"chain-profile: {exc}")
        return 1
    print(render(profile), end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
