"""`tools chain-serve` — run the always-on processing daemon.

    python -m processing_chain_tpu tools chain-serve --root DIR
        [--port 8790] [--host 127.0.0.1]
        [--executor synthetic|wave] [--workers N] [--wave-width N]
        [--store DIR] [--store-budget BYTES] [--max-attempts N]
        [--tenant-weight NAME=W ...] [--status-file PATH]

The daemon binds ONE HTTP server (observability + /v1 API, see
docs/SERVE.md), recovers its durable queue from --root, and runs until
SIGTERM/SIGINT. `--root/serve-info.json` records {pid, port, url} the
moment the server is up — scripts that started the daemon with
`--port 0` read the bound port from there.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
from typing import Optional, Sequence

from ..utils.log import get_logger


def _parse_tenant_weights(pairs: list) -> dict:
    weights = {}
    for pair in pairs or ():
        name, _, value = pair.partition("=")
        if not name or not value:
            raise ValueError(
                f"--tenant-weight wants NAME=WEIGHT, got {pair!r}"
            )
        weights[name] = float(value)
    return weights


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools chain-serve",
        description="always-on processing service (docs/SERVE.md)",
    )
    parser.add_argument("--root", required=True,
                        help="serve state root (queue/requests/artifacts/store)")
    parser.add_argument("--port", type=int, default=8790,
                        help="HTTP port; 0 binds an ephemeral one "
                             "(read it from serve-info.json)")
    parser.add_argument("--host", default=None,
                        help="bind host (default 127.0.0.1 / PC_LIVE_HOST)")
    parser.add_argument("--executor", default="synthetic",
                        help="unit executor: synthetic | wave | chain "
                             "(chain = real databases through p01-p04; "
                             "requests carry params.config)")
    parser.add_argument("--workers", type=int, default=2,
                        help="scheduler worker threads")
    parser.add_argument("--wave-width", type=int, default=4,
                        help="max units packed into one device wave")
    parser.add_argument("--store", default=None,
                        help="artifact store root (default ROOT/store)")
    parser.add_argument("--store-budget", default=None,
                        help="store size budget, bytes (suffixes K/M/G ok); "
                             "GC pressure evicts LRU past it")
    parser.add_argument("--store-tiers", default=None, metavar="SPEC",
                        help="tiered store placement, e.g. "
                             "'hot@64M,shared=/mnt/warm@2G,object=/mnt/cold'"
                             " (docs/STORE.md \"Tier hierarchy\"; default "
                             "PC_STORE_TIERS, else single-tier)")
    parser.add_argument("--max-attempts", type=int, default=2,
                        help="execution attempts per job before it fails")
    parser.add_argument("--tenant-weight", action="append", default=[],
                        metavar="NAME=W",
                        help="fair-share weight for a tenant (default 1)")
    parser.add_argument("--status-file", default=None,
                        help="also rewrite the /status JSON to this file")
    parser.add_argument("--replica-id", default=None,
                        help="stable name for this replica in a fleet "
                             "(default: host-pid-random)")
    parser.add_argument("--lease-s", type=float, default=15.0,
                        help="execution-lease duration; a replica that "
                             "stops renewing for this long loses its "
                             "claims to peers (docs/SERVE.md)")
    parser.add_argument("--poll-s", type=float, default=1.0,
                        help="fleet maintenance tick: peer-record merge, "
                             "dead-lease stealing, remote completions")
    parser.add_argument("--info-file", default=None,
                        help="where to write {pid, port, url, replica} "
                             "(default ROOT/serve-info.json; give each "
                             "replica of a fleet its own)")
    parser.add_argument("--wave-budget-s", type=float, default=None,
                        help="cost-aware wave packing: fill waves to "
                             "this many PREDICTED seconds (serve/cost.py)"
                             " instead of stopping at --wave-width")
    parser.add_argument("--admission-budget-s", type=float, default=None,
                        help="refuse (429) any request whose cold units "
                             "predict more than this many seconds")
    parser.add_argument("--tenant-budget-s", type=float, default=None,
                        help="refuse (429, retryable) work that would "
                             "push a tenant's outstanding predicted "
                             "seconds past this budget")
    parser.add_argument("--cost-calibrate", action="store_true",
                        help="periodically refit the per-host cost-"
                             "prediction scale from the observed/"
                             "predicted ratio ring (serve/cost.py; "
                             "reported in /status and /fleet)")
    parser.add_argument("--control-interval-s", type=float, default=10.0,
                        help="SLO control loop cadence: alert-rule "
                             "grading + scale-signal re-grade on the "
                             "maintenance tick (docs/TELEMETRY.md "
                             "\"Alerting & the scale signal\")")
    parser.add_argument("--alert-window-scale", type=float, default=1.0,
                        help="uniformly compress every burn-rate window "
                             "and alert hold by this factor (soak "
                             "harnesses squeeze hours into seconds; "
                             "production leaves it at 1.0)")
    args = parser.parse_args(list(argv) if argv is not None else None)

    from .store_admin import _parse_bytes
    from ..serve.service import ChainServeService
    from ..telemetry.live import StatusFileWriter

    budget = _parse_bytes(args.store_budget) if args.store_budget else None
    # plan-exempt: (names WHERE artifact bytes are placed, never what they contain)
    tiers = args.store_tiers or os.environ.get("PC_STORE_TIERS")
    service = ChainServeService(
        root=args.root,
        port=args.port,
        host=args.host,
        executor=args.executor,
        workers=args.workers,
        wave_width=args.wave_width,
        store_root=args.store,
        store_budget_bytes=budget,
        store_tiers=tiers,
        tenant_weights=_parse_tenant_weights(args.tenant_weight),
        max_attempts=args.max_attempts,
        replica=args.replica_id,
        lease_s=args.lease_s,
        poll_s=args.poll_s,
        info_path=args.info_file,
        wave_budget_s=args.wave_budget_s,
        admission_budget_s=args.admission_budget_s,
        tenant_budget_s=args.tenant_budget_s,
        cost_calibrate=args.cost_calibrate,
        control_interval_s=args.control_interval_s,
        alert_window_scale=args.alert_window_scale,
    )
    stop = threading.Event()

    def _on_signal(signum, frame) -> None:
        get_logger().info("chain-serve: signal %d — draining and stopping",
                          signum)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)

    def _on_drain_signal(signum, frame) -> None:
        # SIGUSR1 toggles drain: the operator's no-HTTP path to the
        # same state flip POST /v1/drain performs (docs/SERVE.md
        # "Draining a replica"). A second SIGUSR1 resumes.
        if service.scheduler.draining:
            get_logger().info("chain-serve: SIGUSR1 — resuming")
            service.resume()
        else:
            get_logger().info("chain-serve: SIGUSR1 — draining "
                              "(again to resume)")
            service.drain()

    if hasattr(signal, "SIGUSR1"):
        signal.signal(signal.SIGUSR1, _on_drain_signal)
    service.start()
    status_writer = None
    if args.status_file:
        status_writer = StatusFileWriter(args.status_file).start()
    try:
        while not stop.wait(0.5):
            pass
    finally:
        if status_writer is not None:
            status_writer.stop()
        service.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
