"""chain-top: a refreshing terminal view of a live chain run.

Polls either the live HTTP endpoint (`--live-port`, telemetry/live.py)
or the atomically-rewritten `--status-file` JSON and renders per-stage
progress bars with ETA, the in-flight task table with beat ages, and
the chain counters — `top` for the processing chain.

    python -m processing_chain_tpu tools chain-top http://host:8080
    python -m processing_chain_tpu tools chain-top /path/status.json --once
    python tools/chain_top.py http://host:8080 -i 1

A URL source appends /status itself, so passing the server root is
enough. `--once` renders a single frame and exits (CI smoke, scripts);
otherwise it refreshes every `--interval` seconds until Ctrl-C, and
keeps the last good frame (with a note) across transient fetch errors
mid-run.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

_BAR_WIDTH = 28


class StatusSourceError(OSError):
    """Status fetch failed (endpoint down, file missing/torn)."""


def fetch_status(source: str, timeout_s: float = 3.0) -> dict:
    """Load the status document from a URL (…/status appended unless the
    path already names an endpoint) or a status-file path."""
    if source.startswith(("http://", "https://")):
        url = source if source.endswith("/status") else source.rstrip("/") + "/status"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, TimeoutError, ValueError) as exc:
            raise StatusSourceError(f"cannot fetch {url}: {exc}") from exc
    try:
        with open(source) as f:
            return json.load(f)
    except OSError as exc:
        raise StatusSourceError(f"cannot read status file {source}: {exc}") from exc
    except ValueError as exc:
        # os.replace-atomic writers make this unreachable mid-rewrite;
        # a partial copy (scp'd file) still deserves a clean error
        raise StatusSourceError(f"status file {source} is not JSON: {exc}") from exc


def _bar(progress: Optional[float]) -> str:
    if progress is None:
        return "[" + "?" * _BAR_WIDTH + "]"
    filled = int(round(progress * _BAR_WIDTH))
    return "[" + "#" * filled + "-" * (_BAR_WIDTH - filled) + "]"


def _fmt_eta(eta_s) -> str:
    if eta_s is None:
        return "eta --"
    eta_s = float(eta_s)
    if eta_s >= 3600:
        return f"eta {eta_s / 3600:.1f}h"
    if eta_s >= 60:
        return f"eta {eta_s / 60:.1f}m"
    return f"eta {eta_s:.0f}s"


def _fmt_age(age_s: float) -> str:
    age_s = float(age_s)
    if age_s >= 3600:
        return f"{age_s / 3600:.1f}h"
    if age_s >= 60:
        return f"{age_s / 60:.1f}m"
    return f"{age_s:.0f}s"


def render(status: dict, note: str = "") -> str:
    """One full frame (plain text, no cursor control — the loop clears)."""
    lines: list[str] = []
    run = status.get("run", {})
    head = f"chain-top — pid {status.get('pid', '?')}"
    if run.get("name"):
        head += f"  run {run['name']}"
    head += f"  up {_fmt_age(status.get('uptime_s', 0.0))}"
    if note:
        head += f"  [{note}]"
    lines.append(head)
    if run.get("argv"):
        lines.append("  argv: " + " ".join(str(a) for a in run["argv"]))
    lines.append("")

    stages = status.get("stages", {})
    current = status.get("current_stage")
    lines.append("stages:")
    if not stages:
        lines.append("  (none started yet)")
    for stage in sorted(stages):
        s = stages[stage]
        state = s.get("state", "?")
        marker = ">" if stage == current else " "
        done = int(s.get("jobs_done", 0))
        planned = s.get("jobs_planned")
        frac = s.get("progress")
        jobs = f"{done}/{int(planned)}" if planned is not None else f"{done}/?"
        tail = f"{_fmt_eta(s.get('eta_s'))}" if state == "running" else state
        lines.append(
            f" {marker}{stage}  {_bar(frac)} "
            f"{(frac or 0.0) * 100:5.1f}%  jobs {jobs:>9}  "
            f"wall {_fmt_age(s.get('wall_s', 0.0)):>6}  {tail}"
        )
    lines.append("")

    tasks = status.get("tasks", [])
    lines.append(f"in flight ({len(tasks)}):")
    if not tasks:
        lines.append("  (idle)")
    for t in tasks[:20]:
        flags = "STALLED " if t.get("stalled") else ""
        flags += "CANCELLED " if t.get("cancelled") else ""
        prog = t.get("progress")
        prog_txt = f"{prog * 100:5.1f}%" if prog is not None else "     -"
        lines.append(
            f"  {t.get('kind', '?'):<10} {str(t.get('label', '?'))[:46]:<46} "
            f"age {_fmt_age(t.get('age_s', 0.0)):>6}  "
            f"beat {_fmt_age(t.get('beat_age_s', 0.0)):>6}  "
            f"{prog_txt}  {_fmt_eta(t.get('eta_s'))}  {flags}".rstrip()
        )
    if len(tasks) > 20:
        lines.append(f"  … and {len(tasks) - 20} more")

    serve = status.get("serve", {})
    if serve:
        # replica identity first: in a multi-replica fleet this is how
        # an operator tells which daemon the frame describes
        parts = [
            f"replica {serve.get('replica', '?')}",
            f"epoch {serve.get('replica_epoch', '?')}",
            f"pid {serve.get('pid', status.get('pid', '?'))}",
        ]
        queue = serve.get("queue", {})
        if queue:
            parts.append("queue " + " ".join(
                f"{k}={v}" for k, v in sorted(queue.items())))
        reqs = serve.get("requests", {})
        if reqs:
            parts.append("requests " + " ".join(
                f"{k}={v}" for k, v in sorted(reqs.items())))
        lines.append("")
        lines.append("serve: " + "  ".join(parts))

    counters = status.get("counters", {})
    if counters:
        lines.append("")
        lines.append(
            "counters: "
            f"decoded {int(counters.get('frames_decoded', 0))} frames, "
            f"encoded {int(counters.get('frames_encoded', 0))} frames "
            f"({counters.get('bytes_encoded', 0) / 1e6:.1f} MB)"
        )
    resources = status.get("resources", {})
    if resources:
        rss = resources.get("rss_bytes") or 0
        parts = [f"rss {rss / 1e6:.0f} MB"]
        if resources.get("cpu_percent") is not None:
            parts.append(f"cpu {resources['cpu_percent']:.0f}%")
        parts.append(
            f"pool {resources.get('pool_outstanding_bytes', 0) / 1e6:.0f}"
            f"+{resources.get('pool_free_bytes', 0) / 1e6:.0f} MB"
        )
        if resources.get("open_fds") is not None:
            parts.append(f"fds {resources['open_fds']}")
        for queue, depth in sorted(resources.get("queues", {}).items()):
            parts.append(f"q:{queue} {depth}")
        dev = resources.get("device_memory", {})
        if dev.get("bytes_in_use") is not None:
            parts.append(f"hbm {dev['bytes_in_use'] / 1e6:.0f} MB")
        lines.append("resources: " + "  ".join(parts))
    recent = status.get("recent", [])
    failed = [r for r in recent if r.get("status") not in ("ok", "")]
    if failed:
        lines.append("")
        lines.append(f"recent failures ({len(failed)}):")
        for r in failed[:5]:
            lines.append(
                f"  {r.get('status')}: {r.get('kind')} {r.get('label')}"
            )
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Refreshing terminal view of a live chain run "
        "(--live-port endpoint or --status-file JSON)"
    )
    parser.add_argument(
        "source",
        help="status source: http://host:port (the run's --live-port) "
        "or a --status-file path",
    )
    parser.add_argument(
        "-i", "--interval", default=2.0, type=float,
        help="refresh period in seconds",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="render one frame and exit (for scripts/CI)",
    )
    args = parser.parse_args(argv)

    if args.once:
        print(render(fetch_status(args.source)), end="")
        return 0

    last_frame = None
    try:
        while True:
            note = ""
            try:
                frame = render(fetch_status(args.source))
                last_frame = frame
            except StatusSourceError as exc:
                if last_frame is None:
                    raise  # never reached the source at all: fail loudly
                note = f"stale: {exc}"
                frame = last_frame.rstrip("\n") + f"\n[{note}]\n"
            sys.stdout.write("\033[2J\033[H" + frame)
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
