"""chainlint: invariant-aware static analysis for the processing chain.

Generic linters catch generic Python mistakes; this package encodes the
rules that are specific to THIS codebase's concurrency and durability
conventions (docs/LINT.md), so a reviewer never again has to hand-check:

  * ``lock-guard``        — attributes annotated ``# guarded-by: <lock>``
                            are only touched under ``with <lock>``;
  * ``lock-order``        — the static lock-acquisition graph (nested
                            ``with`` scopes) stays acyclic, matched by a
                            runtime recorder (utils/lockdebug.py);
  * ``bufpool-ownership`` — every ``BufferPool.acquire`` result reaches
                            ``release``/``recycle=`` or a documented
                            ownership transfer on all control-flow paths;
  * ``subprocess-hygiene``— external commands go through
                            ``utils.runner.shell`` with list argv;
  * ``atomic-write``      — run-dir artifact writes use
                            ``fsio.atomic_write`` or tmp+``os.replace``;
  * ``telemetry-name``    — metric/event names are declared once in
                            ``telemetry/catalog.py`` and stay in sync
                            with docs/TELEMETRY.md.

Exposed as ``tools chain-lint`` (cli.py) and gated in CI against the
committed ``CHAINLINT_BASELINE.json``.
"""

from .core import Finding, LintConfig, run_lint  # noqa: F401
