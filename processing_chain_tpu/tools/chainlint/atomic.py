"""Atomic-write discipline for run-dir artifacts.

The chain's durability rule (utils/fsio.py): anything a later run's
exists-check, a concurrent reader (live /status, chain-top), or the
store's integrity layer might trust must be written via
``fsio.atomic_write`` or the tmp+``os.replace`` idiom — an interrupted
writer must never leave a truncated file under a trusted name.

A ``open(path, "w"/"wb"/"x")`` call is compliant when:

  * the path expression mentions a temp name (``tmp``/``.part``) — the
    first half of the idiom; or
  * the enclosing function also calls ``os.replace``/``os.rename`` —
    the second half; or
  * it happens inside the ``write_fn`` handed to ``fsio.atomic_write``
    (a lambda argument, or a local def whose name is passed in); or
  * it opens in append mode (streams are append-only by design and
    torn tails are handled by readers — events.read_jsonl).

Anything else is a finding. Deliberate exceptions (crash-sentinel touch
files whose CONTENT is irrelevant, per-job provenance logs) carry inline
disables with reasons.
"""

from __future__ import annotations

import ast
from typing import Optional

from .core import Checker, Finding, ModuleSource, symbol_of
from .locks import dotted


def _mode_of(call: ast.Call) -> Optional[str]:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


class AtomicWriteChecker(Checker):
    rule = "atomic-write"

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        findings: list[Finding] = []
        blessed: set[int] = set()      # node ids inside atomic_write(...) args
        blessed_fn_names: set[str] = set()  # local defs passed to atomic_write

        # atomic wrappers: local defs that forward a function parameter
        # into atomic_write (models/metadata._maybe_write) bless their
        # call sites exactly like atomic_write itself does
        wrapper_names: set[str] = {"atomic_write"}
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = {a.arg for a in node.args.args + node.args.kwonlyargs}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        (dotted(sub.func) or "").split(".")[-1] == "atomic_write" \
                        and any(isinstance(a, ast.Name) and a.id in params
                                for a in sub.args):
                    wrapper_names.add(node.name)

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                name = dotted(node.func) or ""
                if name.split(".")[-1] in wrapper_names:
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        if isinstance(arg, ast.Name):
                            blessed_fn_names.add(arg.id)
                        for sub in ast.walk(arg):
                            blessed.add(id(sub))

        # map: function node -> does it (or an enclosing one) replace/rename?
        def has_replace(fn: ast.AST) -> bool:
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    nm = dotted(n.func) or ""
                    if nm in ("os.replace", "os.rename", "shutil.move"):
                        return True
            return False

        class _Walker(ast.NodeVisitor):
            def __init__(self) -> None:
                self.fn_stack: list[ast.AST] = []

            def _visit_fn(self, node) -> None:
                self.fn_stack.append(node)
                self.generic_visit(node)
                self.fn_stack.pop()

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn
            visit_Lambda = _visit_fn

            def visit_Call(self, node: ast.Call) -> None:
                self.generic_visit(node)
                name = dotted(node.func) or ""
                if name not in ("open", "io.open") or not node.args:
                    return
                mode = _mode_of(node)
                if mode is None or not any(c in mode for c in "wx"):
                    return
                if id(node) in blessed:
                    return  # inside atomic_write's write_fn argument
                try:
                    path_text = ast.unparse(node.args[0]).lower()
                except Exception:  # pragma: no cover - unparse is total on 3.9+
                    path_text = ""
                if "tmp" in path_text or "part" in path_text:
                    return
                for fn in self.fn_stack:
                    if getattr(fn, "name", None) in blessed_fn_names:
                        return  # a def handed to atomic_write as write_fn
                    if has_replace(fn):
                        return
                f = mod.finding(
                    AtomicWriteChecker.rule, node,
                    f"open({ast.unparse(node.args[0])}, {mode!r}) writes a "
                    "trusted path in place — an interrupted run leaves a "
                    "truncated file; use fsio.atomic_write or the "
                    "tmp+os.replace idiom (docs/LINT.md)",
                    symbol=symbol_of(mod.tree, node))
                if f:
                    findings.append(f)

        _Walker().visit(mod.tree)
        return findings
