"""Baseline: grandfathered findings, each with a mandatory reason.

The committed ``CHAINLINT_BASELINE.json`` lets the gate turn on before
every historical finding is fixed, without letting NEW violations in.
Semantics:

  * a finding whose fingerprint matches a baseline entry is *suppressed*
    (reported only under ``--show-baselined``);
  * a finding with no entry **fails** the lint;
  * an entry matching no finding is *stale* — the code got fixed, the
    entry must go. Stale entries fail the lint too (baseline hygiene is
    part of the gate; ``--allow-stale`` relaxes this for transitional
    branches) and ``--update-baseline`` expires them.
  * every entry carries a non-empty ``reason``; a reasonless entry is a
    lint error — nothing gets grandfathered silently.

Fingerprints are line-number-free (rule + file + symbol + normalized
source line), so unrelated edits above a grandfathered site don't churn
the file. ``--update-baseline`` preserves the reasons of surviving
entries and stamps new ones with the operator-supplied ``--reason``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from ...utils.fsio import atomic_write_text
from .core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "CHAINLINT_BASELINE.json"


class BaselineError(ValueError):
    """Malformed baseline file (schema, or an entry without a reason)."""


@dataclass
class BaselineEntry:
    rule: str
    path: str
    symbol: str
    snippet: str
    reason: str

    def fingerprint(self) -> str:
        f = Finding(rule=self.rule, path=self.path, line=0,
                    message="", symbol=self.symbol)
        f.snippet = self.snippet
        return f.fingerprint()

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "symbol": self.symbol,
            "snippet": self.snippet, "reason": self.reason,
        }


@dataclass
class BaselineResult:
    new: list = field(default_factory=list)        # findings not baselined
    baselined: list = field(default_factory=list)  # suppressed findings
    stale: list = field(default_factory=list)      # entries with no finding


def load_baseline(path: str) -> list[BaselineEntry]:
    if not os.path.isfile(path):
        return []
    with open(path, encoding="utf-8") as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as exc:
            raise BaselineError(f"{path}: not valid JSON: {exc}") from exc
    if not isinstance(doc, dict) or "entries" not in doc:
        raise BaselineError(f"{path}: expected {{'version', 'entries'}}")
    entries = []
    for i, raw in enumerate(doc["entries"]):
        missing = {"rule", "path", "snippet", "reason"} - set(raw)
        if missing:
            raise BaselineError(
                f"{path}: entry {i} is missing {sorted(missing)}")
        if not str(raw["reason"]).strip():
            raise BaselineError(
                f"{path}: entry {i} ({raw['rule']} at {raw['path']}) has "
                "an empty reason — every grandfathered finding must say "
                "why it is exempt")
        entries.append(BaselineEntry(
            rule=raw["rule"], path=raw["path"],
            symbol=raw.get("symbol", ""), snippet=raw["snippet"],
            reason=str(raw["reason"]),
        ))
    return entries


def apply_baseline(findings: list[Finding],
                   entries: list[BaselineEntry]) -> BaselineResult:
    by_fp: dict[str, BaselineEntry] = {e.fingerprint(): e for e in entries}
    result = BaselineResult()
    matched: set[str] = set()
    for f in findings:
        fp = f.fingerprint()
        if fp in by_fp:
            matched.add(fp)
            result.baselined.append(f)
        else:
            result.new.append(f)
    result.stale = [e for e in entries if e.fingerprint() not in matched]
    return result


def write_baseline(path: str, findings: list[Finding],
                   keep: list[BaselineEntry], reason: str) -> int:
    """Rewrite the baseline: surviving entries keep their reasons, the
    still-unbaselined `findings` are added under `reason`, stale entries
    are dropped (expire). Returns the entry count written."""
    keep_fps = {e.fingerprint(): e for e in keep}
    entries = list(keep_fps.values())
    for f in findings:
        if f.fingerprint() not in keep_fps:
            entries.append(BaselineEntry(
                rule=f.rule, path=f.path, symbol=f.symbol,
                snippet=f.snippet, reason=reason,
            ))
    entries.sort(key=lambda e: (e.path, e.rule, e.snippet))
    payload = {
        "version": BASELINE_VERSION,
        "_comment": (
            "chainlint grandfathered findings (docs/LINT.md). Every entry "
            "needs a reason; entries whose finding is fixed are stale and "
            "expire via `tools chain-lint --update-baseline`."
        ),
        "entries": [e.as_dict() for e in entries],
    }

    atomic_write_text(path, json.dumps(payload, indent=1) + "\n")
    return len(entries)
