"""`tools chain-lint` — run the chain's own static analysis.

Exit codes: 0 clean (baselined findings allowed), 1 findings or stale
baseline entries, 2 usage/configuration errors. The CI gate runs it
bare; `--update-baseline --reason "…"` is the grandfathering workflow
(docs/LINT.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

from .baseline import (
    DEFAULT_BASELINE, BaselineError, apply_baseline, load_baseline,
    write_baseline,
)
from .core import ALL_RULES, LintConfig, run_lint


def find_repo_root(start: Optional[str] = None) -> str:
    """Nearest ancestor holding the package dir (or .git) — chain-lint
    must work from any cwd inside the checkout."""
    cur = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(cur, "processing_chain_tpu")) or \
                os.path.isdir(os.path.join(cur, ".git")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return os.path.abspath(start or os.getcwd())
        cur = nxt


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools chain-lint",
        description="invariant-aware static analysis for the chain "
                    "(rules: %s)" % ", ".join(ALL_RULES),
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the shipped tree)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detect from cwd)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <root>/{DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules to run")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline: keep matched entries, add "
                        "current findings under --reason, expire stale")
    p.add_argument("--reason", default=None,
                   help="reason recorded for entries added by "
                        "--update-baseline (required with it)")
    p.add_argument("--allow-stale", action="store_true",
                   help="don't fail on stale baseline entries")
    p.add_argument("--show-baselined", action="store_true",
                   help="also print suppressed (baselined) findings")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(list(argv) if argv is not None else None)
    root = os.path.abspath(args.root) if args.root else find_repo_root()
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(ALL_RULES)
        if unknown:
            print(f"chain-lint: unknown rule(s): {sorted(unknown)} "
                  f"(known: {', '.join(ALL_RULES)})", file=sys.stderr)
            return 2
    else:
        rules = None
    if args.update_baseline and not args.reason:
        print("chain-lint: --update-baseline requires --reason "
              "(every grandfathered finding must say why)", file=sys.stderr)
        return 2

    cfg = LintConfig(
        root=root,
        targets=[os.path.abspath(p) for p in args.paths],
        rules=rules,
    )
    findings = run_lint(cfg)

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    entries = []
    if not args.no_baseline:
        try:
            entries = load_baseline(baseline_path)
        except BaselineError as exc:
            print(f"chain-lint: {exc}", file=sys.stderr)
            return 2
    result = apply_baseline(findings, entries)

    if args.update_baseline:
        kept = [e for e in entries if e not in result.stale]
        n = write_baseline(baseline_path, result.new, kept, args.reason)
        print(f"chain-lint: baseline updated: {n} entries "
              f"({len(result.new)} added, {len(result.stale)} expired) "
              f"-> {baseline_path}")
        return 0

    if args.as_json:
        print(json.dumps({
            "root": root,
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "symbol": f.symbol, "message": f.message,
                 "fingerprint": f.fingerprint()}
                for f in result.new
            ],
            "baselined": len(result.baselined),
            "stale_baseline_entries": [
                e.as_dict() for e in result.stale
            ],
        }, indent=1))
    else:
        for f in result.new:
            print(f.render())
        if args.show_baselined and result.baselined:
            print(f"-- {len(result.baselined)} baselined finding(s):")
            for f in result.baselined:
                print(f"   (baselined) {f.render()}")
        for e in result.stale:
            print(f"chain-lint: STALE baseline entry ({e.rule} at {e.path}"
                  f" [{e.symbol}]): the finding is gone — expire it with "
                  "--update-baseline")
        counts: dict = {}
        for f in result.new:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        if result.new:
            summary = ", ".join(f"{k}: {v}" for k, v in sorted(counts.items()))
            print(f"chain-lint: FAIL — {len(result.new)} finding(s) "
                  f"({summary})"
                  + (f", {len(result.baselined)} baselined" if result.baselined else ""))
        elif result.stale and not args.allow_stale:
            print(f"chain-lint: FAIL — {len(result.stale)} stale baseline "
                  "entr(y/ies)")
        else:
            print("chain-lint: OK — 0 findings"
                  + (f", {len(result.baselined)} baselined" if result.baselined else "")
                  + (f", {len(result.stale)} stale (allowed)" if result.stale else ""))

    if result.new:
        return 1
    if result.stale and not args.allow_stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
