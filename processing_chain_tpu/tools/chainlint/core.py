"""chainlint driver: file model, disable comments, checker protocol.

The analysis unit is a ``ModuleSource`` — path + text + AST + the
comment map (extracted with ``tokenize`` so strings that merely look
like comments can't confuse the suppression logic). Checkers are small
classes over that model; cross-file rules (the lock-order graph) get a
``finalize()`` pass after every module has been visited.

Suppression contract (docs/LINT.md):

  * ``# chainlint: disable=<rule>[,<rule>…] (<reason>)`` — on the
    offending line, or alone on the line directly above it. The reason
    is REQUIRED: a disable without one is itself a finding
    (``bad-disable``), so exemptions stay auditable.
  * ``# chainlint: disable-file=<rule> (<reason>)`` — module-wide, must
    appear in the first 20 lines.

Annotations (consumed by individual checkers, never suppressions):

  * ``# guarded-by: <lock>``          declares a lock-protected attribute
  * ``# holds-lock: <lock>``          marks a function whose callers hold
                                      the lock already
  * ``# chainlint: ownership-transfer (<reason>)`` marks a statement that
    hands a pooled buffer to another owner
  * ``# plan-exempt: (<reason>)``     marks an environment-input read whose
    value never alters artifact bytes (plan-purity rule; the input must
    also be declared ``exempt`` in store/plan_schema.py)
  * ``# queue-transition: <from>[|<from>…] -> <to>`` declares which edge
    of the serve queue state machine a ``.state`` assignment implements
    (queue-transition rule; the edge must exist in serve/queue.py's
    declared TRANSITIONS table)
"""

from __future__ import annotations

import ast
import hashlib
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence

#: every rule chainlint knows; ``bad-disable`` guards the suppression
#: syntax itself and can never be disabled
ALL_RULES = (
    "lock-guard",
    "lock-order",
    "bufpool-ownership",
    "subprocess-hygiene",
    "atomic-write",
    "telemetry-name",
    "plan-purity",
    "queue-transition",
    "bad-disable",
)

_DISABLE_RE = re.compile(
    r"#\s*chainlint:\s*(disable|disable-file)\s*=\s*"
    r"(?P<rules>[a-z-]+(?:\s*,\s*[a-z-]+)*)"
    r"(?P<reason>\s*\(.*\))?"
)
_TRANSFER_RE = re.compile(
    r"#\s*chainlint:\s*ownership-transfer(?P<reason>\s*\(.*\))?"
)
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_][\w.]*)")
_HOLDS_RE = re.compile(r"#\s*holds-lock:\s*(?P<lock>[A-Za-z_][\w.]*)")
_PLAN_EXEMPT_RE = re.compile(r"#\s*plan-exempt:(?P<reason>\s*\(.*\))?")
_QUEUE_EDGE_RE = re.compile(
    r"#\s*queue-transition:\s*"
    r"(?P<src>[a-z]+(?:\s*\|\s*[a-z]+)*)\s*->\s*(?P<dst>[a-z]+)"
)


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    symbol: str = ""  # enclosing function/class, for stable baselines

    @property
    def snippet(self) -> str:
        return getattr(self, "_snippet", "")

    @snippet.setter
    def snippet(self, value: str) -> None:
        self._snippet = value.strip()[:160]

    def fingerprint(self) -> str:
        """Line-number-free identity: baselines must survive unrelated
        edits above a grandfathered site, so the key is the rule + file
        + enclosing symbol + normalized source line, not the line no."""
        basis = f"{self.rule}|{self.path}|{self.symbol}|{self.snippet}"
        return hashlib.sha1(basis.encode()).hexdigest()[:12]

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule}{sym}: {self.message}"


@dataclass
class ModuleSource:
    """A parsed target file plus everything checkers ask of it."""

    path: str
    rel: str
    text: str
    tree: ast.Module
    lines: list[str]
    #: {line no -> comment text} (tokenize-accurate)
    comments: dict[int, str] = field(default_factory=dict)
    #: {line no -> rules disabled on that line}
    disables: dict[int, set] = field(default_factory=dict)
    file_disables: set = field(default_factory=set)
    #: disables whose reason is missing (line -> raw comment)
    bad_disables: list = field(default_factory=list)
    #: {line no -> lock name} from # guarded-by:
    guarded_by: dict[int, str] = field(default_factory=dict)
    #: {line no -> lock name} from # holds-lock:
    holds_lock: dict[int, str] = field(default_factory=dict)
    #: lines carrying a valid ownership-transfer annotation
    transfer_lines: set = field(default_factory=set)
    #: {line no -> reason} from valid # plan-exempt: (reason) annotations
    plan_exempt: dict[int, str] = field(default_factory=dict)
    #: {line no -> (sources tuple, destination)} from # queue-transition:
    queue_edges: dict[int, tuple] = field(default_factory=dict)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def disabled(self, rule: str, lineno: int) -> bool:
        if rule in self.file_disables:
            return True
        return rule in self.disables.get(lineno, ())

    def finding(self, rule: str, node_or_line, message: str,
                symbol: str = "") -> Optional[Finding]:
        """Build a Finding unless a disable comment covers it."""
        lineno = getattr(node_or_line, "lineno", node_or_line)
        if rule != "bad-disable" and self.disabled(rule, lineno):
            return None
        f = Finding(rule=rule, path=self.rel, line=lineno,
                    message=message, symbol=symbol)
        f.snippet = self.line_text(lineno)
        return f


def _extract_comments(text: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # the ast parse already succeeded; comments are best-effort
    return out


def _comment_effective_lines(comments: dict[int, str],
                             lines: list[str]) -> Iterable[tuple[int, str, int]]:
    """Yield (effective code line, comment text, comment line). A comment
    sharing a line with code applies to that line; a standalone comment
    line applies to the next line (annotations sit above long calls)."""
    for lineno, comment in comments.items():
        code = lines[lineno - 1][: lines[lineno - 1].find("#")].strip() \
            if lineno <= len(lines) else ""
        effective = lineno if code else lineno + 1
        yield effective, comment, lineno


def load_module(path: str, root: str) -> Optional[ModuleSource]:
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    try:
        tree = ast.parse(text)
    except SyntaxError:
        return None  # the compileall CI gate owns syntax errors
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    mod = ModuleSource(
        path=path, rel=rel, text=text, tree=tree,
        lines=text.splitlines(),
    )
    mod.comments = _extract_comments(text)
    for eff, comment, cline in _comment_effective_lines(mod.comments, mod.lines):
        m = _DISABLE_RE.search(comment)
        if m:
            rules = {r.strip() for r in m.group("rules").split(",")}
            unknown = rules - set(ALL_RULES)
            reason = (m.group("reason") or "").strip("() \t")
            if not reason or unknown:
                why = (f"unknown rule(s) {sorted(unknown)}" if unknown
                       else "missing (reason)")
                mod.bad_disables.append((cline, comment.strip(), why))
            elif m.group(1) == "disable-file":
                if cline <= 20:
                    mod.file_disables |= rules
                else:
                    mod.bad_disables.append(
                        (cline, comment.strip(),
                         "disable-file must sit in the first 20 lines"))
            else:
                mod.disables.setdefault(eff, set()).update(rules)
        m = _TRANSFER_RE.search(comment)
        if m:
            if (m.group("reason") or "").strip("() \t"):
                mod.transfer_lines.add(eff)
            else:
                mod.bad_disables.append(
                    (cline, comment.strip(), "missing (reason)"))
        m = _GUARDED_RE.search(comment)
        if m:
            mod.guarded_by[eff] = m.group("lock")
        m = _HOLDS_RE.search(comment)
        if m:
            mod.holds_lock[eff] = m.group("lock")
        m = _PLAN_EXEMPT_RE.search(comment)
        if m:
            reason = (m.group("reason") or "").strip("() \t")
            if reason:
                mod.plan_exempt[eff] = reason
            else:
                mod.bad_disables.append(
                    (cline, comment.strip(), "missing (reason)"))
        m = _QUEUE_EDGE_RE.search(comment)
        if m:
            sources = tuple(
                s.strip() for s in m.group("src").split("|") if s.strip()
            )
            mod.queue_edges[eff] = (sources, m.group("dst"))
    return mod


class Checker:
    """Base checker: per-module visit plus an optional cross-file pass."""

    rule: str = ""

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        return []

    def finalize(self) -> list[Finding]:
        return []


@dataclass
class LintConfig:
    root: str
    targets: Sequence[str] = ()
    rules: Optional[set] = None  # None = all
    catalog_path: str = "processing_chain_tpu/telemetry/catalog.py"
    doc_path: str = "docs/TELEMETRY.md"
    plan_schema_path: str = "processing_chain_tpu/store/plan_schema.py"
    queue_module_path: str = "processing_chain_tpu/serve/queue.py"
    serve_doc_path: str = "docs/SERVE.md"

    #: directories whose findings are skipped wholesale (fixtures carry
    #: deliberate violations; vendored/test trees are out of contract)
    EXCLUDE_PARTS = ("__pycache__", ".git", "tests/chainlint_fixtures")

    def default_targets(self) -> list[str]:
        return [
            os.path.join(self.root, "processing_chain_tpu"),
            os.path.join(self.root, "tools"),
            os.path.join(self.root, "bench.py"),
        ]

    def iter_files(self) -> Iterable[str]:
        targets = list(self.targets) or self.default_targets()
        for target in targets:
            if os.path.isfile(target):
                if target.endswith(".py"):
                    yield target
                continue
            for dirpath, dirnames, filenames in os.walk(target):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                rel = os.path.relpath(dirpath, self.root).replace(os.sep, "/")
                if any(part in rel for part in self.EXCLUDE_PARTS):
                    continue
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def symbol_of(tree: ast.Module, node: ast.AST) -> str:
    """Dotted enclosing-scope name of `node` (Class.method), for stable
    baseline keys. Linear scan — fine at lint cadence."""
    path: list[str] = []

    def descend(parent: ast.AST, trail: list[str]) -> bool:
        for child in ast.iter_child_nodes(parent):
            if child is node:
                path.extend(trail)
                own = getattr(node, "name", None)
                if isinstance(node, (ast.ClassDef, ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and own:
                    path.append(own)
                return True
            if isinstance(child, (ast.ClassDef, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                if descend(child, trail + [child.name]):
                    return True
            else:
                if descend(child, trail):
                    return True
        return False

    descend(tree, [])
    return ".".join(path)


def build_checkers(cfg: LintConfig) -> list[Checker]:
    from . import (
        atomic, locks, ownership, planpurity, queue_transitions, subproc,
        telemetry_names,
    )

    checkers: list[Checker] = [
        locks.LockGuardChecker(),
        locks.LockOrderChecker(),
        ownership.BufpoolOwnershipChecker(),
        subproc.SubprocessHygieneChecker(),
        atomic.AtomicWriteChecker(),
        telemetry_names.TelemetryNameChecker(
            catalog_path=os.path.join(cfg.root, cfg.catalog_path),
            doc_path=os.path.join(cfg.root, cfg.doc_path),
        ),
        planpurity.PlanPurityChecker(
            schema_path=os.path.join(cfg.root, cfg.plan_schema_path),
        ),
        queue_transitions.QueueTransitionChecker(
            queue_path=os.path.join(cfg.root, cfg.queue_module_path),
            doc_path=os.path.join(cfg.root, cfg.serve_doc_path),
        ),
    ]
    if cfg.rules is not None:
        checkers = [c for c in checkers if c.rule in cfg.rules]
    return checkers


def run_lint(cfg: LintConfig) -> list[Finding]:
    """Run every enabled checker over the configured tree; returns the
    raw (pre-baseline) findings, sorted by location."""
    checkers = build_checkers(cfg)
    findings: list[Finding] = []
    want_bad_disable = cfg.rules is None or "bad-disable" in cfg.rules
    for path in cfg.iter_files():
        mod = load_module(path, cfg.root)
        if mod is None:
            continue
        if want_bad_disable:
            for cline, comment, why in mod.bad_disables:
                f = mod.finding(
                    "bad-disable", cline,
                    f"malformed chainlint annotation ({why}): {comment}")
                if f:
                    findings.append(f)
        for checker in checkers:
            findings.extend(checker.visit_module(mod))
    for checker in checkers:
        findings.extend(checker.finalize())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
