"""Lock discipline: guarded attributes and the acquisition-order graph.

``lock-guard``: an attribute whose declaration line (usually in
``__init__``) carries ``# guarded-by: <lock>`` may only be read or
written inside a ``with <lock>`` scope. Exemptions: the declaring
class's ``__init__``/``__new__`` (construction happens before the
object is shared) and functions annotated ``# holds-lock: <lock>``
(callers acquire for them — the ``_locked``-suffix convention, made
machine-readable).

Lock matching is by dotted-suffix after stripping the ``self``/``cls``
receiver, so ``# guarded-by: _registry._lock`` accepts both
``with self._registry._lock`` and ``with metric._registry._lock``.

``lock-order``: every *lexically nested* pair ``with A: … with B:``
contributes an A→B edge to a process-wide graph; a cycle means two code
paths can acquire the same locks in opposite orders — the classic
deadlock. Lock-looking context managers are recognized by their final
attribute component containing ``lock`` (case-insensitive). The static
graph only sees same-function nesting; the runtime recorder
(utils/lockdebug.py, PC_LOCK_DEBUG=1 under tests) sees cross-function
chains, and both feed the same cycle detector so the evidence agrees.
"""

from __future__ import annotations

import ast
from typing import Optional

from ...utils.lockdebug import find_cycle
from .core import Checker, Finding, ModuleSource, symbol_of


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a pure Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _strip_receiver(parts: list[str]) -> list[str]:
    return parts[1:] if parts and parts[0] in ("self", "cls") else parts


def guard_matches(declared: str, held: str) -> bool:
    """Componentwise suffix match after stripping self/cls, either way:
    declared '_registry._lock' is satisfied by held
    'metric._registry._lock'; declared 'self._lock' by held '_lock'."""
    d = _strip_receiver(declared.split("."))
    h = _strip_receiver(held.split("."))
    if not d or not h:
        return False
    shorter, longer = (d, h) if len(d) <= len(h) else (h, d)
    return longer[-len(shorter):] == shorter


def _is_lockish(name: str) -> bool:
    return "lock" in name.split(".")[-1].lower()


class _FunctionWalker(ast.NodeVisitor):
    """Shared traversal: tracks the with-stack of dotted context
    expressions and the enclosing class/function chain."""

    def __init__(self, mod: ModuleSource) -> None:
        self.mod = mod
        self.with_stack: list[tuple[str, int]] = []  # (dotted expr, line)
        self.class_stack: list[str] = []
        self.func_stack: list[ast.AST] = []
        self.findings: list[Finding] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node) -> None:
        self.func_stack.append(node)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            # `with lock:` and `with lock.acquire_timeout(…):` both hold
            # the lock; use the callee text for call expressions
            name = dotted(expr.func if isinstance(expr, ast.Call) else expr)
            if name is not None:
                self.on_with(name, node.lineno)
                self.with_stack.append((name, node.lineno))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        del self.with_stack[len(self.with_stack) - pushed:]

    visit_AsyncWith = visit_With

    def on_with(self, name: str, lineno: int) -> None:
        pass


class LockGuardChecker(Checker):
    rule = "lock-guard"

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        declared = self._collect_declarations(mod)
        if not declared:
            return []
        walker = _GuardWalker(mod, declared)
        walker.visit(mod.tree)
        return walker.findings

    @staticmethod
    def _collect_declarations(mod: ModuleSource) -> dict[str, tuple[str, Optional[str], int]]:
        """{attr/global name: (lock expr, declaring class or None, line)}
        from ``# guarded-by:`` comments on assignment lines."""
        declared: dict[str, tuple[str, Optional[str], int]] = {}

        class _Decl(ast.NodeVisitor):
            def __init__(self) -> None:
                self.class_stack: list[str] = []

            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                self.class_stack.append(node.name)
                self.generic_visit(node)
                self.class_stack.pop()

            def _handle(self, node, targets) -> None:
                lock = mod.guarded_by.get(node.lineno)
                if lock is None:
                    return
                cls = self.class_stack[-1] if self.class_stack else None
                for target in targets:
                    if isinstance(target, ast.Attribute) and \
                            isinstance(target.value, ast.Name) and \
                            target.value.id in ("self", "cls"):
                        declared[target.attr] = (lock, cls, node.lineno)
                    elif isinstance(target, ast.Name):
                        declared[target.id] = (lock, cls, node.lineno)

            def visit_Assign(self, node: ast.Assign) -> None:
                self._handle(node, node.targets)
                self.generic_visit(node)

            def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
                self._handle(node, [node.target])
                self.generic_visit(node)

        _Decl().visit(mod.tree)
        return declared


class _GuardWalker(_FunctionWalker):
    def __init__(self, mod: ModuleSource, declared: dict) -> None:
        super().__init__(mod)
        self.declared = declared

    def _exempt(self, name: str, node: ast.AST) -> bool:
        lock, cls, decl_line = self.declared[name]
        if node.lineno == decl_line:
            return True  # the declaration itself
        func = self.func_stack[-1] if self.func_stack else None
        if func is not None:
            if func.name in ("__init__", "__new__") and (
                    cls is None or (self.class_stack
                                    and self.class_stack[-1] == cls)):
                return True
            held_doc = self.mod.holds_lock.get(func.lineno)
            if held_doc is not None and guard_matches(lock, held_doc):
                return True
        return any(guard_matches(lock, held) for held, _ in self.with_stack)

    def _check(self, name: str, node: ast.AST) -> None:
        if name not in self.declared or self._exempt(name, node):
            return
        lock = self.declared[name][0]
        f = self.mod.finding(
            "lock-guard", node,
            f"'{name}' is declared guarded-by {lock} but is accessed "
            f"outside any `with {lock}` scope (add the lock, a "
            f"`# holds-lock: {lock}` contract on the enclosing function, "
            "or a justified disable)",
            symbol=symbol_of(self.mod.tree, self.func_stack[-1])
            if self.func_stack else "",
        )
        if f:
            self.findings.append(f)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self._check(node.attr, node)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        # module-level guarded globals (declared without a class)
        if node.id in self.declared and self.declared[node.id][1] is None:
            self._check(node.id, node)


class LockOrderChecker(Checker):
    rule = "lock-order"

    def __init__(self) -> None:
        #: (from, to) -> first location observed
        self.edges: dict[tuple[str, str], tuple[str, int]] = {}

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        checker = self

        class _OrderWalker(_FunctionWalker):
            def on_with(self, name: str, lineno: int) -> None:
                if not _is_lockish(name):
                    return
                inner = self._canonical(name)
                for held, _ in self.with_stack:
                    if not _is_lockish(held):
                        continue
                    outer = self._canonical(held)
                    if outer != inner:
                        checker.edges.setdefault(
                            (outer, inner), (self.mod.rel, lineno))

            def _canonical(self, name: str) -> str:
                parts = name.split(".")
                if parts[0] in ("self", "cls") and self.class_stack:
                    parts[0] = self.class_stack[-1]
                return ".".join(parts[-2:]) if len(parts) >= 2 else parts[0]

        walker = _OrderWalker(mod)
        walker.visit(mod.tree)
        return []

    def finalize(self) -> list[Finding]:
        graph: dict[str, set] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        cycle = find_cycle(graph)
        if not cycle:
            return []
        locs = []
        for a, b in zip(cycle, cycle[1:]):
            where = self.edges.get((a, b))
            if where:
                locs.append(f"{a}→{b} at {where[0]}:{where[1]}")
        first = self.edges.get((cycle[0], cycle[1]), ("", 0))
        f = Finding(
            rule="lock-order",
            path=first[0],
            line=first[1],
            message=("static lock-acquisition cycle "
                     f"{' → '.join(cycle)} ({'; '.join(locs)}): two paths "
                     "can take these locks in opposite orders and "
                     "deadlock — pick one global order"),
            symbol="lock-order-graph",
        )
        f.snippet = " → ".join(cycle)
        return [f]
