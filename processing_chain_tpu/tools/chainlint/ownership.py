"""Pooled-buffer ownership: every acquire must reach a release.

The bug class PR 4/5 review passes kept catching by hand: a
``BufferPool.acquire`` result that never reaches ``release()`` /
``put(..., recycle=...)`` on some path silently strands a ~100 MB block
(the pool's weakref tracking turns it into a leak-of-one-allocation,
but at chunk cadence that is the high-water mark).

Model (deliberately function-local — the pool protocol is designed so
ownership transfers are explicit at call boundaries):

  * An *acquire site* is any call ``<pool>.acquire(...)`` where the
    receiver's last component contains "pool" (``pool``, ``self._pool``,
    ``DEFAULT_POOL``…).
  * The result must be bound to a simple name (directly or via a
    comprehension); acquiring into an expression — discarded, passed
    straight into a call, stored into a container — requires a
    ``# chainlint: ownership-transfer (<reason>)`` annotation on the
    statement, because the new owner is not visible to a local analysis.
  * A bound name reaches a *sink* when it is passed to a ``release``
    call, mentioned in a ``recycle=`` keyword, returned or yielded
    (ownership passes to the consumer — the bufpool protocol), mentioned
    on an ownership-transfer-annotated statement, or captured by a
    nested function (deferred-release callbacks).
  * Coverage is structural: starting from the statements after the
    acquire in its own block, a sink covers when it is reached on every
    path — a plain statement, an ``if`` with sinks in BOTH arms, a
    ``with`` body, a ``try`` whose ``finally`` (or body plus every
    handler) sinks, or an enclosing ``finally``. Sinks only inside one
    arm of a branch, or inside a loop the acquire is not in, leave a
    leaking path and the rule fires.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from .core import Checker, Finding, ModuleSource, symbol_of
from .locks import dotted

_SIMPLE_STMTS = (
    ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Expr, ast.Return,
    ast.Raise, ast.Assert, ast.Delete,
)
_TRY_TYPES = tuple(
    t for t in (getattr(ast, "Try", None), getattr(ast, "TryStar", None))
    if t is not None
)


def _is_pool_acquire(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute) or call.func.attr != "acquire":
        return False
    recv = dotted(call.func.value)
    return recv is not None and "pool" in recv.split(".")[-1].lower()


def _mentions(node: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(node)
    )


def _find_acquire(stmt: ast.stmt) -> Optional[ast.Call]:
    """The acquire call in a SIMPLE statement (compound statements are
    scanned via their nested simple statements, never wholesale — a
    `while` must not re-report its body's acquires)."""
    if not isinstance(stmt, _SIMPLE_STMTS):
        return None
    for n in ast.walk(stmt):
        if isinstance(n, ast.Call) and _is_pool_acquire(n):
            return n
    return None


def _iter_blocks(node: ast.AST) -> Iterable[list]:
    """Every statement list directly owned by `node` (nested function
    scopes excluded — they run on their own clock)."""
    for field_ in ("body", "orelse", "finalbody"):
        block = getattr(node, field_, None)
        if isinstance(block, list) and block and \
                isinstance(block[0], ast.stmt):
            yield block
    for handler in getattr(node, "handlers", []):
        yield handler.body


def _walk_blocks(func: ast.AST):
    """(block, owner-chain) pairs for every block in `func`'s own scope;
    owner-chain is the list of compound statements from `func` down."""
    def rec(node, chain):
        for block in _iter_blocks(node):
            yield block, chain
            for stmt in block:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                yield from rec(stmt, chain + [stmt])
    yield from rec(func, [])


class BufpoolOwnershipChecker(Checker):
    rule = "bufpool-ownership"

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(mod, node))
        return findings

    # ------------------------------------------------------------ sinks

    @staticmethod
    def _is_sink_stmt(mod: ModuleSource, stmt: ast.stmt, name: str) -> bool:
        if not isinstance(stmt, _SIMPLE_STMTS) or not _mentions(stmt, name):
            return False
        if stmt.lineno in mod.transfer_lines:
            return True
        if isinstance(stmt, ast.Return) and stmt.value is not None \
                and _mentions(stmt.value, name):
            return True
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and node.value is not None \
                    and _mentions(node.value, name):
                return True
            if isinstance(node, ast.Call):
                fn = node.func
                fname = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if fname in ("release", "recycle") and any(
                        _mentions(arg, name) for arg in node.args):
                    return True
                for kw in node.keywords:
                    if kw.arg == "recycle" and _mentions(kw.value, name):
                        return True
        return False

    # --------------------------------------------------------- coverage

    # tri-state path analysis over a statement block
    COVERED = "covered"          # every path reaches a sink
    LEAKED = "leaked"            # some path exits the function unsinked
    FALLTHROUGH = "fallthrough"  # runs off the end of the block unsinked

    def _analyze(self, mod: ModuleSource, stmts: list, name: str) -> str:
        for stmt in stmts:
            if self._is_sink_stmt(mod, stmt, name):
                return self.COVERED
            if isinstance(stmt, (ast.Return, ast.Raise)):
                return self.LEAKED  # exits without a sink
            if isinstance(stmt, ast.If):
                body = self._analyze(mod, stmt.body, name)
                orelse = (self._analyze(mod, stmt.orelse, name)
                          if stmt.orelse else self.FALLTHROUGH)
                if self.LEAKED in (body, orelse):
                    return self.LEAKED
                if body == orelse == self.COVERED:
                    return self.COVERED
                # at least one arm falls through unsinked: keep scanning
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                state = self._analyze(mod, stmt.body, name)
                if state != self.FALLTHROUGH:
                    return state
            elif isinstance(stmt, _TRY_TYPES):
                if stmt.finalbody:
                    state = self._analyze(mod, stmt.finalbody, name)
                    if state != self.FALLTHROUGH:
                        return state
                body = self._analyze(mod, stmt.body, name)
                handlers = [self._analyze(mod, h.body, name)
                            for h in stmt.handlers]
                if body == self.LEAKED or self.LEAKED in handlers:
                    return self.LEAKED
                if body == self.COVERED and handlers and \
                        all(h == self.COVERED for h in handlers):
                    return self.COVERED
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                # zero iterations possible -> a sink inside never covers,
                # but an unsinked return/raise inside still leaks
                if self._analyze(mod, stmt.body, name) == self.LEAKED:
                    return self.LEAKED
        return self.FALLTHROUGH

    def _covers(self, mod: ModuleSource, stmts: list, name: str) -> bool:
        """True when every control-flow path through `stmts` reaches a
        sink for `name` (or terminates the function through one)."""
        return self._analyze(mod, stmts, name) == self.COVERED

    # --------------------------------------------------------------- main

    def _check_function(self, mod: ModuleSource, func: ast.AST) -> list[Finding]:
        findings: list[Finding] = []
        sym = symbol_of(mod.tree, func) or func.name

        nested_defs = [
            n for n in ast.walk(func)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)) and n is not func
        ]

        for block, chain in _walk_blocks(func):
            for idx, stmt in enumerate(block):
                acq = _find_acquire(stmt)
                if acq is None:
                    continue
                target = None
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    target = stmt.targets[0].id
                elif isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name):
                    target = stmt.target.id
                if target is None:
                    if stmt.lineno not in mod.transfer_lines:
                        f = mod.finding(
                            self.rule, acq,
                            "pool.acquire() result is not bound to a "
                            "simple name — the new owner is invisible to "
                            "leak analysis; bind it, or annotate the "
                            "statement with `# chainlint: "
                            "ownership-transfer (reason)`",
                            symbol=sym)
                        if f:
                            findings.append(f)
                    continue

                if any(_mentions(nd, target) for nd in nested_defs):
                    continue  # captured for deferred release
                all_stmts = [
                    s for b, _ in _walk_blocks(func) for s in b
                    if s is not stmt
                ]
                if not any(self._is_sink_stmt(mod, s, target)
                           for s in all_stmts):
                    f = mod.finding(
                        self.rule, acq,
                        f"'{target}' is acquired from a pool but never "
                        "reaches release()/recycle=/return — the block "
                        "leaks; release it, or annotate the hand-off "
                        "with `# chainlint: ownership-transfer (reason)`",
                        symbol=sym)
                    if f:
                        findings.append(f)
                    continue
                covered = self._covers(mod, block[idx + 1:], target)
                if not covered:
                    # an enclosing try's finally can still cover
                    for owner in chain:
                        if isinstance(owner, _TRY_TYPES) and owner.finalbody \
                                and self._covers(mod, owner.finalbody, target):
                            covered = True
                            break
                if not covered:
                    f = mod.finding(
                        self.rule, acq,
                        f"'{target}' is not released on every path from "
                        "here (a branch, loop-skip, or error exit leaks "
                        "the block) — release unconditionally, in a "
                        "finally:, in both arms of the branch, or "
                        "annotate ownership-transfer",
                        symbol=sym)
                    if f:
                        findings.append(f)
        return findings
