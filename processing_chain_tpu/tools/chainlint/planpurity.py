"""plan-purity: hidden inputs must reach the plan, not just the bytes.

The store serves artifact BYTES by plan hash and chain-serve dedupes
across tenants by it, so any input that can influence what an encoder
writes while escaping the plan payload is a latent cache poisoner: two
processes with different knob values mint the same key for different
byte streams, and whichever commits first is served to everyone.

This checker makes that impossible to do silently. It traces
environment reads — ``os.environ.get``/``[]``/``in``, ``os.getenv``,
and *wrapper* functions whose env-key argument is a parameter
(``_env_int("PC_X")``) — through a statically-resolvable call graph
built over every linted module, and intersects them with two surfaces:

  * the **byte surface**: functions that (transitively) issue one of the
    registry's ``BYTE_SINK_CALLS`` (``VideoWriter``, ``run_bucket``, …)
    or are named in ``BYTE_PRODUCER_DEFS`` (the serve Executor
    ``run_batch`` protocol);
  * the **plan surface**: functions that construct plan payloads
    (methods named ``plan``, functions named ``*_plan``, or any function
    building a dict with an ``"op"`` key — the plan schema's marker).

An env input that reaches bytes must be declared in
``store/plan_schema.py`` (the registry, parsed by AST like
telemetry/catalog.py) as either

  * ``plan``   — and then it must ALSO reach the plan surface, so the
    plan field can never be deleted without re-opening the finding; or
  * ``exempt`` — and then every read site must carry a
    ``# plan-exempt: (reason)`` annotation; the claim is verified
    dynamically by the ``PC_PLAN_DEBUG`` recorder (utils/plandebug.py),
    which fails the suite on same-plan/different-bytes.

Resolution is deliberately conservative: only calls the AST can resolve
(same-module functions, ``self.``-methods of the enclosing class, and
package-relative imports) propagate taint, so the checker can miss but
never invent a path. Module-level reads (import-time constants) are out
of scope — they cannot vary between two jobs in one process.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Optional

from .core import Checker, Finding, ModuleSource
from .locks import dotted

RULE = "plan-purity"

#: fallback byte surface for trees without a registry module (self-tests
#: on scratch roots): the real tree always ships store/plan_schema.py,
#: whose declarations override these.
DEFAULT_SINKS = ("VideoWriter", "run_bucket", "write_batch",
                 "concat_video", "remux")
DEFAULT_PRODUCERS = ("run_batch",)


def load_schema(path: str) -> tuple[dict, tuple, tuple, tuple]:
    """(ENV_INPUTS, BYTE_SINK_CALLS, BYTE_PRODUCER_DEFS,
    OUT_OF_SCOPE_MODULES) parsed from the registry module's AST (never
    imported; works on any tree)."""
    env_inputs: dict = {}
    sinks: tuple = DEFAULT_SINKS
    producers: tuple = DEFAULT_PRODUCERS
    out_of_scope: tuple = ()
    if not os.path.isfile(path):
        return env_inputs, sinks, producers, out_of_scope
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            targets, value = [node.target.id], node.value
        else:
            continue
        if "ENV_INPUTS" in targets and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(v, ast.Dict):
                    entry = {}
                    for ek, ev in zip(v.keys, v.values):
                        if isinstance(ek, ast.Constant) and \
                                isinstance(ev, ast.Constant):
                            entry[ek.value] = ev.value
                    env_inputs[k.value] = entry
        if "BYTE_SINK_CALLS" in targets:
            sinks = tuple(
                c.value for c in ast.walk(value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            )
        if "BYTE_PRODUCER_DEFS" in targets:
            producers = tuple(
                c.value for c in ast.walk(value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            )
        if "OUT_OF_SCOPE_MODULES" in targets:
            out_of_scope = tuple(
                c.value for c in ast.walk(value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            )
    return env_inputs, sinks, producers, out_of_scope


@dataclass
class _EnvRead:
    var: str
    line: int
    exempt_reason: Optional[str]  # a valid # plan-exempt annotation
    suppressed: bool              # a chainlint disable covers the site
    snippet: str


@dataclass
class _Func:
    """One function/method node of the interprocedural graph."""

    rel: str
    qual: str
    name: str
    enclosing_class: Optional[str]
    reads: list = field(default_factory=list)     # [_EnvRead]
    #: (dotted callee name, positional literal-str args (None for
    #: non-literals), call line)
    calls: list = field(default_factory=list)
    #: parameter index used as the env-var name in a read (wrapper
    #: functions like _env_int(name))
    param_env_index: Optional[int] = None
    contains_sink: bool = False
    is_plan_surface: bool = False
    is_producer: bool = False

    @property
    def key(self) -> tuple:
        return (self.rel, self.qual)


@dataclass
class _ModuleFacts:
    rel: str
    funcs: dict = field(default_factory=dict)      # qual -> _Func
    #: raw import records: (alias, candidate module parts tuple,
    #: imported name or None) — resolved in finalize against the set of
    #: visited modules
    imports: list = field(default_factory=list)
    plan_exempt: dict = field(default_factory=dict)
    #: suppression state carried past visit time, so reads synthesized
    #: at wrapper call sites in finalize honor site disables too
    disables: dict = field(default_factory=dict)
    file_disabled: bool = False

    def suppressed(self, line: int) -> bool:
        return self.file_disabled or RULE in self.disables.get(line, ())


def _is_environ(expr: ast.AST) -> bool:
    name = dotted(expr) or ""
    return name == "os.environ" or name.endswith(".environ") or \
        name == "environ"


class _Collector:
    """Per-module AST walk building _Func records with qualnames."""

    def __init__(self, mod: ModuleSource, facts: _ModuleFacts,
                 sinks: tuple, producers: tuple) -> None:
        self.mod = mod
        self.facts = facts
        self.sinks = sinks
        self.producers = producers

    def collect(self) -> None:
        self._imports(self.mod.tree)
        for node in self.mod.tree.body:
            self._visit(node, prefix=[], enclosing_class=None, func=None)

    # ------------------------------------------------------------ imports

    def _imports(self, tree: ast.Module) -> None:
        pkg_parts = self.facts.rel.split("/")[:-1]  # module's package dir
        for node in ast.walk(tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            if node.level:
                if node.level - 1 > len(pkg_parts):
                    continue
                base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
            else:
                base = []
            mod_parts = (node.module or "").split(".") if node.module else []
            mod_parts = [p for p in mod_parts if p]
            for alias in node.names:
                if alias.name == "*":
                    continue
                self.facts.imports.append((
                    alias.asname or alias.name,
                    tuple(base + mod_parts),
                    alias.name,
                ))

    # -------------------------------------------------------------- walk

    def _visit(self, node: ast.AST, prefix: list,
               enclosing_class: Optional[str], func: Optional[_Func]) -> None:
        if isinstance(node, ast.ClassDef):
            for child in node.body:
                self._visit(child, prefix + [node.name], node.name, None)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = ".".join(prefix + [node.name])
            f = _Func(
                rel=self.facts.rel, qual=qual, name=node.name,
                enclosing_class=enclosing_class,
                is_producer=node.name in self.producers,
                is_plan_surface=(
                    node.name == "plan" or node.name.endswith("_plan")
                ),
            )
            self.facts.funcs[qual] = f
            params = [a.arg for a in (
                node.args.posonlyargs + node.args.args
            )]
            f._params = params
            for child in node.body:
                self._visit(child, prefix + [node.name], enclosing_class, f)
            return
        if func is not None:
            self._inspect(node, func)
        for child in ast.iter_child_nodes(node):
            self._visit(child, prefix, enclosing_class, func)

    # ----------------------------------------------------------- inspect

    def _read(self, func: _Func, var: str, line: int) -> None:
        func.reads.append(_EnvRead(
            var=var, line=line,
            exempt_reason=self.mod.plan_exempt.get(line),
            suppressed=self.mod.disabled(RULE, line),
            snippet=self.mod.line_text(line),
        ))

    def _inspect(self, node: ast.AST, func: _Func) -> None:
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            tail = name.split(".")[-1]
            if tail in self.sinks:
                func.contains_sink = True
            if name == "os.getenv" or name.endswith("environ.get") or \
                    name == "getenv":
                if node.args:
                    first = node.args[0]
                    if isinstance(first, ast.Constant) and \
                            isinstance(first.value, str):
                        self._read(func, first.value, node.lineno)
                    elif isinstance(first, ast.Name) and \
                            first.id in getattr(func, "_params", ()):
                        func.param_env_index = \
                            getattr(func, "_params").index(first.id)
            else:
                lits = tuple(
                    a.value if isinstance(a, ast.Constant)
                    and isinstance(a.value, str) else None
                    for a in node.args[:6]
                )
                func.calls.append((name, lits, node.lineno))
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and _is_environ(node.value):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                self._read(func, sl.value, node.lineno)
        elif isinstance(node, ast.Compare) and node.comparators and \
                _is_environ(node.comparators[0]) and \
                any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            if isinstance(node.left, ast.Constant) and \
                    isinstance(node.left.value, str):
                self._read(func, node.left.value, node.lineno)
        elif isinstance(node, ast.Constant) and node.value == "op":
            # a dict/subscript key "op" marks plan-payload construction
            # (store/plan_schema: every plan carries its op name)
            func.is_plan_surface = True


class PlanPurityChecker(Checker):
    rule = RULE

    def __init__(self, schema_path: str) -> None:
        self.schema_path = schema_path
        (self.env_inputs, self.sinks, self.producers,
         self.out_of_scope) = load_schema(schema_path)
        self.modules: dict[str, _ModuleFacts] = {}
        self.schema_rel: Optional[str] = None
        self.schema_visited = False

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        facts = _ModuleFacts(rel=mod.rel)
        facts.plan_exempt = dict(mod.plan_exempt)
        facts.disables = {ln: set(rs) for ln, rs in mod.disables.items()}
        facts.file_disabled = RULE in mod.file_disables
        _Collector(mod, facts, self.sinks, self.producers).collect()
        self.modules[mod.rel] = facts
        if os.path.normpath(os.path.abspath(mod.path)) == \
                os.path.normpath(os.path.abspath(self.schema_path)):
            self.schema_visited = True
            self.schema_rel = mod.rel
        return []

    # ---------------------------------------------------------- finalize

    def _resolve_imports(self) -> dict:
        """alias maps per module: alias -> ("mod", rel) | ("func", key)."""
        visited = set(self.modules)
        out: dict = {}
        for rel, facts in self.modules.items():
            amap: dict = {}
            for alias, base_parts, name in facts.imports:
                as_mod = "/".join(base_parts + (name,)) + ".py"
                holder = "/".join(base_parts) + ".py" if base_parts else None
                if as_mod in visited:
                    amap[alias] = ("mod", as_mod)
                elif holder and holder in visited and \
                        name in self.modules[holder].funcs:
                    amap[alias] = ("func", (holder, name))
            out[rel] = amap
        return out

    def _build_graph(self) -> tuple[dict, dict]:
        """(edges: key -> set of callee keys, funcs: key -> _Func); also
        propagates wrapper env reads (param-named keys) to call sites."""
        funcs: dict = {}
        for facts in self.modules.values():
            for f in facts.funcs.values():
                funcs[f.key] = f
        aliases = self._resolve_imports()
        edges: dict = {k: set() for k in funcs}
        for rel, facts in self.modules.items():
            amap = aliases.get(rel, {})
            local = facts.funcs
            for f in facts.funcs.values():
                for name, lits, line in f.calls:
                    target = None
                    parts = name.split(".") if name else []
                    if len(parts) == 1:
                        # nearest enclosing scope first (nested helper
                        # siblings), then module level, then imports
                        pref = f.qual.split(".")[:-1]
                        while target is None:
                            cand = ".".join(pref + [parts[0]])
                            if cand in local:
                                target = (rel, cand)
                            if not pref:
                                break
                            pref = pref[:-1]
                        if target is None and \
                                amap.get(parts[0], ("", ""))[0] == "func":
                            target = amap[parts[0]][1]
                    elif len(parts) == 2:
                        head, meth = parts
                        if head in ("self", "cls") and f.enclosing_class:
                            cand = f"{f.enclosing_class}.{meth}"
                            if cand in local:
                                target = (rel, cand)
                        elif amap.get(head, ("", ""))[0] == "mod":
                            mod_rel = amap[head][1]
                            if meth in self.modules[mod_rel].funcs:
                                target = (mod_rel, meth)
                        elif amap.get(head, ("", ""))[0] == "func":
                            pass  # attribute on an imported function: skip
                    if target is not None and target in funcs:
                        edges[f.key].add(target)
                        callee = funcs[target]
                        if callee.param_env_index is not None and \
                                len(lits) > callee.param_env_index and \
                                lits[callee.param_env_index] is not None:
                            var = lits[callee.param_env_index]
                            mod_facts = self.modules[rel]
                            f.reads.append(_EnvRead(
                                var=var, line=line,
                                exempt_reason=mod_facts.plan_exempt.get(line),
                                suppressed=mod_facts.suppressed(line),
                                snippet="",
                            ))
        return edges, funcs

    def finalize(self) -> list[Finding]:
        if not self.modules:
            return []
        edges, funcs = self._build_graph()

        # transitive closure over callees: env reads + sink reachability.
        # ITERATIVE FIXPOINT, not memoized DFS — a memo filled while a
        # cycle was cut open records truncated answers for every node on
        # the cycle, silently dropping reads/sinks in mutually recursive
        # call chains. The graph is a few thousand nodes at lint
        # cadence; iterating to fixpoint is cheap and cycle-correct.
        reads: dict = {k: {r.var for r in f.reads}
                       for k, f in funcs.items()}
        sink: dict = {k: f.contains_sink or f.is_producer
                      for k, f in funcs.items()}
        changed = True
        while changed:
            changed = False
            for key in funcs:
                for callee in edges.get(key, ()):
                    if callee not in funcs:
                        continue
                    if not reads[callee] <= reads[key]:
                        reads[key] |= reads[callee]
                        changed = True
                    if sink[callee] and not sink[key]:
                        sink[key] = True
                        changed = True

        tainted: set = set()
        plan_vars: set = set()
        for key, f in funcs.items():
            if sink[key]:
                tainted |= reads[key]
            if f.is_plan_surface:
                plan_vars |= reads[key]

        findings: list[Finding] = []

        def report(f: _Func, read: _EnvRead, message: str) -> None:
            if read.suppressed:
                return
            finding = Finding(rule=self.rule, path=f.rel, line=read.line,
                              message=message, symbol=f.qual)
            finding.snippet = read.snippet or f"{read.var}"
            findings.append(finding)

        seen_vars: set = set()
        for f in funcs.values():
            out_of_scope = any(
                f.rel == p or f.rel.startswith(p) for p in self.out_of_scope
            )
            for read in f.reads:
                seen_vars.add(read.var)
                if read.var not in tainted or out_of_scope:
                    continue
                decl = self.env_inputs.get(read.var)
                if decl is None:
                    report(f, read,
                         f"hidden input {read.var!r} can reach artifact "
                         "bytes but is not declared in "
                         "store/plan_schema.py — fold it into the plan "
                         "payload (status 'plan') or declare it 'exempt' "
                         "and annotate the read '# plan-exempt: (reason)'")
                elif decl.get("status") == "plan":
                    if read.var not in plan_vars:
                        report(f, read,
                             f"{read.var!r} is declared plan-covered in "
                             "store/plan_schema.py but no plan "
                             "construction reads it — the plan field is "
                             "missing or went stale")
                elif decl.get("status") == "covered":
                    if not decl.get("via") or not decl.get("reason"):
                        report(f, read,
                             f"{read.var!r} is declared 'covered' in "
                             "store/plan_schema.py but the entry names no "
                             "'via'/'reason' — say which derived plan "
                             "value captures it")
                elif decl.get("status") == "exempt":
                    if read.exempt_reason is None:
                        report(f, read,
                             f"{read.var!r} is declared exempt in "
                             "store/plan_schema.py but this byte-reaching "
                             "read carries no '# plan-exempt: (reason)' "
                             "annotation")
                else:
                    report(f, read,
                         f"{read.var!r} has unknown status "
                         f"{decl.get('status')!r} in store/plan_schema.py "
                         "(expected 'plan' or 'exempt')")

        # registry hygiene, full-tree runs only (the schema module was
        # among the linted files): a declared input nobody reads is a
        # stale entry — mirror the baseline's stale-entry discipline
        if self.schema_visited and self.schema_rel:
            for var in sorted(set(self.env_inputs) - seen_vars):
                f_ = Finding(
                    rule=self.rule, path=self.schema_rel, line=1,
                    message=f"{var!r} is declared in store/plan_schema.py "
                            "but no linted module reads it — stale "
                            "declaration, remove it",
                    symbol="schema-stale")
                f_.snippet = var
                findings.append(f_)
        return findings
