"""queue-transition: every serve queue state write is a declared edge.

The serve daemon's crash story rests on the DurableQueue record state
machine (queued → running → done | failed, plus the re-arm and
recovery edges). PR 7's review rounds found the failure mode twice: a
state write added in one code path that recovery or the scheduler's
settle pass didn't know about, stranding records. The cure is ONE
declared transition table in ``serve/queue.py`` (``STATES``,
``INITIAL``, ``TRANSITIONS``) shared by three consumers:

  * this static checker — every ``<record>.state = "…"`` assignment in
    a serve-queue module must carry a ``# queue-transition: <from> ->
    <to>`` annotation naming a declared edge (multiple sources:
    ``a|b -> c``); undeclared writes, unknown states, non-literal
    assignments and constructor states other than ``INITIAL`` are
    findings, and a declared edge no annotated write implements is a
    stale-table finding (baseline-style hygiene);
  * ``tools queue-crashcheck`` — fault-injects every atomic-write
    boundary in claim/settle/recover and asserts reload lands every
    record in a declared state with no stranded ``running`` records;
  * docs/SERVE.md — the rendered table between the
    ``<!-- queue-transitions:begin/end -->`` markers is drift-checked
    both ways against the declaration (render it with
    ``tools queue-crashcheck --render-table``).

Scope: ``serve/queue.py`` itself plus any linted module that imports
``JobRecord``/``DurableQueue`` from it — the only places a queue record
can leak to.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from .core import Checker, Finding, ModuleSource, symbol_of

RULE = "queue-transition"

#: names whose import marks a module as handling queue records
_SCOPE_NAMES = ("JobRecord", "DurableQueue")

_DOC_BEGIN = "<!-- queue-transitions:begin -->"
_DOC_END = "<!-- queue-transitions:end -->"
_DOC_EDGE_RE = re.compile(r"`([a-z]+)\s*(?:->|→)\s*([a-z]+)`")


def load_transitions(path: str) -> tuple[tuple, Optional[str], set, dict]:
    """(STATES, INITIAL, TRANSITIONS, edge meanings) parsed from
    serve/queue.py's AST — never imported, so the linter works on any
    tree. The meaning of each edge is its trailing comment on the
    declaration line, so the rendered docs/SERVE.md table has exactly
    ONE source (no second copy of the semantics to drift)."""
    states: tuple = ()
    initial: Optional[str] = None
    transitions: set = set()
    meanings: dict = {}
    if not os.path.isfile(path):
        return states, initial, transitions, meanings
    with open(path, encoding="utf-8") as f:
        text = f.read()
    lines = text.splitlines()
    tree = ast.parse(text)
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            targets, value = [node.target.id], node.value
        else:
            continue
        if value is None:
            continue
        if "STATES" in targets:
            states = tuple(
                c.value for c in ast.walk(value)
                if isinstance(c, ast.Constant) and isinstance(c.value, str)
            )
        if "INITIAL" in targets and isinstance(value, ast.Constant):
            initial = value.value
        if "TRANSITIONS" in targets:
            for sub in ast.walk(value):
                if isinstance(sub, ast.Tuple) and len(sub.elts) == 2 and \
                        all(isinstance(e, ast.Constant) and
                            isinstance(e.value, str) for e in sub.elts):
                    edge = (sub.elts[0].value, sub.elts[1].value)
                    transitions.add(edge)
                    if 1 <= sub.lineno <= len(lines):
                        _, hash_, comment = \
                            lines[sub.lineno - 1].partition("#")
                        if hash_:
                            meanings[edge] = comment.strip()
    return states, initial, transitions, meanings


def render_table(states: tuple, initial: Optional[str],
                 transitions: set, meanings: Optional[dict] = None) -> str:
    """The markdown block docs/SERVE.md carries between the markers.
    `meanings` comes from load_transitions — the trailing comments on
    the declaration lines — so the table is rendered from exactly one
    source and a new edge can never ship with a silently blank cell."""
    meanings = meanings or {}
    lines = [
        _DOC_BEGIN,
        f"Initial state: `{initial}`. States: "
        + ", ".join(f"`{s}`" for s in states) + ".",
        "",
        "| edge | meaning |",
        "|------|---------|",
    ] + [
        f"| `{a} -> {b}` | {meanings.get((a, b), '')} |"
        for a, b in sorted(transitions)
    ] + [_DOC_END]
    return "\n".join(lines)


class QueueTransitionChecker(Checker):
    rule = RULE

    def __init__(self, queue_path: str, doc_path: str) -> None:
        self.queue_path = queue_path
        self.doc_path = doc_path
        self.states, self.initial, self.transitions, self.meanings = \
            load_transitions(queue_path)
        self.queue_visited = False
        self.queue_rel = "processing_chain_tpu/serve/queue.py"
        #: declared edges actually implemented by an annotated write
        self.implemented: set = set()

    # ------------------------------------------------------------- scope

    def _in_scope(self, mod: ModuleSource) -> bool:
        if os.path.normpath(os.path.abspath(mod.path)) == \
                os.path.normpath(os.path.abspath(self.queue_path)):
            return True
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                if any(a.name in _SCOPE_NAMES for a in node.names):
                    return True
            if isinstance(node, ast.ClassDef) and node.name in _SCOPE_NAMES:
                return True
        return False

    # ------------------------------------------------------------- visit

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        if not self.transitions or not self._in_scope(mod):
            return []
        is_queue_mod = os.path.normpath(os.path.abspath(mod.path)) == \
            os.path.normpath(os.path.abspath(self.queue_path))
        if is_queue_mod:
            self.queue_visited = True
            self.queue_rel = mod.rel
        findings: list[Finding] = []

        def add(node, message):
            f = mod.finding(self.rule, node, message,
                            symbol=symbol_of(mod.tree, node))
            if f:
                findings.append(f)

        for node in ast.walk(mod.tree):
            # --- record.state = <value> -----------------------------------
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                state_targets = [
                    t for t in targets
                    if isinstance(t, ast.Attribute) and t.attr == "state"
                ]
                if state_targets and node.value is not None:
                    self._check_write(mod, node, add)
            # --- JobRecord(..., state="…") --------------------------------
            if isinstance(node, ast.Call):
                name = node.func
                callee = name.id if isinstance(name, ast.Name) else (
                    name.attr if isinstance(name, ast.Attribute) else ""
                )
                if callee == "JobRecord":
                    for kw in node.keywords:
                        if kw.arg == "state":
                            if not (isinstance(kw.value, ast.Constant) and
                                    kw.value.value == self.initial):
                                add(node,
                                    "JobRecord must be constructed in the "
                                    f"declared initial state {self.initial!r}"
                                    " — later states only via declared "
                                    "transitions")
        # the dataclass default itself (queue.py): state must default to
        # INITIAL
        if is_queue_mod:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef) and \
                        node.name == "JobRecord":
                    for stmt in node.body:
                        if isinstance(stmt, ast.AnnAssign) and \
                                isinstance(stmt.target, ast.Name) and \
                                stmt.target.id == "state" and \
                                stmt.value is not None:
                            if not (isinstance(stmt.value, ast.Constant) and
                                    stmt.value.value == self.initial):
                                add(stmt,
                                    "JobRecord.state default must be the "
                                    f"declared initial state {self.initial!r}")
        return findings

    def _check_write(self, mod: ModuleSource, node: ast.AST, add) -> None:
        value = node.value
        if not (isinstance(value, ast.Constant) and
                isinstance(value.value, str)):
            add(node,
                "queue state must be assigned as a string literal — a "
                "computed state cannot be checked against the declared "
                "transition table")
            return
        dst = value.value
        if dst not in self.states:
            add(node,
                f"{dst!r} is not a declared queue state "
                f"(serve/queue.py STATES: {', '.join(self.states)})")
            return
        edge = mod.queue_edges.get(node.lineno)
        if edge is None:
            add(node,
                f"undeclared queue state write (-> {dst!r}): annotate "
                "with '# queue-transition: <from> -> <to>' naming an "
                "edge declared in serve/queue.py TRANSITIONS")
            return
        sources, ann_dst = edge
        if ann_dst != dst:
            add(node,
                f"queue-transition annotation says '-> {ann_dst}' but the "
                f"assignment writes {dst!r}")
            return
        for src in sources:
            if src not in self.states:
                add(node,
                    f"{src!r} in the queue-transition annotation is not a "
                    "declared queue state")
            elif (src, dst) not in self.transitions:
                add(node,
                    f"edge {src} -> {dst} is not declared in "
                    "serve/queue.py TRANSITIONS — declare it (and teach "
                    "recovery/crashcheck about it) or fix the write")
            else:
                self.implemented.add((src, dst))

    # ---------------------------------------------------------- finalize

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        if not self.transitions or not self.queue_visited:
            return findings
        rel_queue = self.queue_rel
        # declaration sanity: the table every other consumer derives
        # from must be internally closed — an edge naming an undeclared
        # state (or an initial state outside STATES) would let writes
        # pass the per-site check while recovery and the crashcheck
        # harness have no idea the state exists
        if self.initial not in self.states:
            f = Finding(
                rule=self.rule, path=rel_queue, line=1,
                message=f"INITIAL {self.initial!r} is not in the declared "
                        "STATES tuple",
                symbol="table-unsound")
            findings.append(f)
        for a, b in sorted(self.transitions):
            for endpoint in (a, b):
                if endpoint not in self.states:
                    f = Finding(
                        rule=self.rule, path=rel_queue, line=1,
                        message=f"declared edge {a} -> {b} names "
                                f"{endpoint!r}, which is not in STATES — "
                                "declare the state or fix the edge",
                        symbol="table-unsound")
                    f.snippet = f"{a} -> {b}"
                    findings.append(f)
        for a, b in sorted(self.transitions - self.implemented):
            f = Finding(
                rule=self.rule, path=rel_queue, line=1,
                message=f"declared edge {a} -> {b} is implemented by no "
                        "annotated state write — stale table entry, "
                        "remove it or annotate its implementation",
                symbol="table-stale")
            f.snippet = f"{a} -> {b}"
            findings.append(f)
        # docs/SERVE.md drift, both ways (telemetry-doc discipline)
        try:
            with open(self.doc_path, encoding="utf-8") as fh:
                doc = fh.read()
        except OSError:
            doc = ""
        rel_doc = "docs/" + os.path.basename(self.doc_path)
        if _DOC_BEGIN in doc and _DOC_END in doc:
            block = doc.split(_DOC_BEGIN, 1)[1].split(_DOC_END, 1)[0]
            doc_edges = {
                (a, b) for a, b in _DOC_EDGE_RE.findall(block)
            }
            for a, b in sorted(self.transitions - doc_edges):
                f = Finding(
                    rule=self.rule, path=rel_doc, line=1,
                    message=f"declared edge {a} -> {b} is missing from the "
                            f"{rel_doc} transition table — re-render it "
                            "with `tools queue-crashcheck --render-table`",
                    symbol="doc-drift")
                f.snippet = f"{a} -> {b}"
                findings.append(f)
            for a, b in sorted(doc_edges - self.transitions):
                f = Finding(
                    rule=self.rule, path=rel_doc, line=1,
                    message=f"{rel_doc} documents edge {a} -> {b} but "
                            "serve/queue.py TRANSITIONS does not declare "
                            "it — stale doc or missing declaration",
                    symbol="doc-drift")
                f.snippet = f"{a} -> {b}"
                findings.append(f)
        else:
            f = Finding(
                rule=self.rule, path=rel_doc, line=1,
                message=f"{rel_doc} carries no queue-transition table "
                        f"(markers {_DOC_BEGIN} … {_DOC_END}) — render one "
                        "with `tools queue-crashcheck --render-table`",
                symbol="doc-drift")
            findings.append(f)
        return findings
