"""Subprocess hygiene: one blessed door for external commands.

``utils.runner.shell`` is the chain's only sanctioned subprocess entry:
it takes LIST argv, bounds wall time, and converts failures into
``ChainError`` with a bounded stderr tail. Everything else is a finding:

  * direct ``subprocess.run/Popen/call/check_call/check_output``,
    ``os.system``, ``os.popen`` outside utils/runner.py;
  * ``shell=True`` anywhere (literal): an interpolated command string is
    one filename-with-a-space away from an injection or a quoting bug;
  * ``shell("…string…")`` / ``shell(f"…")`` — the helper accepts a
    string for historical reasons, but chain code must pass list argv.

Infrastructure call sites that genuinely cannot route through
``runner.shell`` (the native-library bootstrap that runs before the
package is importable-safe, the device health probe) carry inline
disables with their reasons — visible at the call site, counted here.
"""

from __future__ import annotations

import ast

from .core import Checker, Finding, ModuleSource, symbol_of
from .locks import dotted

_BANNED = {
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "subprocess.getoutput", "subprocess.getstatusoutput",
    "os.system", "os.popen",
}

#: the blessed implementation itself
_ALLOW_FILES = ("processing_chain_tpu/utils/runner.py",)


class SubprocessHygieneChecker(Checker):
    rule = "subprocess-hygiene"

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        if mod.rel in _ALLOW_FILES:
            return []
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            sym = ""
            if name in _BANNED:
                sym = symbol_of(mod.tree, node)
                f = mod.finding(
                    self.rule, node,
                    f"direct {name}() — external commands go through "
                    "utils.runner.shell (list argv, timeout, bounded "
                    "stderr in ChainError)",
                    symbol=sym)
                if f:
                    findings.append(f)
            for kw in node.keywords:
                if kw.arg == "shell" and isinstance(kw.value, ast.Constant) \
                        and kw.value.value is True:
                    f = mod.finding(
                        self.rule, node,
                        "shell=True — pass list argv instead of an "
                        "interpolated command string",
                        symbol=sym or symbol_of(mod.tree, node))
                    if f:
                        findings.append(f)
            if name.split(".")[-1] == "shell" and node.args:
                first = node.args[0]
                if isinstance(first, ast.JoinedStr) or (
                        isinstance(first, ast.Constant)
                        and isinstance(first.value, str)):
                    f = mod.finding(
                        self.rule, node,
                        "runner.shell() called with a command STRING — "
                        "pass list argv so no shell ever parses it",
                        symbol=symbol_of(mod.tree, node))
                    if f:
                        findings.append(f)
        return findings
