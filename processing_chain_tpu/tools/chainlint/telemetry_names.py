"""Telemetry-name registry: call sites ↔ catalog ↔ docs, all in sync.

Names are parsed out of ``telemetry/catalog.py`` by AST (not imported),
so the linter works on any tree — including the test fixtures. Three
obligations, all under the one ``telemetry-name`` rule:

  1. every metric-constructor literal (``counter("chain_…")`` etc.) is
     declared in ``METRICS`` with the same kind, and every ``emit("…")``
     literal is declared in ``EVENTS``;
  2. dynamic (non-literal) names are findings — a name the catalog can't
     see is a name the doc drift check can't protect;
  3. the catalog and docs/TELEMETRY.md agree both ways: every catalog
     name appears in the doc, every ``chain_[a-z_]*`` token in the doc
     appears in the catalog — and the same for alert rules: every
     ``ALERT_RULES`` key is documented as an ``alert:<name>`` token,
     every ``alert:<name>`` token resolves to a declared rule.

The registry plumbing itself (telemetry/metrics.py, events.py, the
``telemetry/__init__`` re-exports) is allowlisted: its parameters ARE
the dynamic names.
"""

from __future__ import annotations

import ast
import os
import re

from .core import Checker, Finding, ModuleSource, symbol_of
from .locks import dotted

_METRIC_CTORS = ("counter", "gauge", "histogram")
#: registry plumbing whose name arguments are parameters by design
_ALLOW_FILES = (
    "processing_chain_tpu/telemetry/metrics.py",
    "processing_chain_tpu/telemetry/events.py",
    "processing_chain_tpu/telemetry/__init__.py",
    "processing_chain_tpu/telemetry/catalog.py",
)
#: emit receivers that are the chain event log (`ln.emit(...)` on a
#: pipeline lane is NOT an event emission)
_EMIT_RECEIVERS = ("telemetry", "tm", "events", "EVENTS")

_DOC_NAME_RE = re.compile(r"`(chain_[a-z0-9_]+)`")
_DOC_ALERT_RE = re.compile(r"`alert:([a-z0-9_]+)`")


def load_catalog(path: str) -> tuple[dict, set, set]:
    """(METRICS dict, EVENTS set, ALERT_RULES names) parsed from the
    catalog module's AST."""
    metrics: dict = {}
    events: set = set()
    rules: set = set()
    if not os.path.isfile(path):
        return metrics, events, rules
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read())
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            targets = [node.target.id]
            value = node.value
        else:
            continue
        if "METRICS" in targets and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(v, ast.Constant):
                    metrics[k.value] = v.value
        if "EVENTS" in targets:
            for sub in ast.walk(value):
                if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                    events.add(sub.value)
        if "ALERT_RULES" in targets and isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    rules.add(k.value)
    return metrics, events, rules


class TelemetryNameChecker(Checker):
    rule = "telemetry-name"

    def __init__(self, catalog_path: str, doc_path: str) -> None:
        self.catalog_path = catalog_path
        self.doc_path = doc_path
        self.metrics, self.events, self.rules = load_catalog(catalog_path)

    def visit_module(self, mod: ModuleSource) -> list[Finding]:
        if mod.rel in _ALLOW_FILES or not (self.metrics or self.events):
            return []
        findings: list[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            last = name.split(".")[-1]
            if last in _METRIC_CTORS and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    lit = first.value
                    if not lit.startswith("chain_"):
                        continue  # a foreign registry / test helper
                    if lit not in self.metrics:
                        f = mod.finding(
                            self.rule, node,
                            f"metric {lit!r} is not declared in "
                            "telemetry/catalog.py — declare it there and "
                            "in docs/TELEMETRY.md",
                            symbol=symbol_of(mod.tree, node))
                        if f:
                            findings.append(f)
                    elif self.metrics[lit] != last:
                        f = mod.finding(
                            self.rule, node,
                            f"metric {lit!r} is declared as "
                            f"{self.metrics[lit]} in the catalog but "
                            f"constructed here as {last}",
                            symbol=symbol_of(mod.tree, node))
                        if f:
                            findings.append(f)
            if last == "emit":
                recv = name.split(".")[:-1]
                if recv and recv[-1] not in _EMIT_RECEIVERS:
                    continue  # someone else's emit (pipeline lanes, logging)
                if not node.args:
                    continue
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(first.value, str):
                    if first.value not in self.events:
                        f = mod.finding(
                            self.rule, node,
                            f"event {first.value!r} is not declared in "
                            "telemetry/catalog.py EVENTS — declare it "
                            "there and in docs/TELEMETRY.md",
                            symbol=symbol_of(mod.tree, node))
                        if f:
                            findings.append(f)
                else:
                    f = mod.finding(
                        self.rule, node,
                        "dynamic event name — emit() literals are the "
                        "contract the catalog and doc drift checks "
                        "protect; use a declared literal",
                        symbol=symbol_of(mod.tree, node))
                    if f:
                        findings.append(f)
        return findings

    def finalize(self) -> list[Finding]:
        findings: list[Finding] = []
        if not (self.metrics or self.events or self.rules):
            return findings
        try:
            with open(self.doc_path, encoding="utf-8") as f:
                doc = f.read()
        except OSError:
            f_ = Finding(
                rule=self.rule, path=os.path.basename(self.doc_path), line=1,
                message=f"telemetry doc {self.doc_path} is missing — the "
                        "catalog has nothing to agree with",
                symbol="doc-drift")
            return [f_]
        doc_lines = doc.splitlines()
        rel_doc = os.path.basename(os.path.dirname(self.doc_path) or ".") \
            + "/" + os.path.basename(self.doc_path)
        rel_cat = "processing_chain_tpu/telemetry/catalog.py"

        def _doc_line(tok: str) -> int:
            for i, line in enumerate(doc_lines, 1):
                if tok in line:
                    return i
            return 1

        for name in sorted(self.metrics):
            if name not in doc:
                f_ = Finding(
                    rule=self.rule, path=rel_cat, line=1,
                    message=f"metric {name!r} is in the catalog but not "
                            "documented in docs/TELEMETRY.md",
                    symbol="doc-drift")
                f_.snippet = name
                findings.append(f_)
        for name in sorted(self.events):
            if name not in doc:
                f_ = Finding(
                    rule=self.rule, path=rel_cat, line=1,
                    message=f"event {name!r} is in the catalog but not "
                            "documented in docs/TELEMETRY.md",
                    symbol="doc-drift")
                f_.snippet = name
                findings.append(f_)
        for name in sorted(self.rules):
            if f"alert:{name}" not in doc:
                f_ = Finding(
                    rule=self.rule, path=rel_cat, line=1,
                    message=f"alert rule {name!r} is in the catalog but "
                            "not documented in docs/TELEMETRY.md (name "
                            "it as `alert:" + name + "`)",
                    symbol="doc-drift")
                f_.snippet = name
                findings.append(f_)
        for tok in sorted(set(_DOC_ALERT_RE.findall(doc))):
            if tok not in self.rules:
                f_ = Finding(
                    rule=self.rule, path=rel_doc,
                    line=_doc_line(f"alert:{tok}"),
                    message=f"docs/TELEMETRY.md names alert rule {tok!r} "
                            "but telemetry/catalog.py ALERT_RULES does "
                            "not declare it — stale doc or missing "
                            "declaration",
                    symbol="doc-drift")
                f_.snippet = tok
                findings.append(f_)
        for tok in sorted(set(_DOC_NAME_RE.findall(doc))):
            base = tok
            # the doc's histogram tables legitimately reference the
            # derived _bucket/_sum/_count series
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix) and base[: -len(suffix)] in self.metrics:
                    base = base[: -len(suffix)]
            if base not in self.metrics:
                f_ = Finding(
                    rule=self.rule, path=rel_doc, line=_doc_line(tok),
                    message=f"docs/TELEMETRY.md names {tok!r} but the "
                            "catalog does not declare it — stale doc or "
                            "missing declaration",
                    symbol="doc-drift")
                f_.snippet = tok
                findings.append(f_)
        return findings
