"""Purge transient chain byproducts from a database folder.

Parity target: reference util/clean_logs.sh:19-23 — removes `*.log`,
`*.mbtree` (x264 two-pass lookahead stats) and `*.temp` files left in the
database tree. Here the two-pass stats files (`*.stats`, `*.stats.cutree`,
the libav names for what x264's CLI calls mbtree) and trace reports are
included; provenance `.log` files are only removed with `--provenance`
since they are the chain's per-artifact audit trail.
"""

from __future__ import annotations

import argparse
import fnmatch
import os
from typing import Optional, Sequence

from ..utils.log import get_logger

# NOTE: *.inprogress crash sentinels (engine/jobs.Job) are deliberately
# NOT purged here — deleting one would make the next run trust a
# possibly-truncated artifact; Job completion removes them itself.
TRANSIENT_PATTERNS = (
    "*.mbtree", "*.temp", "*.stats", "*.stats.cutree", "*.stats.mbtree",
)
PROVENANCE_PATTERNS = ("*.log", "trace_*.json")
#: barrier markers are only swept once no run can still be polling them
#: (fs_barrier's wait times out after 24 h)
BARRIER_PATTERN = ".barrier_*"
BARRIER_MIN_AGE_S = 25 * 3600.0


def collect(
    root: str, include_provenance: bool = False
) -> list[str]:
    import time

    patterns = TRANSIENT_PATTERNS + (
        PROVENANCE_PATTERNS if include_provenance else ()
    )
    now = time.time()
    hits: list[str] = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            path = os.path.join(dirpath, name)
            if any(fnmatch.fnmatch(name, pat) for pat in patterns):
                hits.append(path)
            elif fnmatch.fnmatch(name, BARRIER_PATTERN):
                # an active multi-host run may be waiting on this marker
                try:
                    if now - os.path.getmtime(path) > BARRIER_MIN_AGE_S:
                        hits.append(path)
                except OSError:
                    pass
    return sorted(hits)


def run(
    root: str, include_provenance: bool = False, dry_run: bool = False
) -> list[str]:
    log = get_logger()
    removed = []
    for path in collect(root, include_provenance):
        if dry_run:
            log.info("[dry-run] would remove %s", path)
        else:
            log.debug("removing %s", path)
            os.unlink(path)
        removed.append(path)
    log.info(
        "%s %d transient file(s) under %s",
        "would remove" if dry_run else "removed", len(removed), root,
    )
    return removed


def build_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    parser = parser or argparse.ArgumentParser(
        description="purge transient chain byproducts from a database folder"
    )
    parser.add_argument("root", help="database folder to clean")
    parser.add_argument(
        "--provenance", action="store_true",
        help="also remove provenance .log files and trace reports",
    )
    parser.add_argument("-n", "--dry-run", action="store_true")
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if not os.path.isdir(args.root):
        get_logger().error("%s is not a directory", args.root)
        return 1
    run(args.root, include_provenance=args.provenance, dry_run=args.dry_run)
    return 0
