"""SRC complexity classification: CRF-23 proxy encode, or codec priors.

Parity target: reference util/complexity_classification.py:18-251. Every SRC
is proxy-encoded with x264 CRF 23 (yuv420p, no audio), its normalized
bitrate and log-complexity computed (ops/siti.norm_bitrate_complexity), and
SRCs are binned into classes 0-3 at the {.25, .5, .75} complexity quantiles
of their framerate band (≤30 fps vs >30 fps). The resulting
`complexity_classification.csv` is what flips `TestConfig.complex_bitrates`
(config/test_config.py) and drives low/high bitrate-pair selection per
segment.

`--priors` (docs/PRIORS.md) removes the proxy re-encode from the hot path
entirely: the classifier reads QP/size statistics of the *existing* encoded
stream (priors.ensure_priors — MV/QP/frame-type side data the decoder
already computed) and maps the observed stream rate to a QP-23-equivalent
rate with the H.264 rate model (bitrate halves per +6 QP), so a stream that
is small because it was crushed at QP 40 is not mistaken for simple
content. The quantile-binning layer is UNCHANGED — both modes feed the same
`classify_dataframe`, and on a corpus encoded at one quality level they
assign the same classes (pinned by tests/test_priors.py).

Deliberate fix over the reference: the CSV `file` column holds the *SRC*
basename, not the `<src>_crf23.avi` proxy name the reference tool writes —
the config layer looks complexity up by SRC filename
(reference test_config.py:436), and the CSVs shipped with the reference are
keyed that way too; the raw reference tool output would never match. The
proxy artifact name is kept in a separate `proxy_file` column.

Second fix (proxy mode): proxies are encoded inside a scratch directory and
removed after analysis unless `--keep-proxy` — the reference leaves a
half-written `<src>_crf23.avi` next to its output on every failed run.
"""

from __future__ import annotations

import argparse
import math
import os
import shutil
import tempfile
from typing import Optional, Sequence

import pandas as pd

from ..io import medialib
from ..io.probe import get_segment_info
from ..io.video import VideoReader, VideoWriter
from ..ops.siti import REFERENCE_BITRATE, norm_bitrate_complexity
from ..store import runtime as store_runtime
from ..utils.log import get_logger
from ..utils.runner import ParallelRunner

#: quantile keys used for the class thresholds
QUANTILES = (0.25, 0.5, 0.75)

#: the proxy encoder's quality point; --priors normalizes observed stream
#: rates to this QP so both modes measure the same "rate at CRF/QP 23"
PRIORS_QP_REF = 23.0

#: complexity units per QP step: H.264 rate halves per +6 QP
#: (20*log10(2)/6 dB per step, over the reference's 2.75 divisor)
QP_COMPLEXITY_PER_STEP = 20.0 * math.log10(2.0) / 6.0 / REFERENCE_BITRATE


def proxy_encode(input_file: str, output_file: str) -> str:
    """Stream-encode `input_file` with x264 CRF 23, yuv420p, audio dropped
    (reference encode_file, util/complexity_classification.py:134-141:
    `ffmpeg -i IN -pix_fmt yuv420p -an -c:v libx264 -crf 23 OUT`)."""
    with VideoReader(input_file) as reader:
        w, h = reader.width, reader.height
        with VideoWriter(
            output_file,
            codec="libx264",
            width=w,
            height=h,
            pix_fmt="yuv420p",
            fps=reader.fps_fraction,
            opts="crf=23",
        ) as writer:
            native_420 = reader.pix_fmt == "yuv420p"
            for frame in reader:
                if native_420:
                    writer.write(*frame.planes)
                else:
                    y, u, v = medialib.sws_scale_yuv(
                        frame.planes, w, h, reader.pix_fmt, w, h, "yuv420p"
                    )
                    writer.write(y, u, v)
    return output_file


def get_difficulty(proxy_file: str, src_file: Optional[str] = None) -> dict:
    """Complexity record for one proxy encode (reference get_difficulty,
    util/complexity_classification.py:50-69)."""
    info = get_segment_info(proxy_file)
    size = float(info["file_size"])
    duration = float(info["video_duration"])
    framerate = float(info["video_frame_rate"])
    width = int(info["video_width"])
    height = int(info["video_height"])
    norm_bitrate, complexity = norm_bitrate_complexity(
        size, framerate, duration, width, height
    )
    return {
        "file": os.path.basename(src_file or proxy_file),
        "proxy_file": os.path.basename(proxy_file),
        "norm_bitrate": norm_bitrate,
        "complexity": complexity,
        "framerate": framerate,
        "width": width,
        "height": height,
        "size": int(size),
        "duration": duration,
    }


def get_priors_difficulty(src_file: str, force: bool = False) -> dict:
    """Complexity record for one SRC from its OWN bitstream's coding
    metadata — no re-encode (docs/PRIORS.md "Complexity without the
    proxy"). Stream bytes stand in for the proxy's file size; when the
    codec exports QP, the rate is normalized to PRIORS_QP_REF so streams
    encoded at different quality points stay comparable. MV statistics
    ride along as CSV columns for downstream feature users."""
    from .. import priors
    from ..priors import features as pf

    data, _ = priors.ensure_priors(src_file, force=force)
    info = get_segment_info(src_file)
    duration = float(info["video_duration"])
    framerate = float(info["video_frame_rate"])
    width = int(info["video_width"])
    height = int(info["video_height"])
    # a frame whose packet could not be matched (timestamp-less or
    # pathological streams) carries pkt_size 0 — a PARTIAL sum would
    # silently undercount the stream and misclassify the clip as simple.
    # Fallback order: the independent VIDEO-stream packet scan (exact,
    # audio/mux overhead excluded — --priors accepts audio-bearing
    # containers), then the container size as the last resort.
    if data.n_frames and (data.pkt_size > 0).all():
        size = float(data.pkt_size.sum())
    else:
        try:
            from ..io import sharedscan

            size = float(sharedscan.video(src_file)["size"].sum())
        except medialib.MediaError:
            size = 0.0
        if size <= 0:
            size = float(info["file_size"])
    norm_bitrate, complexity = norm_bitrate_complexity(
        size, framerate, duration, width, height
    )
    qp_sel = data.qp_blocks > 0
    qp_mean = None
    if qp_sel.any():
        weights = data.qp_blocks[qp_sel].astype(float)
        qp_mean = float((data.qp_mean[qp_sel] * weights).sum() / weights.sum())
        # observed rate at QP q ≙ rate at QP_REF scaled by 2^((q-REF)/6):
        # in complexity units that is a linear shift per QP step
        complexity += (qp_mean - PRIORS_QP_REF) * QP_COMPLEXITY_PER_STEP
    stats = pf.frame_mv_stats(data)
    mv_sel = stats["mv_count"] > 0
    return {
        "file": os.path.basename(src_file),
        "norm_bitrate": norm_bitrate,
        "complexity": complexity,
        "framerate": framerate,
        "width": width,
        "height": height,
        "size": int(size),
        "duration": duration,
        "qp_mean": round(qp_mean, 3) if qp_mean is not None else None,
        "mv_mean_mag": round(float(stats["mean_mag"][mv_sel].mean()), 4)
        if mv_sel.any() else None,
        "mv_p95_mag": round(float(stats["p95_mag"][mv_sel].mean()), 4)
        if mv_sel.any() else None,
    }


def classify_complexity(complexity: float, framerate: float, quantiles: dict) -> int:
    """Class 0-3 from the framerate band's quantiles (reference
    classify_complexity, util/complexity_classification.py:72-88)."""
    band = quantiles["low"] if framerate <= 30 else quantiles["high"]
    if complexity > band[0.50]:
        return 3 if complexity > band[0.75] else 2
    return 1 if complexity > band[0.25] else 0


def classify_dataframe(data: pd.DataFrame) -> pd.DataFrame:
    """Append `complexity_class` using per-framerate-band quantiles
    (reference main, :230-241)."""
    quants = {
        "low": data[data["framerate"] <= 30]["complexity"].quantile(list(QUANTILES)),
        "high": data[data["framerate"] > 30]["complexity"].quantile(list(QUANTILES)),
    }
    data = data.copy()
    data["complexity_class"] = data.apply(
        lambda r: classify_complexity(r["complexity"], r["framerate"], quants), axis=1
    )
    return data


#: CSV column orders per mode (shared tail keeps the config-layer lookup
#: columns identical across modes)
_PROXY_COLUMNS = [
    "file", "proxy_file", "norm_bitrate", "complexity", "framerate",
    "width", "height", "size", "duration",
]
_PRIORS_COLUMNS = [
    "file", "norm_bitrate", "complexity", "framerate", "width", "height",
    "size", "duration", "qp_mean", "mv_mean_mag", "mv_p95_mag",
]


def _select_inputs(inputs: Sequence[str], priors: bool) -> list[str]:
    log = get_logger()
    input_files = []
    for f in inputs:
        if priors or f.endswith(".avi"):
            input_files.append(f)
        else:
            log.warning("skipping %s: not an .avi file", f)
    basenames = [os.path.basename(f) for f in input_files]
    dupes = {b for b in basenames if basenames.count(b) > 1}
    if dupes:
        # same basename ⇒ same proxy path AND ambiguous CSV `file` keys —
        # the config layer looks complexity up by SRC basename, so this
        # cannot be disambiguated; refuse instead of silently misclassifying
        raise ValueError(
            f"duplicate SRC basenames across inputs: {sorted(dupes)}"
        )
    return input_files


def _proxy_records(
    input_files: Sequence[str],
    tmp_dir: str,
    parallelism: int,
    force: bool,
    dry_run: bool,
    keep_proxy: bool,
) -> Optional[list[dict]]:
    """Proxy-encode records. Encodes happen inside a scratch directory so
    a failed run never strands a half-written proxy; finished proxies are
    promoted into `tmp_dir` only with `keep_proxy` (where later runs may
    reuse them without `--force`)."""
    log = get_logger()
    runner = ParallelRunner(max_parallel=parallelism, name="complexity-encode")
    scratch = tempfile.mkdtemp(dir=tmp_dir, prefix=".proxy-scratch-")
    try:
        pairs: list[tuple[str, str, str]] = []  # (src, kept path, work path)
        for input_file in input_files:
            base = os.path.splitext(os.path.basename(input_file))[0]
            kept = os.path.join(tmp_dir, base + "_crf23.avi")
            work = os.path.join(scratch, base + "_crf23.avi")
            if keep_proxy and os.path.isfile(kept) and not force:
                log.warning("proxy %s exists, use --force to re-encode", kept)
                pairs.append((input_file, kept, kept))
            else:
                pairs.append((input_file, kept, work))
                runner.add(proxy_encode, input_file, work, label=work)

        if dry_run:
            for input_file, _kept, work in pairs:
                if work.startswith(scratch):
                    log.info("would encode %s -> %s", input_file,
                             os.path.basename(work))
            return None

        if len(runner):
            log.info("encoding %d proxies, this may take a while …", len(runner))
            runner.run()

        records = []
        for src, kept, work in pairs:
            records.append(get_difficulty(work, src))
            if keep_proxy and work != kept:
                os.replace(work, kept)
        return records
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def run(
    inputs: Sequence[str],
    tmp_dir: str,
    output_file: str = "complexity_classification.csv",
    parallelism: int = 1,
    force: bool = False,
    dry_run: bool = False,
    priors: bool = False,
    keep_proxy: bool = False,
) -> Optional[pd.DataFrame]:
    """Classify all inputs; writes `<tmp_dir>/<output_file>` and returns
    the DataFrame (None on dry run). `priors=True` classifies from the
    existing streams' coding metadata — zero encodes on the hot path."""
    log = get_logger()
    os.makedirs(tmp_dir, exist_ok=True)
    if not output_file.endswith(".csv"):
        raise ValueError("output file must be .csv")

    input_files = _select_inputs(inputs, priors)

    if priors:
        if dry_run:
            for f in input_files:
                log.info("would extract priors from %s", f)
            return None
        # same -p semantics as proxy mode: extractions are independent
        # single-threaded bitstream passes, so they parallelize cleanly
        runner = ParallelRunner(max_parallel=parallelism,
                                name="complexity-priors")
        for f in input_files:
            runner.add(get_priors_difficulty, f, force=force, label=f)
        runner.run()
        records = [runner.results[f] for f in input_files]
        columns = _PRIORS_COLUMNS
    else:
        records = _proxy_records(
            input_files, tmp_dir, parallelism, force, dry_run, keep_proxy
        )
        if records is None:
            return None
        columns = _PROXY_COLUMNS
    if not records:
        raise ValueError("no inputs analysed")

    data = pd.DataFrame(records)[columns].sort_values("file")
    data = classify_dataframe(data)

    csv_path = os.path.join(tmp_dir, output_file)
    data.to_csv(csv_path, index=False)
    log.info("wrote %s (%d rows)", csv_path, len(data))
    return data


def build_parser(parser: Optional[argparse.ArgumentParser] = None) -> argparse.ArgumentParser:
    p = parser or argparse.ArgumentParser(
        "complexity",
        description="Classify SRC encoding complexity (CRF-23 proxy, or "
        "codec priors with --priors)",
    )
    p.add_argument("-i", "--input", required=True, nargs="+",
                   help="input SRC files (.avi; --priors accepts any container)")
    p.add_argument("-t", "--tmp-dir", default="complexityAnalysis",
                   help="directory for the output CSV (and kept proxies)")
    p.add_argument("-p", "--parallelism", type=int, default=1,
                   help="number of parallel proxy encodes")
    p.add_argument("-o", "--output-file", default="complexity_classification.csv",
                   help="CSV output filename")
    p.add_argument("-f", "--force", action="store_true",
                   help="re-encode existing proxies / re-extract priors")
    p.add_argument("-n", "--dry-run", action="store_true",
                   help="show what would be encoded/extracted")
    p.add_argument("--priors", action="store_true",
                   help="classify from the existing streams' MV/QP/size "
                   "coding metadata — no proxy re-encode (docs/PRIORS.md)")
    p.add_argument("--keep-proxy", action="store_true",
                   help="proxy mode: keep <src>_crf23.avi under --tmp-dir "
                   "for reuse (default: proxies live in a scratch dir and "
                   "are removed after analysis)")
    p.add_argument("--store", default=None, metavar="DIR",
                   help="artifact store for priors sidecars (default: "
                   "PC_STORE_DIR when set)")
    p.add_argument("--no-store", action="store_true",
                   help="disable the artifact store even if PC_STORE_DIR is set")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    store_runtime.configure_from_args(args)
    run(
        args.input,
        tmp_dir=args.tmp_dir,
        output_file=args.output_file,
        parallelism=args.parallelism,
        force=args.force,
        dry_run=args.dry_run,
        priors=args.priors,
        keep_proxy=args.keep_proxy,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
