"""SRC complexity classification via the CRF-23 proxy encode.

Parity target: reference util/complexity_classification.py:18-251. Every SRC
is proxy-encoded with x264 CRF 23 (yuv420p, no audio), its normalized
bitrate and log-complexity computed (ops/siti.norm_bitrate_complexity), and
SRCs are binned into classes 0-3 at the {.25, .5, .75} complexity quantiles
of their framerate band (≤30 fps vs >30 fps). The resulting
`complexity_classification.csv` is what flips `TestConfig.complex_bitrates`
(config/test_config.py) and drives low/high bitrate-pair selection per
segment.

Deliberate fix over the reference: the CSV `file` column holds the *SRC*
basename, not the `<src>_crf23.avi` proxy name the reference tool writes —
the config layer looks complexity up by SRC filename
(reference test_config.py:436), and the CSVs shipped with the reference are
keyed that way too; the raw reference tool output would never match. The
proxy artifact name is kept in a separate `proxy_file` column.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

import pandas as pd

from ..io import medialib
from ..io.probe import get_segment_info
from ..io.video import VideoReader, VideoWriter
from ..ops.siti import norm_bitrate_complexity
from ..utils.log import get_logger
from ..utils.runner import ParallelRunner

#: quantile keys used for the class thresholds
QUANTILES = (0.25, 0.5, 0.75)


def proxy_encode(input_file: str, output_file: str) -> str:
    """Stream-encode `input_file` with x264 CRF 23, yuv420p, audio dropped
    (reference encode_file, util/complexity_classification.py:134-141:
    `ffmpeg -i IN -pix_fmt yuv420p -an -c:v libx264 -crf 23 OUT`)."""
    with VideoReader(input_file) as reader:
        w, h = reader.width, reader.height
        with VideoWriter(
            output_file,
            codec="libx264",
            width=w,
            height=h,
            pix_fmt="yuv420p",
            fps=reader.fps_fraction,
            opts="crf=23",
        ) as writer:
            native_420 = reader.pix_fmt == "yuv420p"
            for frame in reader:
                if native_420:
                    writer.write(*frame.planes)
                else:
                    y, u, v = medialib.sws_scale_yuv(
                        frame.planes, w, h, reader.pix_fmt, w, h, "yuv420p"
                    )
                    writer.write(y, u, v)
    return output_file


def get_difficulty(proxy_file: str, src_file: Optional[str] = None) -> dict:
    """Complexity record for one proxy encode (reference get_difficulty,
    util/complexity_classification.py:50-69)."""
    info = get_segment_info(proxy_file)
    size = float(info["file_size"])
    duration = float(info["video_duration"])
    framerate = float(info["video_frame_rate"])
    width = int(info["video_width"])
    height = int(info["video_height"])
    norm_bitrate, complexity = norm_bitrate_complexity(
        size, framerate, duration, width, height
    )
    return {
        "file": os.path.basename(src_file or proxy_file),
        "proxy_file": os.path.basename(proxy_file),
        "norm_bitrate": norm_bitrate,
        "complexity": complexity,
        "framerate": framerate,
        "width": width,
        "height": height,
        "size": int(size),
        "duration": duration,
    }


def classify_complexity(complexity: float, framerate: float, quantiles: dict) -> int:
    """Class 0-3 from the framerate band's quantiles (reference
    classify_complexity, util/complexity_classification.py:72-88)."""
    band = quantiles["low"] if framerate <= 30 else quantiles["high"]
    if complexity > band[0.50]:
        return 3 if complexity > band[0.75] else 2
    return 1 if complexity > band[0.25] else 0


def classify_dataframe(data: pd.DataFrame) -> pd.DataFrame:
    """Append `complexity_class` using per-framerate-band quantiles
    (reference main, :230-241)."""
    quants = {
        "low": data[data["framerate"] <= 30]["complexity"].quantile(list(QUANTILES)),
        "high": data[data["framerate"] > 30]["complexity"].quantile(list(QUANTILES)),
    }
    data = data.copy()
    data["complexity_class"] = data.apply(
        lambda r: classify_complexity(r["complexity"], r["framerate"], quants), axis=1
    )
    return data


def run(
    inputs: Sequence[str],
    tmp_dir: str,
    output_file: str = "complexity_classification.csv",
    parallelism: int = 1,
    force: bool = False,
    dry_run: bool = False,
) -> Optional[pd.DataFrame]:
    """Proxy-encode + classify all inputs; writes `<tmp_dir>/<output_file>`
    and returns the DataFrame (None on dry run)."""
    log = get_logger()
    os.makedirs(tmp_dir, exist_ok=True)
    if not output_file.endswith(".csv"):
        raise ValueError("output file must be .csv")

    input_files = []
    for f in inputs:
        if f.endswith(".avi"):
            input_files.append(f)
        else:
            log.warning("skipping %s: not an .avi file", f)

    basenames = [os.path.basename(f) for f in input_files]
    dupes = {b for b in basenames if basenames.count(b) > 1}
    if dupes:
        # same basename ⇒ same proxy path AND ambiguous CSV `file` keys —
        # the config layer looks complexity up by SRC basename, so this
        # cannot be disambiguated; refuse instead of silently misclassifying
        raise ValueError(
            f"duplicate SRC basenames across inputs: {sorted(dupes)}"
        )

    runner = ParallelRunner(max_parallel=parallelism, name="complexity-encode")
    pairs: list[tuple[str, str]] = []
    for input_file in input_files:
        base = os.path.splitext(os.path.basename(input_file))[0]
        proxy = os.path.join(tmp_dir, base + "_crf23.avi")
        pairs.append((input_file, proxy))
        if os.path.isfile(proxy) and not force:
            log.warning("proxy %s exists, use --force to re-encode", proxy)
        else:
            runner.add(proxy_encode, input_file, proxy, label=proxy)

    if dry_run:
        for input_file, proxy in pairs:
            log.info("would encode %s -> %s", input_file, proxy)
        return None

    if len(runner):
        log.info("encoding %d proxies, this may take a while …", len(runner))
        runner.run()

    records = [get_difficulty(proxy, src) for src, proxy in pairs]
    if not records:
        raise ValueError("no inputs analysed")

    data = pd.DataFrame(records)[
        [
            "file",
            "proxy_file",
            "norm_bitrate",
            "complexity",
            "framerate",
            "width",
            "height",
            "size",
            "duration",
        ]
    ].sort_values("file")
    data = classify_dataframe(data)

    csv_path = os.path.join(tmp_dir, output_file)
    data.to_csv(csv_path, index=False)
    log.info("wrote %s (%d rows)", csv_path, len(data))
    return data


def build_parser(parser: Optional[argparse.ArgumentParser] = None) -> argparse.ArgumentParser:
    p = parser or argparse.ArgumentParser(
        "complexity", description="Classify SRC encoding complexity (CRF-23 proxy)"
    )
    p.add_argument("-i", "--input", required=True, nargs="+", help="input SRC files (.avi)")
    p.add_argument("-t", "--tmp-dir", default="complexityAnalysis",
                   help="directory for proxy encodes + the output CSV")
    p.add_argument("-p", "--parallelism", type=int, default=1,
                   help="number of parallel proxy encodes")
    p.add_argument("-o", "--output-file", default="complexity_classification.csv",
                   help="CSV output filename")
    p.add_argument("-f", "--force", action="store_true",
                   help="re-encode existing proxies")
    p.add_argument("-n", "--dry-run", action="store_true",
                   help="show what would be encoded")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    run(
        args.input,
        tmp_dir=args.tmp_dir,
        output_file=args.output_file,
        parallelism=args.parallelism,
        force=args.force,
        dry_run=args.dry_run,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
