"""`tools fleet-doctor` — cross-plane incident correlation.

The flight recorders each journal their own plane: serve spans
(queue/spans), store heat (store/heat), mesh occupancy (meshobs) and
now the alert lifecycle (alerts/). When an alert fires, the question
is never "did it fire" — it is *what else was happening*. fleet-doctor
joins all four journal planes on one time axis and renders the
incident window around any alert:

    python -m processing_chain_tpu tools fleet-doctor al-r1-0001 --root DIR
    python -m processing_chain_tpu tools fleet-doctor 'slo_burn_queue_wait{...}' \\
        --root DIR --window-s 30 --chrome incident.json

A bare `--root DIR` (no alert ref) lists the alerts on record. The
`--chrome` export writes a Chrome-trace (chrome://tracing /
ui.perfetto.dev) file: one track per plane, alert episodes as
duration events spanning fired→resolved.

`--soak` runs the SLO-breach proof harness instead: an in-process
replica fleet is driven through a healthy control phase (zero alerts
must fire), an injected breach (an interactive flood against one slow
worker per replica + an undersized hot tier; the declared burn-rate
and regret alerts must fire, and the scale signal must recommend up),
a replica loss (the stale-replica rule must fire), and a recovery
(every alert must resolve, the scale signal must return to steady).
The one-line JSON report is the committed `ALERTS_r20.json` evidence;
exit is nonzero on any violated invariant.

    python -m processing_chain_tpu tools fleet-doctor --soak
        [--root DIR] [--out FILE]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import time
from typing import Optional, Sequence

from ..telemetry import alerts as alerts_mod
from ..utils.fsio import atomic_write_json, atomic_write_text
from ..utils.log import get_logger

#: every burn window/threshold/hold in the soak is the production
#: declaration times this — hours of SRE windows compressed into
#: seconds without forking the rules (telemetry/alerts.py)
SOAK_WINDOW_SCALE = 0.001


# ------------------------------------------------------------ gathering


def gather_planes(root: str) -> list[dict]:
    """Every journal record of every plane under one serve root, each
    tagged with its `plane`, merged onto one (ts, replica, seq) axis."""
    from ..parallel import meshobs
    from ..serve import spans as serve_spans
    from ..store import heat as store_heat

    records: list[dict] = []
    for plane, recs in (
        ("spans", serve_spans.read_journals(
            os.path.join(root, "queue", "spans"))),
        ("heat", store_heat.read_journals(
            store_heat.heat_dir(os.path.join(root, "store")))),
        ("mesh", meshobs.read_journals(meshobs.mesh_dir(root))),
        ("alerts", alerts_mod.read_journals(alerts_mod.alerts_dir(root))),
    ):
        for rec in recs:
            records.append({"plane": plane, **rec})
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("replica", ""),
                                r.get("seq", 0)))
    return records


def _summarize(rec: dict) -> str:
    """One render line per record, per plane dialect."""
    plane = rec.get("plane")
    if plane == "spans":
        extra = ""
        if rec.get("queue_wait_s") is not None:
            extra = f" wait={rec['queue_wait_s']:.3f}s"
        elif rec.get("exec_s") is not None:
            extra = f" exec={rec['exec_s']:.3f}s"
        return (f"{rec.get('phase', '?')} job={rec.get('job', '?')} "
                f"state={rec.get('state', '?')}{extra}")
    if plane == "heat":
        kind = rec.get("kind", "?")
        plan = (rec.get("plan") or "?")[:12]
        if kind == "read":
            return f"read plan={plan} mode={rec.get('mode')} " \
                   f"bytes={rec.get('bytes', 0)}"
        if kind == "evict":
            return f"EVICT plan={plan} bytes={rec.get('bytes', 0)}"
        if kind == "regret":
            return f"REGRET plan={plan} via={rec.get('via')} " \
                   f"evicted_ago_s={rec.get('evicted_ago_s')}"
        return f"{kind} plan={plan}"
    if plane == "mesh":
        return (f"{rec.get('kind', '?')} bucket={rec.get('bucket', '?')} "
                f"valid={rec.get('valid', '?')}/"
                f"{rec.get('dispatched', '?')}")
    if plane == "alerts":
        kind = rec.get("kind", "?")
        if kind == "scale":
            return (f"SCALE {rec.get('current')}->{rec.get('desired')} "
                    f"[{','.join(rec.get('reasons') or [])}]")
        label = {"fired": "FIRED", "resolved": "RESOLVED",
                 "renotify": "renotify"}.get(kind, kind)
        tail = rec.get("reason") or rec.get("alert") or ""
        return f"{label} {rec.get('rule', '?')} id={rec.get('id')}  {tail}"
    return json.dumps(rec, sort_keys=True)[:120]


def render_incident(root: str, ref: str,
                    window_s: float = 30.0) -> Optional[dict]:
    """The incident document around one alert: the folded alert state,
    every journal record (all planes) inside [fired - window_s,
    resolved/last + window_s], and the rendered text timeline."""
    anchor = alerts_mod.find_alert(root, ref)
    if anchor is None:
        return None
    t_fire = anchor.get("fired_ts") or 0.0
    t_end = anchor.get("resolved_ts") or anchor.get("last_ts") or t_fire
    lo, hi = t_fire - window_s, t_end + window_s
    records = [r for r in gather_planes(root)
               if lo <= r.get("ts", 0.0) <= hi]
    lines = [
        f"incident {anchor.get('id')}  {anchor.get('alert')}",
        f"  fired    {_stamp(t_fire)}   "
        f"severity={anchor.get('severity')}",
        (f"  resolved {_stamp(anchor['resolved_ts'])}   "
         f"after {anchor.get('duration_s')}s"
         if anchor.get("resolved_ts") else "  still firing"),
        f"  window   ±{window_s:g}s, {len(records)} records across "
        f"{len({r['plane'] for r in records})} planes",
        "",
    ]
    for rec in records:
        dt = rec.get("ts", 0.0) - t_fire
        mark = ">>" if rec.get("plane") == "alerts" else "  "
        lines.append(
            f"{mark} {dt:+9.3f}s [{rec['plane']:<6}] "
            f"{rec.get('replica', '?'):<12} {_summarize(rec)}"
        )
    return {"alert": {k: v for k, v in anchor.items() if k != "records"},
            "window_s": window_s, "records": records,
            "planes": sorted({r["plane"] for r in records}),
            "text": "\n".join(lines)}


def _stamp(ts: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(ts)) \
        + f".{int((ts % 1) * 1000):03d}"


def chrome_trace(incident: dict) -> dict:
    """Chrome-trace export: one track (tid) per plane, instant events
    for journal records, a duration event for the alert episode."""
    events: list[dict] = []
    tids = {"alerts": 0, "spans": 1, "heat": 2, "mesh": 3}
    for rec in incident["records"]:
        events.append({
            "name": _summarize(rec)[:80],
            "cat": rec["plane"],
            "ph": "i", "s": "t",
            "ts": rec.get("ts", 0.0) * 1e6,
            "pid": 1, "tid": tids.get(rec["plane"], 9),
            "args": {k: v for k, v in rec.items()
                     if k not in ("plane",) and not isinstance(v, (dict,
                                                                   list))},
        })
    alert = incident["alert"]
    t0 = alert.get("fired_ts") or 0.0
    t1 = alert.get("resolved_ts") or alert.get("last_ts") or t0
    events.append({
        "name": alert.get("alert", "alert"),
        "cat": "alerts", "ph": "X",
        "ts": t0 * 1e6, "dur": max(1.0, (t1 - t0) * 1e6),
        "pid": 1, "tid": tids["alerts"],
        "args": {"id": alert.get("id"), "rule": alert.get("rule"),
                 "severity": alert.get("severity")},
    })
    for plane, tid in tids.items():
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": plane}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ------------------------------------------------------------- the soak


def _submit(service, i: int, *, tenant: str = "soak",
            priority: str = "interactive", work_ms: int = 5,
            size_bytes: int = 512, base: int = 10_000) -> str:
    """One single-unit request; a distinct (base + i) means a distinct
    plan, a repeated one re-requests the same plan (the regret path)."""
    doc = service.submit({
        "tenant": tenant, "priority": priority, "database": "P2STR01",
        "srcs": [f"SRC{base + i:05d}"], "hrcs": ["HRC100"],
        "params": {"geometry": [64, 36], "work_ms": work_ms,
                   "size_bytes": size_bytes},
    })
    return doc["request"]


def _wait_requests(service, req_ids: list, timeout: float) -> list:
    return [r for r in req_ids
            if service.wait_request(r, timeout=timeout) != "done"]


def _fired_rules(root: str) -> dict:
    """rule -> fired-record count, from the durable journals."""
    out: dict = {}
    for rec in alerts_mod.read_journals(alerts_mod.alerts_dir(root)):
        if rec.get("kind") == "fired":
            out[rec.get("rule")] = out.get(rec.get("rule"), 0) + 1
    return out


def _wait_for(predicate, timeout_s: float, poll_s: float = 0.2) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return bool(predicate())


def run_soak(args) -> int:
    """The breach harness (module doc). Control and breach run under
    separate roots so "zero alerts in the healthy fleet" is provable
    from a journal that the breach never touches."""
    from ..serve.service import ChainServeService

    log = get_logger()
    base = args.root or tempfile.mkdtemp(prefix="chain-alert-soak-")
    os.makedirs(base, exist_ok=True)
    report: dict = {"soak": "alerts", "window_scale": SOAK_WINDOW_SCALE,
                    "root": base, "phases": {}}
    failures: list[str] = []

    # ---- phase 1: healthy control — the fleet at rest must be silent
    control_root = os.path.join(base, "control")
    svc = ChainServeService(
        control_root, port=0, workers=2, wave_width=2, poll_s=0.1,
        control_interval_s=0.15, alert_window_scale=SOAK_WINDOW_SCALE,
        replica="ctl-a",
    ).start()
    try:
        reqs = [_submit(svc, i, base=10_000) for i in range(6)]
        stuck = _wait_requests(svc, reqs, timeout=30.0)
        if stuck:
            failures.append(f"control: requests never completed: {stuck}")
        time.sleep(1.0)  # several control ticks over the settled fleet
        svc._control_tick(force=True)
    finally:
        svc.stop()
    control_fired = _fired_rules(control_root)
    report["phases"]["control"] = {
        "requests": 6, "alerts_fired": control_fired,
        "scale": alerts_mod.latest_scale(control_root),
    }
    if control_fired:
        failures.append(
            f"control: alerts fired in a healthy fleet: {control_fired}")

    # ---- phase 2: breach — interactive flood on slow workers + an
    # undersized hot tier. Replica A grades (fast control ticks);
    # replica B only serves, so the dedup contract stays checkable
    # against a single grader.
    root = os.path.join(base, "fleet")
    svc_a = ChainServeService(
        root, port=0, workers=1, wave_width=1, poll_s=0.1,
        control_interval_s=0.15, alert_window_scale=SOAK_WINDOW_SCALE,
        store_budget_bytes=90_000, replica="soak-a",
        info_path=os.path.join(root, "serve-info-a.json"),
    ).start()
    svc_b = ChainServeService(
        root, port=0, workers=1, wave_width=1, poll_s=0.1,
        control_interval_s=1e9, alert_window_scale=SOAK_WINDOW_SCALE,
        store_budget_bytes=90_000, replica="soak-b",
        info_path=os.path.join(root, "serve-info-b.json"),
    ).start()
    expected = {"slo_burn_queue_wait", "store_eviction_regret",
                "fleet_replica_stale"}
    tolerated = expected | {"slo_burn_e2e", "slo_burn_execution"}
    breach_reqs: list = []
    try:
        # the flood: 36 distinct ~250 ms interactive units against two
        # single-worker replicas — later claims wait far past the
        # 2.5 s interactive queue-wait band
        for i in range(36):
            breach_reqs.append(_submit(
                svc_a, i, base=20_000, work_ms=250, size_bytes=30_000))
        burn_seen = _wait_for(
            lambda: "slo_burn_queue_wait" in _fired_rules(root),
            timeout_s=30.0)
        if not burn_seen:
            failures.append(
                "breach: slo_burn_queue_wait never fired under a "
                "sustained interactive queue-wait breach")
        stuck = _wait_requests(svc_a, breach_reqs, timeout=60.0)
        if stuck:
            failures.append(f"breach: flood never drained: {stuck}")
        # hot-tier pressure: the 30 kB artifacts blew the 90 kB budget
        # long ago; force the GC pass, then re-request early plans —
        # rebuilds of recently-evicted bytes are REGRET
        svc_a.pressure.maybe_collect(force=True)
        # params must MATCH the flood's exactly: a different work_ms is
        # a different plan hash, not a rebuild of the evicted artifact
        regret_reqs = [_submit(svc_a, i, base=20_000, work_ms=250,
                               size_bytes=30_000) for i in range(4)]
        _wait_requests(svc_a, regret_reqs, timeout=30.0)
        regret_seen = _wait_for(
            lambda: "store_eviction_regret" in _fired_rules(root),
            timeout_s=20.0)
        if not regret_seen:
            failures.append(
                "breach: store_eviction_regret never fired after "
                "evicted plans were re-requested")
        # scale evidence from the durable journal: some record during
        # the breach must have recommended up, for a breach reason
        scale_records = [
            r for r in alerts_mod.read_journals(alerts_mod.alerts_dir(root))
            if r.get("kind") == "scale"]
        scale_up = next(
            (r for r in scale_records
             if r.get("desired", 0) > r.get("current", 0)
             and ({"queue_wait_burn", "backlog_pressure"}
                  & set(r.get("reasons") or []))), None)
        report["phases"]["breach"] = {
            "requests": len(breach_reqs),
            "alerts_fired": _fired_rules(root),
            "active": [a.get("alert") for a in
                       alerts_mod.active_alerts(root)],
            "scale": scale_up,
        }
        if scale_up is None:
            failures.append(
                "breach: no scale record recommended up for a breach "
                f"reason; records: {scale_records}")

        # ---- phase 3: replica loss — stop B but leave its serve-info
        # registration; the fleet view grades it stale and the
        # fleet_replica_stale rule pages
        svc_b.stop()
        stale_seen = _wait_for(
            lambda: "fleet_replica_stale" in _fired_rules(root),
            timeout_s=20.0)
        if not stale_seen:
            failures.append(
                "stale: fleet_replica_stale never fired for the "
                "stopped replica")
        report["phases"]["stale"] = {
            "alerts_fired": _fired_rules(root)}

        # ---- phase 4: recovery — deregister the dead replica, feed
        # healthy in-band traffic until every alert resolves and the
        # scale signal returns to steady
        try:
            os.unlink(os.path.join(root, "serve-info-b.json"))
        except OSError:
            pass

        healthy_seq = iter(range(10_000))

        def _all_resolved() -> bool:
            # fresh in-band observations push the burn windows back
            # under threshold; fresh plans never regret
            for _ in range(2):
                rid = _submit(svc_a, next(healthy_seq), base=30_000,
                              work_ms=5)
                svc_a.wait_request(rid, timeout=10.0)
            return not alerts_mod.active_alerts(root)

        recovered = _wait_for(_all_resolved, timeout_s=60.0, poll_s=0.1)
        if not recovered:
            failures.append(
                "recovery: alerts still firing after the fault "
                "cleared: "
                f"{[a.get('alert') for a in alerts_mod.active_alerts(root)]}")
        svc_a._control_tick(force=True)
        scale_after = svc_a.autoscale.latest()
        report["phases"]["recovery"] = {
            "resolved": recovered, "scale": scale_after}
        if scale_after and scale_after.get("replicas_desired", 99) > \
                scale_after.get("replicas_current", 1):
            failures.append(
                f"recovery: scale signal still recommends up: "
                f"{scale_after}")
    finally:
        svc_a.stop()

    # ---- invariants over the durable journals
    fired = _fired_rules(root)
    missing = sorted(expected - set(fired))
    unexpected = sorted(set(fired) - tolerated)
    if missing:
        failures.append(f"expected alerts never fired: {missing}")
    if unexpected:
        failures.append(f"unexpected alerts fired: {unexpected}")
    records = alerts_mod.read_journals(alerts_mod.alerts_dir(root))
    ids = [r.get("id") for r in records if r.get("kind") == "fired"]
    if len(ids) != len(set(ids)):
        failures.append("alert ids are not unique across the journals")
    # dedup/lifecycle: per key the journal must read fired →
    # (renotify)* → resolved, repeating — a second `fired` while an
    # episode is open is exactly the duplicate the dedup keys exist
    # to prevent
    by_key: dict = {}
    for rec in records:
        if rec.get("kind") in ("fired", "renotify", "resolved"):
            by_key.setdefault(rec.get("alert"), []).append(rec)
    for key, episode in sorted(by_key.items()):
        open_ = False
        for rec in episode:
            kind = rec.get("kind")
            if kind == "fired":
                if open_:
                    failures.append(
                        f"dedup violated: {key} re-fired while firing")
                open_ = True
            elif kind in ("renotify", "resolved"):
                if not open_:
                    failures.append(
                        f"lifecycle violated: {key} {kind} without an "
                        "open episode")
                if kind == "resolved":
                    open_ = False
        if open_:
            failures.append(f"alert never resolved: {key}")
    folded = alerts_mod.fold(records)
    report["alerts"] = {
        "fired": fired,
        "lifecycle": {k: {"state": v.get("state"),
                          "episodes": v.get("episodes"),
                          "duration_s": v.get("duration_s")}
                      for k, v in sorted(folded.items())},
    }

    # ---- the cross-plane incident render must join ≥2 planes
    burn_id = next(
        (rec.get("id") for rec in
         alerts_mod.read_journals(alerts_mod.alerts_dir(root))
         if rec.get("kind") == "fired"
         and rec.get("rule") == "slo_burn_queue_wait"), None)
    if burn_id:
        incident = render_incident(root, burn_id, window_s=10.0)
        if incident is None:
            failures.append(f"fleet-doctor cannot find alert {burn_id}")
        else:
            report["incident"] = {
                "id": burn_id, "planes": incident["planes"],
                "records": len(incident["records"]),
            }
            if len(incident["planes"]) < 2:
                failures.append(
                    "incident render joined fewer than 2 planes: "
                    f"{incident['planes']}")
            print(incident["text"])
    else:
        failures.append("no slo_burn_queue_wait fired record to render")

    report["failures"] = failures
    report["ok"] = not failures
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    if failures:
        for f in failures:
            log.error("alert-soak: %s", f)
        return 1
    log.info("alert-soak: OK — %s fired and resolved, scale signal "
             "up under breach, steady after", sorted(fired))
    return 0


# ------------------------------------------------------------------ CLI


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools fleet-doctor",
        description="cross-plane incident correlation + the SLO-breach "
                    "soak (docs/TELEMETRY.md \"Alerting & the scale "
                    "signal\")",
    )
    parser.add_argument("alert", nargs="?", default=None,
                        help="alert id (al-…) or dedup key to render; "
                             "omit to list the alerts on record")
    parser.add_argument("--root", default=None,
                        help="serve root (required unless --soak picks "
                             "a temp dir)")
    parser.add_argument("--window-s", type=float, default=30.0,
                        help="seconds of context either side of the "
                             "alert episode")
    parser.add_argument("--chrome", default=None, metavar="FILE",
                        help="also write a Chrome-trace export of the "
                             "incident window")
    parser.add_argument("--json", action="store_true",
                        help="print the incident document as JSON "
                             "instead of the text timeline")
    parser.add_argument("--soak", action="store_true",
                        help="run the SLO-breach proof harness")
    parser.add_argument("--out", default=None,
                        help="(--soak) also write the JSON report here")
    args = parser.parse_args(list(argv) if argv is not None else None)

    if args.soak:
        return run_soak(args)
    if not args.root:
        parser.error("--root is required (or use --soak)")
    if args.alert is None:
        doc = alerts_mod.alerts_report(args.root)
        for section in ("active", "resolved"):
            for a in doc.get(section, []):
                print(f"{a.get('id', '?'):<16} {section:<9} "
                      f"{a.get('alert')}")
        if not doc.get("active") and not doc.get("resolved"):
            print("(no alerts on record)")
        return 0
    incident = render_incident(args.root, args.alert,
                               window_s=args.window_s)
    if incident is None:
        get_logger().error("fleet-doctor: no alert matching %r under %s",
                           args.alert, args.root)
        return 1
    if args.chrome:
        atomic_write_json(args.chrome, chrome_trace(incident))
    if args.json:
        print(json.dumps({k: v for k, v in incident.items()
                          if k != "text"}, sort_keys=True))
    else:
        print(incident["text"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
