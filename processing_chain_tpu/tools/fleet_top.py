"""fleet-top: a refreshing terminal view of a chain-serve replica fleet.

`chain-top`'s fleet-shaped sibling: where chain-top watches ONE
process, fleet-top renders the merged view of every replica over one
serve root — who is alive (replica id, epoch, pid), the shared queue
and request truth from disk, span-journal traffic, and the SLO layer's
per-(tenant × priority) latency grades against the declared bands
(telemetry/catalog.SLO_BANDS).

    python -m processing_chain_tpu tools fleet-top /srv/chain
    python -m processing_chain_tpu tools fleet-top http://host:8790 --once

A directory source builds the view locally (telemetry/fleet.py —
works with every replica dead); a URL asks a live replica's /fleet
endpoint. `--once` renders one frame for scripts/CI.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from .chain_top import StatusSourceError, _fmt_age


def fetch_fleet(source: str, timeout_s: float = 5.0) -> dict:
    """The fleet document from a /fleet URL or built from a root dir."""
    if source.startswith(("http://", "https://")):
        url = source if source.endswith("/fleet") \
            else source.rstrip("/") + "/fleet"
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, TimeoutError, ValueError) as exc:
            raise StatusSourceError(f"cannot fetch {url}: {exc}") from exc
    from ..telemetry import fleet

    return fleet.fleet_view(source)


def _fmt_cell(cell: dict) -> str:
    p50 = cell.get("p50")
    p99 = cell.get("p99")
    txt = f"n={cell.get('count', 0):<5} "
    txt += f"p50≤{p50 * 1e3:7.1f}ms " if p50 is not None else "p50      -  "
    txt += f"p99≤{p99 * 1e3:7.1f}ms " if p99 is not None else "p99      -  "
    ok = cell.get("ok")
    if ok is None:
        txt += "band -"
    else:
        within = cell.get("within_band")
        txt += f"band {cell.get('band_s')}s " \
               f"{within * 100:5.1f}% {'OK' if ok else 'BREACH'}"
    return txt


def render(view: dict, note: str = "") -> str:
    """One full frame (plain text; the loop clears the screen)."""
    lines: list[str] = []
    head = (f"fleet-top — {view.get('root', '?')}  "
            f"replicas {view.get('alive', 0)}/"
            f"{len(view.get('replicas', []))} alive")
    if note:
        head += f"  [{note}]"
    lines.append(head)
    lines.append("")
    lines.append("replicas:")
    if not view.get("replicas"):
        lines.append("  (none discovered — no serve-info files under "
                     "the root)")
    for rep in view.get("replicas", []):
        mark = "+" if rep.get("alive") else "x"
        ident = (f"{rep.get('replica', '?')} "
                 f"e{rep.get('replica_epoch', '?')} "
                 f"pid {rep.get('pid', '?')}")
        if rep.get("alive"):
            q = rep.get("queue", {})
            qtxt = " ".join(f"{k}={v}" for k, v in sorted(q.items())) \
                or "idle"
            extra = f"up {_fmt_age(rep.get('uptime_s', 0.0))}  {qtxt}"
            if rep.get("rss_bytes"):
                extra += f"  rss {rep['rss_bytes'] / 1e6:.0f} MB"
        else:
            extra = f"DEAD ({rep.get('error', '?')}, " \
                    f"info {rep.get('info_file')})"
            if rep.get("last_seen_s") is not None:
                extra += f"  last seen {_fmt_age(rep['last_seen_s'])} ago"
        lines.append(f" {mark} {ident:<44} {extra}")
    lines.append("")
    queue = view.get("queue", {})
    reqs = view.get("requests", {})
    lines.append(
        "shared root: queue "
        + (" ".join(f"{k}={v}" for k, v in sorted(queue.items()))
           or "(empty)")
        + "  requests "
        + (" ".join(f"{k}={v}" for k, v in sorted(reqs.items()))
           or "(none)")
    )
    span_stats = view.get("spans", {})
    if span_stats.get("total"):
        by_phase = span_stats.get("by_phase", {})
        tail_note = " (recent window)" if span_stats.get("sampled") else ""
        lines.append(
            f"spans: {span_stats['total']}{tail_note} "
            + " ".join(f"{k}={v}" for k, v in sorted(by_phase.items()))
        )
    heat = view.get("heat", {})
    if heat.get("total"):
        tail_note = " (recent window)" if heat.get("sampled") else ""
        lines.append(
            f"reads: {heat.get('reads', 0)}{tail_note} "
            f"full={heat.get('full', 0)} 304={heat.get('not_modified', 0)} "
            f"range={heat.get('range', 0)} "
            f"served={heat.get('bytes_served', 0) / 1e6:.1f}MB "
            f"evictions={heat.get('evictions', 0)} "
            f"regrets={heat.get('regrets', 0)}"
        )
    store_tiers = view.get("store_tiers", {})
    if store_tiers.get("tiers"):
        order = {"hot": 0, "warm": 1, "cold": 2}
        parts = []
        for name, t in sorted(store_tiers["tiers"].items(),
                              key=lambda kv: (order.get(kv[0], 9),
                                              kv[0])):
            parts.append(
                f"{name} hits={t.get('hits', 0)}"
                f"({t.get('hit_ratio', 0.0) * 100:.0f}%) "
                f"{t.get('bytes', 0) / 1e6:.1f}MB"
            )
        moves = sum(t.get("promotions", 0)
                    for t in store_tiers["tiers"].values())
        demotes = sum(t.get("demotions", 0)
                      for t in store_tiers["tiers"].values())
        lines.append(
            "tiers: " + "  ".join(parts)
            + f"  promotions={moves} demotions={demotes}"
        )
    stalls = view.get("stalls") or []
    if stalls:
        parts = []
        for s in stalls[:6]:
            stage = f"/{s['stage']}" if s.get("stage") else ""
            parts.append(
                f"{s.get('replica', '?')}:{s.get('task', '?')}{stage} "
                f"{s.get('incident', 'stalled')} "
                f"{_fmt_age(s.get('beat_age_s', 0.0))}"
            )
        more = f" (+{len(stalls) - 6})" if len(stalls) > 6 else ""
        lines.append("active stalls: " + "  ".join(parts) + more)
    alerts = (view.get("alerts") or {}).get("active") or []
    if alerts:
        parts = []
        for a in alerts[:6]:
            parts.append(
                f"{a.get('rule', '?')}[{a.get('severity', '?')}] "
                f"x{a.get('episodes', 1)}"
            )
        more = f" (+{len(alerts) - 6})" if len(alerts) > 6 else ""
        lines.append(f"ALERTS firing: {len(alerts)}  "
                     + "  ".join(parts) + more)
    scale = view.get("scale")
    if scale:
        reasons = ",".join(scale.get("reasons") or []) or "-"
        lines.append(
            f"scale signal: {scale.get('current', '?')}"
            f"→{scale.get('desired', '?')} replicas  "
            f"confidence {scale.get('confidence', 0.0):.2f}  "
            f"[{reasons}]"
        )
    mesh = view.get("mesh", {})
    if mesh.get("buckets"):
        parts = []
        for name, b in sorted(mesh["buckets"].items()):
            parts.append(
                f"{name} waves={b.get('waves', 0)} "
                f"waste={b.get('waste_fraction', 0.0) * 100:.1f}% "
                f"compiles={b.get('recompiles', 0)}"
            )
        lines.append("mesh: " + "  ".join(parts))
    fleet_cost = view.get("cost", {})
    if fleet_cost.get("tenants") or fleet_cost.get("rejected"):
        lines.append("")
        lines.append("cost (predicted vs observed seconds, "
                     "serve/cost.py):")
        for tenant, entry in sorted(fleet_cost.get("tenants",
                                                   {}).items()):
            lines.append(
                f"  {tenant or '(any)':<20} "
                f"predicted {entry.get('predicted_s', 0.0):9.1f}s  "
                f"observed {entry.get('observed_s', 0.0):9.1f}s"
            )
        err = fleet_cost.get("model_error")
        if err:
            lines.append(
                f"  model error: n={err['n']} "
                f"obs/pred p50≤{err['ratio_p50']} p95≤{err['ratio_p95']}"
            )
        rejected = fleet_cost.get("rejected", {})
        if rejected:
            lines.append(
                "  admission rejected: "
                + " ".join(f"{k}={v}" for k, v in sorted(rejected.items()))
            )
    slo = view.get("slo", {})
    lines.append("")
    lines.append("SLO (merged over live replicas; bands from "
                 "telemetry/catalog.py):")
    if not slo:
        lines.append("  (no phase observations yet)")
    for tenant in sorted(slo):
        for priority in sorted(slo[tenant]):
            lines.append(f"  {tenant}/{priority}:")
            for phase in ("queue_wait_s", "execution_s", "e2e_s"):
                cell = slo[tenant][priority].get(phase)
                if cell is None:
                    continue
                lines.append(f"    {phase:<13} {_fmt_cell(cell)}")
    read_slo = view.get("read_slo", {})
    if read_slo:
        lines.append("")
        lines.append("read SLO (artifact TTFB / full stream, per "
                     "tenant × size class):")
        for tenant in sorted(read_slo):
            for size_class in sorted(read_slo[tenant]):
                lines.append(f"  {tenant or '(any)'}/{size_class}:")
                for phase in ("read_ttfb_s", "read_s"):
                    cell = read_slo[tenant][size_class].get(phase)
                    if cell is None:
                        continue
                    lines.append(f"    {phase:<13} {_fmt_cell(cell)}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools fleet-top",
        description="merged terminal view of a chain-serve replica "
                    "fleet (docs/SERVE.md, docs/TELEMETRY.md)",
    )
    parser.add_argument(
        "source",
        help="serve root directory, or a replica URL (…/fleet appended)",
    )
    parser.add_argument("-i", "--interval", default=2.0, type=float,
                        help="refresh period in seconds")
    parser.add_argument("--once", action="store_true",
                        help="render one frame and exit (scripts/CI)")
    args = parser.parse_args(list(argv) if argv is not None else None)

    if args.once:
        print(render(fetch_fleet(args.source)), end="")
        return 0
    last_frame = None
    try:
        while True:
            note = ""
            try:
                frame = render(fetch_fleet(args.source))
                last_frame = frame
            except StatusSourceError as exc:
                if last_frame is None:
                    raise
                note = f"stale: {exc}"
                frame = last_frame.rstrip("\n") + f"\n[{note}]\n"
            sys.stdout.write("\033[2J\033[H" + frame)
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
