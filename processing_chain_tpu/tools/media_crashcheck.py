"""`tools media-crashcheck` — hostile-input proof for the byte path.

`tools queue-crashcheck` (PR 8) proves the durable-write surface
settles correctly under fault injection at every atomic-write boundary;
this is its twin for the NATIVE MEDIA boundary (docs/ROBUSTNESS.md): a
generated corrupt corpus — truncated mid-GOP, garbage header,
zero-byte, wrong-codec container, mid-stream geometry flip — driven
through the decoder surface, through p01–p04, and through chain-serve,
asserting that every unit terminates with the right disposition and
that nothing leaks:

  * **reader matrix** — each corrupt member through `VideoReader`:
    the expected failure class fires (open rejection vs mid-stream
    MediaError carrying the `path @frame N` forensics contract), the
    bufpool ends with ZERO outstanding blocks and the process fd count
    is unchanged;
  * **injection matrix** — every `PC_MEDIA_FAULTS` kind against a
    CLEAN file (decode-error, short-read, geometry-flip, enospc), plus
    the deadline self-test: an injected native hang must be abandoned
    within the configured `PC_MEDIA_DEADLINE_S` budget (wall-clock
    measured and reported — the CI gate that proves the deadline
    actually fires), the reader poisoned, the expiry classified
    transient;
  * **chain leg** — each corrupt member as the SRC of a tiny database
    through the stage CLI: the run fails as a CLASSIFIED error (exit
    code, not a traceback), no partial artifact and no `.inprogress`
    sentinel survives, the bufpool stays clean;
  * **serve leg** — a real `chain-serve` service (chain executor,
    `PC_ISOLATE_DECODE=1`, wave width 1) over clean + corrupt SRCs:
    clean units `done` with verified store artifacts, corrupt units
    POISON-quarantined **by content digest** (the registry holds the
    files' sha256), queued siblings swept without executing
    (attempts == 0), a second request against the same digest parks at
    POST time, and the operator rearm → re-conviction roundtrip works
    (`tools serve-admin poison`); zero partial store artifacts.

Prints one JSON report line (the `MEDIA_FAULTS_*.json` artifact
committed with the PR) and exits nonzero on any violated expectation.

    python -m processing_chain_tpu tools media-crashcheck
        [--frames 48] [--deadline-s 0.75] [--hang-s 6]
        [--timeout-s 240] [--skip-serve] [--skip-chain]
        [--out FILE] [--root DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import tempfile
import time
from typing import Optional, Sequence

from ..utils.fsio import atomic_write_json, atomic_write_text
from ..utils.log import get_logger

#: corrupt-corpus member -> how the decoder surface must dispose of it.
#: `open-error`   = the demuxer/decoder rejects the container outright;
#: `stream-error` = the open succeeds and a MediaError fires mid-stream
#:                  (carrying the `@frame` forensics contract);
#: `short-or-error` = libav tolerates the damage as a silent early EOF
#:                  on some builds and errors on others — both contain
#:                  (fewer frames than promised, or a classified error),
#:                  and the serve leg's first-contact frame-count check
#:                  is what upgrades the silent shape to a verdict.
CORPUS = {
    "trunc_gop": "short-or-error",   # valid h264, cut mid-GOP
    "garbage": "open-error",         # 64 KiB of deterministic noise
    "zero_byte": "open-error",       # 0-byte file
    "wrong_codec": "open-error",     # valid RIFF/WAVE audio container
    "geom_flip": "stream-error",     # mid-stream geometry change
}

_W, _H, _FPS = 160, 90, 24


# ------------------------------------------------------------- corpus


def _write_clean(path: str, frames: int, w: int = _W, h: int = _H,
                 codec: str = "ffv1", gop: int = 1) -> None:
    import numpy as np

    from ..io.video import VideoWriter

    with VideoWriter(path, codec, w, h, "yuv420p", (_FPS, 1),
                     gop=gop) as wr:
        xx, yy = np.meshgrid(np.arange(w), np.arange(h))
        for f in range(frames):
            y = ((np.sin((xx + 4 * f) / 23) + np.cos((yy + f) / 17))
                 * 50 + 120).astype(np.uint8)
            u = np.full((h // 2, w // 2), 128, np.uint8)
            v = np.full((h // 2, w // 2), 118, np.uint8)
            wr.write(y, u, v)


def _write_wav(path: str, seconds: float = 0.5, rate: int = 8000) -> None:
    """A VALID audio-only RIFF/WAVE container: the wrong-codec shape —
    a well-formed file of the wrong kind, not random bytes."""
    import struct

    n = int(seconds * rate)
    data = struct.pack("<%dh" % n, *([0] * n))
    hdr = (b"RIFF" + struct.pack("<I", 36 + len(data)) + b"WAVE"
           + b"fmt " + struct.pack("<IHHIIHH", 16, 1, 1, rate,
                                   rate * 2, 2, 16)
           + b"data" + struct.pack("<I", len(data)))
    # chainlint: disable=atomic-write (corpus generation into a private tmp dir)
    with open(path, "wb") as f:
        f.write(hdr + data)


def make_corrupt_corpus(root: str, frames: int) -> dict:
    """Generate the corpus; returns {member: path} plus 'clean'."""
    import numpy as np

    os.makedirs(root, exist_ok=True)
    paths = {"clean": os.path.join(root, "clean.avi")}
    _write_clean(paths["clean"], frames)

    # truncated mid-GOP: an INTER-coded stream (one I-frame, the rest
    # P) cut at 55% — the damage lands inside the open GOP
    full = os.path.join(root, "trunc_src.avi")
    _write_clean(full, frames, codec="libx264", gop=max(2, frames))
    paths["trunc_gop"] = os.path.join(root, "trunc_gop.avi")
    size = os.path.getsize(full)
    with open(full, "rb") as f:
        head = f.read(int(size * 0.55))
    # chainlint: disable=atomic-write (corpus generation into a private tmp dir)
    with open(paths["trunc_gop"], "wb") as f:
        f.write(head)
    os.unlink(full)

    paths["garbage"] = os.path.join(root, "garbage.avi")
    rng = np.random.default_rng(15)
    # chainlint: disable=atomic-write (corpus generation into a private tmp dir)
    with open(paths["garbage"], "wb") as f:
        f.write(rng.integers(0, 256, 65536, np.uint8).tobytes())

    paths["zero_byte"] = os.path.join(root, "zero_byte.avi")
    # chainlint: disable=atomic-write (corpus generation into a private tmp dir)
    with open(paths["zero_byte"], "wb") as f:
        pass

    paths["wrong_codec"] = os.path.join(root, "wrong_codec.avi")
    _write_wav(paths["wrong_codec"])

    # mid-stream geometry flip: a clean stream whose decode flips
    # geometry at frame 8 via the injection layer (authoring a real
    # container whose parameter sets flip mid-stream is exactly the
    # fiddly thing io/faults exists to make deterministic; media.cpp's
    # rejection shape is what the clause raises)
    paths["geom_flip"] = os.path.join(root, "geom_flip.avi")
    _write_clean(paths["geom_flip"], frames)
    return paths


def _fault_env(member: str, path: str) -> dict:
    """PC_MEDIA_FAULTS clauses a corpus member needs (geom_flip is
    injection-driven; everything else is real bytes)."""
    if member == "geom_flip":
        return {"PC_MEDIA_FAULTS":
                f"geometry-flip@frame=8,match={os.path.basename(path)},"
                "times=0"}
    return {}


class _EnvPatch:
    """Scoped os.environ overlay (None = remove)."""

    def __init__(self, **values) -> None:
        self._values = values
        self._saved: dict = {}

    def __enter__(self) -> "_EnvPatch":
        for key, value in self._values.items():
            self._saved[key] = os.environ.get(key)
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = str(value)
        return self

    def __exit__(self, *exc) -> None:
        for key, old in self._saved.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


# ------------------------------------------------------------ accounting


def _bufpool_outstanding() -> int:
    from ..io.bufpool import DEFAULT_POOL

    return int(DEFAULT_POOL.stats()["outstanding"])


def _open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def _sweep_leaks(failures: list, where: str, fds_before: int) -> None:
    """Zero leaked bufpool blocks; fd count back to baseline (a couple
    of retries ride out lazily-closed writer threads)."""
    import gc

    gc.collect()  # drop traceback↔frame cycles from caught failures
    out = _bufpool_outstanding()
    if out:
        failures.append(f"{where}: {out} bufpool block(s) leaked")
    for _ in range(20):
        if fds_before < 0 or _open_fds() <= fds_before:
            return
        time.sleep(0.1)
    failures.append(
        f"{where}: fd count {_open_fds()} above baseline {fds_before}")


# --------------------------------------------------------- reader matrix


def _drain_reader(path: str) -> dict:
    """Decode every frame of `path` through the chunked reader,
    releasing pooled blocks as they stream; returns {frames} or raises."""
    from ..io.bufpool import DEFAULT_POOL
    from ..io.video import VideoReader

    frames = 0
    with VideoReader(path) as reader:
        for chunk in reader.iter_chunks():
            frames += int(chunk[0].shape[0])
            DEFAULT_POOL.release(*chunk)
    return {"frames": frames}


def reader_matrix(paths: dict, frames: int, failures: list) -> dict:
    """Each corrupt member through the decoder surface; dispositions
    per the CORPUS table, leak accounting per member."""
    from ..io.medialib import MediaError

    results: dict = {}
    for member, expect in CORPUS.items():
        path = paths[member]
        fds = _open_fds()
        observed: dict = {"expect": expect}
        with _EnvPatch(**(_fault_env(member, path) or
                          {"PC_MEDIA_FAULTS": None})):
            _reset_faults()
            try:
                observed.update(_drain_reader(path))
                observed["outcome"] = "eof"
            except MediaError as exc:
                observed["outcome"] = "media-error"
                observed["error"] = str(exc)[:200]
            except Exception as exc:  # noqa: BLE001 - matrix verdict
                observed["outcome"] = f"unexpected:{type(exc).__name__}"
                observed["error"] = str(exc)[:200]
        results[member] = observed
        ok = {
            "open-error": observed["outcome"] == "media-error",
            "stream-error": observed["outcome"] == "media-error"
            and "@frame" in observed.get("error", ""),
            "short-or-error":
                observed["outcome"] == "media-error"
                or (observed["outcome"] == "eof"
                    and observed.get("frames", frames) < frames),
        }[expect]
        if not ok:
            failures.append(
                f"reader[{member}]: expected {expect}, observed "
                f"{observed['outcome']} ({observed.get('error', '')[:80]}"
                f" frames={observed.get('frames')})")
        if observed["outcome"] == "media-error" and \
                path not in observed.get("error", ""):
            failures.append(
                f"reader[{member}]: MediaError does not name the source "
                f"path (forensics contract): {observed['error'][:120]}")
        _sweep_leaks(failures, f"reader[{member}]", fds)
    return results


def _reset_faults() -> None:
    from ..io import faults

    faults.reset_fire_counts()


# ------------------------------------------------------ injection matrix


def injection_matrix(paths: dict, frames: int, deadline_s: float,
                     hang_s: float, failures: list) -> dict:
    """Every PC_MEDIA_FAULTS kind against the CLEAN file, including the
    deadline self-test (the hang must be abandoned within budget)."""
    import numpy as np

    from ..io.medialib import MediaError
    from ..io.video import VideoWriter

    clean = paths["clean"]
    base = os.path.basename(clean)
    results: dict = {}

    # decode-error at a mid-stream frame: classified, frame-attributed
    fds = _open_fds()
    with _EnvPatch(PC_MEDIA_FAULTS=f"decode-error@frame=10,match={base}"):
        _reset_faults()
        try:
            _drain_reader(clean)
            failures.append("inject[decode-error]: no error raised")
            results["decode_error"] = {"outcome": "eof"}
        except MediaError as exc:
            results["decode_error"] = {"outcome": "media-error",
                                       "error": str(exc)[:200]}
            if "@frame" not in str(exc):
                failures.append(
                    "inject[decode-error]: error lacks the @frame "
                    f"forensics: {exc}")
    _sweep_leaks(failures, "inject[decode-error]", fds)

    # short-read: silent EOF after exactly N frames, NO error
    with _EnvPatch(PC_MEDIA_FAULTS=f"short-read@frame=12,match={base}"):
        _reset_faults()
        try:
            got = _drain_reader(clean)
            results["short_read"] = got
            if got["frames"] != 12:
                failures.append(
                    f"inject[short-read]: {got['frames']} frames "
                    "delivered, expected exactly 12")
        except MediaError as exc:
            failures.append(f"inject[short-read]: raised {exc!r}, "
                            "expected a silent early EOF")

    # geometry-flip: the media.cpp mid-stream rejection shape
    with _EnvPatch(PC_MEDIA_FAULTS=f"geometry-flip@frame=6,match={base}"):
        _reset_faults()
        try:
            _drain_reader(clean)
            failures.append("inject[geometry-flip]: no error raised")
        except MediaError as exc:
            results["geometry_flip"] = {"error": str(exc)[:200]}
            if "geometry" not in str(exc):
                failures.append(
                    f"inject[geometry-flip]: unexpected shape: {exc}")

    # enospc on the encode write: the full-disk shape, an OSError with
    # the real errno so classify_failure reads it transient
    import errno as errno_mod

    enc_path = os.path.join(os.path.dirname(clean), "enospc_out.avi")
    with _EnvPatch(PC_MEDIA_FAULTS="enospc@frame=3,match=enospc_out"):
        _reset_faults()
        try:
            y = np.full((_H, _W), 128, np.uint8)
            u = np.full((_H // 2, _W // 2), 128, np.uint8)
            v = np.full((_H // 2, _W // 2), 128, np.uint8)
            with VideoWriter(enc_path, "ffv1", _W, _H, "yuv420p",
                             (_FPS, 1)) as wr:
                for _ in range(8):
                    wr.write(y, u, v)
            failures.append("inject[enospc]: encode completed")
        except OSError as exc:
            results["enospc"] = {"errno": exc.errno}
            if exc.errno != errno_mod.ENOSPC:
                failures.append(
                    f"inject[enospc]: errno {exc.errno}, expected ENOSPC")
        finally:
            if os.path.isfile(enc_path):
                os.unlink(enc_path)

    # THE DEADLINE SELF-TEST: an injected native hang (longer than the
    # whole gate's patience) must be abandoned within the configured
    # budget — this is the claim "a hung decoder call cannot own a
    # worker" made empirical. The reader must come back poisoned.
    from ..io import faults as faults_mod
    from ..io.bufpool import DEFAULT_POOL
    from ..io.video import VideoReader

    with _EnvPatch(
        PC_MEDIA_FAULTS=f"hang@seconds={hang_s:g},op=decode,match={base}",
        PC_MEDIA_DEADLINE_S=f"{deadline_s:g}",
    ):
        _reset_faults()
        t0 = time.perf_counter()
        reader = VideoReader(clean)
        try:
            for chunk in reader.iter_chunks():
                DEFAULT_POOL.release(*chunk)
            failures.append("inject[hang]: decode completed — the hang "
                            "never fired")
            elapsed = time.perf_counter() - t0
        except faults_mod.MediaDeadlineExpired as exc:
            elapsed = time.perf_counter() - t0
            results["hang_deadline"] = {
                "deadline_s": deadline_s,
                "hang_s": hang_s,
                "abandoned_after_s": round(elapsed, 3),
                "kind": getattr(exc, "kind", None),
            }
            if elapsed > deadline_s + 2.0:
                failures.append(
                    f"inject[hang]: abandoned after {elapsed:.2f}s — far "
                    f"past the {deadline_s:g}s budget")
            if getattr(exc, "kind", None) != "transient":
                failures.append(
                    "inject[hang]: expiry not classified transient "
                    f"(kind={getattr(exc, 'kind', None)!r})")
            try:
                next(iter(reader.iter_chunks()))
                failures.append("inject[hang]: poisoned reader still "
                                "decodes")
            except faults_mod.MediaError:
                pass  # refused: the poisoned-handle contract
        # the abandoned thread still sleeps inside the injected hang,
        # holding its blocks — DELIBERATELY leaked with the handle. A
        # real worker dies here; this harness lives on, so wait for the
        # abandoned thread to run out and drop them before later legs
        # do their own zero-leak accounting. The frames that held the
        # blocks sit in traceback↔frame cycles: only the cyclic GC
        # returns them.
        import gc

        deadline = time.monotonic() + hang_s + 15.0
        while time.monotonic() < deadline and \
                DEFAULT_POOL.stats()["outstanding"]:
            gc.collect()
            time.sleep(0.2)
        if DEFAULT_POOL.stats()["outstanding"]:
            failures.append(
                "inject[hang]: abandoned blocks never settled after the "
                "hang ran out")
    return results


# ------------------------------------------------------------- chain leg


_DB_YAML = """\
databaseId: {db}
syntaxVersion: 6
type: short
qualityLevelList:
  Q0: {{index: 0, videoCodec: h264, videoBitrate: 200, width: 160, height: 90, fps: 24}}
codingList:
  VC01: {{type: video, encoder: libx264, passes: 1, iFrameInterval: 1, preset: ultrafast}}
srcList:
  SRC000: SRC000.avi
hrcList:
  HRC000: {{videoCodingId: VC01, eventList: [[Q0, 2]]}}
  HRC001: {{videoCodingId: VC01, eventList: [[Q0, 1]]}}
pvsList:
  - {db}_SRC000_HRC000
  - {db}_SRC000_HRC001
postProcessingList:
  - {{type: pc, displayWidth: 160, displayHeight: 90, codingWidth: 160, codingHeight: 90, displayFrameRate: 24}}
"""


def _residue(db_dir: str) -> list[str]:
    """Partial artifacts / sentinels left under a database dir."""
    bad = []
    for base, _dirs, names in os.walk(db_dir):
        for name in names:
            if name.endswith(".inprogress") or name.endswith(".part") \
                    or name.endswith(".tmp"):
                bad.append(os.path.relpath(os.path.join(base, name),
                                           db_dir))
    return bad


def chain_leg(paths: dict, root: str, failures: list) -> dict:
    """Each corrupt member as SRC000 of a tiny database through the
    stage CLI: classified failure, zero residue, zero leaks. The clean
    member must pass p01–p04 in the same harness (the corpus is only
    proof if the pipeline it fails is one that works)."""
    from ..cli import main as cli_main

    results: dict = {}
    members = ["clean", *CORPUS]
    for i, member in enumerate(members):
        db = f"P2SXM{60 + i}"
        db_dir = os.path.join(root, "chain", db)
        os.makedirs(os.path.join(db_dir, "srcVid"), exist_ok=True)
        atomic_write_text(os.path.join(db_dir, db + ".yaml"),
                          _DB_YAML.format(db=db))
        shutil.copyfile(paths[member],
                        os.path.join(db_dir, "srcVid", "SRC000.avi"))
        yaml_path = os.path.join(db_dir, db + ".yaml")
        fds = _open_fds()
        observed: dict = {}
        with _EnvPatch(**(_fault_env(member, "SRC000.avi") or
                          {"PC_MEDIA_FAULTS": None})):
            _reset_faults()
            stage_rcs: dict = {}
            try:
                for stage in ("p01", "p02", "p03", "p04"):
                    rc = cli_main([stage, "-c", yaml_path,
                                   "--skip-requirements"])
                    stage_rcs[stage] = rc
                    if rc != 0:
                        break
                observed = {"stages": stage_rcs, "outcome": "exit"}
            except BaseException as exc:  # noqa: BLE001 - matrix verdict
                observed = {"stages": stage_rcs,
                            "outcome": f"raise:{type(exc).__name__}",
                            "error": str(exc)[:200]}
        results[member] = observed
        if member == "clean":
            if observed["outcome"] != "exit" or \
                    any(rc != 0 for rc in observed["stages"].values()):
                failures.append(
                    f"chain[clean]: the control run failed: {observed}")
        else:
            terminal_ok = observed["outcome"] == "exit" and \
                any(rc != 0 for rc in observed["stages"].values())
            if not terminal_ok:
                failures.append(
                    f"chain[{member}]: expected a CLASSIFIED nonzero "
                    f"exit, observed {observed} — an unclassified "
                    "traceback (or a clean pass) is a containment "
                    "failure")
        residue = _residue(db_dir)
        if residue:
            failures.append(f"chain[{member}]: residue after the run: "
                            f"{residue[:5]}")
        _sweep_leaks(failures, f"chain[{member}]", fds)
    return results


# ------------------------------------------------------------- serve leg


def _post(url: str, payload: dict, timeout: float = 60.0) -> dict:
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        return json.loads(exc.read().decode())


def _serve_corpus(root: str, paths: dict, frames: int) -> dict:
    """A chain-executor database whose srcVid holds the clean SRC and
    two REAL corrupt members (the ones whose bytes are hostile without
    injection help)."""
    db = "P2SXM75"
    db_dir = os.path.join(root, "serve-corpus", db)
    os.makedirs(os.path.join(db_dir, "srcVid"), exist_ok=True)
    members = {"SRC000": "clean", "SRC001": "trunc_gop", "SRC002":
               "garbage"}
    for src, member in members.items():
        shutil.copyfile(paths[member],
                        os.path.join(db_dir, "srcVid", src + ".avi"))
    hrc_rows = "\n".join(
        f"  HRC{i:03d}: {{videoCodingId: VC01, eventList: [[Q0, 2]]}}"
        for i in range(3)
    )
    pvs_rows = "\n".join(
        f"  - {db}_{src}_HRC{i:03d}" for src in members for i in range(3)
    )
    config = os.path.join(db_dir, db + ".yaml")
    atomic_write_text(config, (
        f"databaseId: {db}\n"
        "syntaxVersion: 6\n"
        "type: short\n"
        "qualityLevelList:\n"
        "  Q0: {index: 0, videoCodec: h264, videoBitrate: 200, "
        "width: 160, height: 90, fps: 24}\n"
        "codingList:\n"
        "  VC01: {type: video, encoder: libx264, passes: 1, "
        "iFrameInterval: 1, preset: ultrafast}\n"
        "srcList:\n"
        + "\n".join(f"  {s}: {s}.avi" for s in members) + "\n"
        f"hrcList:\n{hrc_rows}\n"
        f"pvsList:\n{pvs_rows}\n"
        "postProcessingList:\n"
        "  - {type: pc, displayWidth: 160, displayHeight: 90, "
        "codingWidth: 160, codingHeight: 90, displayFrameRate: 24}\n"
    ))
    return {"database": db, "config": config, "dir": db_dir,
            "members": members}


def _disk_records(serve_root: str) -> list[dict]:
    """Every queue record from disk — the durable truth, exactly the
    surface `tools serve-chaos` audits."""
    records = []
    jobs_dir = os.path.join(serve_root, "queue", "jobs")
    try:
        names = os.listdir(jobs_dir)
    except OSError:
        return records
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(jobs_dir, name)) as f:
                records.append(json.load(f))
        except (OSError, ValueError):
            continue
    return records


def serve_leg(paths: dict, root: str, frames: int, timeout_s: float,
              failures: list) -> dict:
    """The end-to-end poison story against a REAL chain-serve service
    (see module doc)."""
    from ..serve.service import ChainServeService
    from ..store import runtime as store_runtime
    from ..store.keys import hash_file

    corpus = _serve_corpus(root, paths, frames)
    serve_root = os.path.join(root, "serve")
    results: dict = {}
    with _EnvPatch(PC_ISOLATE_DECODE="1", PC_MEDIA_FAULTS=None,
                   JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu")):
        service = ChainServeService(
            root=serve_root, port=0, executor="chain", workers=1,
            wave_width=1, max_attempts=3, poll_s=0.2,
        ).start()
        try:
            url = service.server.url + "/v1/requests"

            def _wait_terminal(req_id: str) -> dict:
                deadline = time.monotonic() + timeout_s
                while time.monotonic() < deadline:
                    doc = service.request_status(req_id) or {}
                    if doc.get("state") in ("done", "failed"):
                        return doc
                    time.sleep(0.2)
                return {"state": "timeout"}

            # clean SRC: the control — all units done, artifacts real
            clean = _post(url, {
                "tenant": "ok", "priority": "interactive",
                "database": corpus["database"],
                "srcs": ["SRC000"], "hrcs": ["HRC000"],
                "params": {"config": corpus["config"]},
            })
            doc = _wait_terminal(clean["request"])
            results["clean_state"] = doc.get("state")
            if doc.get("state") != "done":
                failures.append(
                    f"serve[clean]: ended {doc.get('state')!r} "
                    f"({doc.get('error')})")

            # corrupt SRCs, two HRCs each: the FIRST failing unit's
            # poison verdict must sweep its queued sibling by digest
            convicted: dict = {}
            for src in ("SRC001", "SRC002"):
                resp = _post(url, {
                    "tenant": "hostile", "priority": "normal",
                    "database": corpus["database"],
                    "srcs": [src], "hrcs": ["HRC000", "HRC001"],
                    "params": {"config": corpus["config"]},
                })
                doc = _wait_terminal(resp["request"])
                convicted[src] = doc
                if doc.get("state") != "failed":
                    failures.append(
                        f"serve[{src}]: ended {doc.get('state')!r}, "
                        "expected failed (poison)")

            queue = service.queue
            poisoned = {e["digest"]: e for e in queue.poisoned_digests()}
            results["poisoned_digests"] = len(poisoned)
            for src in ("SRC001", "SRC002"):
                digest = hash_file(os.path.join(
                    corpus["dir"], "srcVid", src + ".avi"))["sha256"]
                if digest not in poisoned:
                    failures.append(
                        f"serve[{src}]: content digest {digest[:12]}… "
                        "not in the poison registry")
                records = [r for r in _disk_records(serve_root)
                           if r.get("srcDigest") == digest]
                if not records:
                    failures.append(f"serve[{src}]: no queue records "
                                    "carry its digest")
                for r in records:
                    if r.get("state") != "quarantined":
                        failures.append(
                            f"serve[{src}]: record {r.get('job')} ended "
                            f"{r.get('state')!r}, expected quarantined")
                    if r.get("errorKind") != "poison":
                        failures.append(
                            f"serve[{src}]: record {r.get('job')} kind "
                            f"{r.get('errorKind')!r}, expected poison")
                swept = [r for r in records if not r.get("attempts")]
                if not swept:
                    failures.append(
                        f"serve[{src}]: no sibling was swept without "
                        "executing (attempts==0) — fail-fast never "
                        "happened")
                results[f"{src}_records"] = {
                    "total": len(records),
                    "swept_without_executing": len(swept),
                }

            # a SECOND request against a poisoned digest parks at POST
            # time: new plan, zero executions
            digest1 = hash_file(os.path.join(
                corpus["dir"], "srcVid", "SRC001.avi"))["sha256"]
            before = {r.get("job") for r in _disk_records(serve_root)}
            resp = _post(url, {
                "tenant": "other", "priority": "normal",
                "database": corpus["database"],
                "srcs": ["SRC001"], "hrcs": ["HRC002"],
                "params": {"config": corpus["config"]},
            })
            doc = _wait_terminal(resp["request"])
            results["failfast_state"] = doc.get("state")
            if doc.get("state") != "failed":
                failures.append(
                    "serve[failfast]: second request against the "
                    f"poisoned digest ended {doc.get('state')!r}")
            late = [r for r in _disk_records(serve_root)
                    if r.get("job") not in before
                    and r.get("srcDigest") == digest1]
            if not late:
                failures.append("serve[failfast]: the second request "
                                "minted no record to audit")
            for r in late:
                if r.get("attempts") or r.get("state") != "quarantined":
                    failures.append(
                        f"serve[failfast]: record {r.get('job')} "
                        f"state={r.get('state')} attempts="
                        f"{r.get('attempts')} — it EXECUTED against a "
                        "known-poisoned digest")

            # operator roundtrip: rearm unparks every record under the
            # digest; the still-corrupt bytes re-convict
            rearm = queue.rearm_src(digest1)
            results["rearm"] = {"was_poisoned": rearm["was_poisoned"],
                                "rearmed": len(rearm["rearmed"])}
            if not rearm["was_poisoned"] or not rearm["rearmed"]:
                failures.append(f"serve[rearm]: {rearm}")
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                states = {r.get("state")
                          for r in _disk_records(serve_root)
                          if r.get("srcDigest") == digest1}
                if states <= {"quarantined", "failed"}:
                    break
                time.sleep(0.2)
            else:
                failures.append("serve[rearm]: re-armed records never "
                                "re-settled")
            if queue.src_poisoned(digest1) is None:
                failures.append("serve[rearm]: the re-executed corrupt "
                                "bytes were not re-convicted")

            # zero partial store artifacts: every committed object
            # verifies, no temp residue under the store root
            store = store_runtime.active()
            from ..store.store import StoreCorruption

            for manifest in store.iter_manifests():
                for digest in manifest.all_digests():
                    try:
                        store.verify_object(digest)
                    except StoreCorruption as exc:
                        failures.append(f"serve: corrupt store object "
                                        f"({exc})")
            residue = _residue(os.path.join(serve_root, "store"))
            if residue:
                failures.append(
                    f"serve: store temp residue: {residue[:5]}")
        finally:
            service.stop()
            store_runtime.configure(None)
    return results


# ----------------------------------------------------------------- main


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tools media-crashcheck",
        description="corrupt-corpus proof for the native media "
                    "boundary (docs/ROBUSTNESS.md)",
    )
    p.add_argument("--frames", type=int, default=48)
    p.add_argument("--deadline-s", type=float, default=0.75,
                   help="PC_MEDIA_DEADLINE_S for the hang self-test")
    p.add_argument("--hang-s", type=float, default=6.0,
                   help="injected hang length (must dwarf the deadline)")
    p.add_argument("--timeout-s", type=float, default=240.0)
    p.add_argument("--skip-serve", action="store_true")
    p.add_argument("--skip-chain", action="store_true")
    p.add_argument("--out", default=None,
                   help="also write the report JSON here")
    p.add_argument("--root", default=None,
                   help="working dir (default: a fresh temp dir)")
    args = p.parse_args(argv)
    log = get_logger()

    root = args.root or tempfile.mkdtemp(prefix="media-crashcheck-")
    os.makedirs(root, exist_ok=True)
    failures: list[str] = []
    report: dict = {"frames": args.frames, "deadline_s": args.deadline_s,
                    "root": root}
    t0 = time.perf_counter()

    paths = make_corrupt_corpus(os.path.join(root, "corpus"), args.frames)
    report["corpus"] = sorted(CORPUS)
    log.info("media-crashcheck: corpus of %d corrupt members + 1 clean "
             "under %s", len(CORPUS), root)

    report["reader"] = reader_matrix(paths, args.frames, failures)
    log.info("media-crashcheck: reader matrix done (%d findings)",
             len(failures))
    report["inject"] = injection_matrix(
        paths, args.frames, args.deadline_s, args.hang_s, failures)
    log.info("media-crashcheck: injection matrix done (%d findings)",
             len(failures))
    if not args.skip_chain:
        report["chain"] = chain_leg(paths, root, failures)
        log.info("media-crashcheck: chain leg done (%d findings)",
                 len(failures))
    if not args.skip_serve:
        report["serve"] = serve_leg(paths, root, args.frames,
                                    args.timeout_s, failures)
        log.info("media-crashcheck: serve leg done (%d findings)",
                 len(failures))

    report["wall_s"] = round(time.perf_counter() - t0, 2)
    report["failures"] = failures
    report["ok"] = not failures
    print(json.dumps(report, sort_keys=True))
    if args.out:
        atomic_write_json(args.out, report)
    if failures:
        log.error("media-crashcheck: %d violated expectation(s):\n  %s",
                  len(failures), "\n  ".join(failures))
        return 1
    log.info("media-crashcheck: OK (%ss)", report["wall_s"])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
