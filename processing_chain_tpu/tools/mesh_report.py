"""mesh-report: the mesh-scaling report generator (MESH_OBS_r18.json).

    tools mesh-report sweep [--devices 8] [--frames 8] [--out FILE]
                            [--journal DIR]

The measured acceptance harness for the device-plane flight recorder
(parallel/meshobs.py, docs/PERF.md "My waves are wasteful"): a toy
mixed-geometry corpus driven through the REAL wave driver
(parallel/p03_batch.run_bucket) on a virtual CPU mesh, with the wave
journal attached, producing the three scaling curves the ROADMAP's
mesh-efficiency evidence needs:

  * **throughput vs lane count** — the same geometry bucket at 1×, 2×
    and 4× the mesh width: valid frames/second per sweep point, each
    point's journal re-checked for the valid+pad == dispatched
    invariant;
  * **waste vs bucket spread** — uniform lane lengths against a
    deliberately ragged mix in one bucket: the padded-slot fraction
    must rise with the spread (tail-repeat + exhausted-lane pads are
    REAL dispatched work, the accounting must show it);
  * **compile ledger** — three distinct geometries then a REVISIT of
    the first: recompiles == distinct geometries, and the revisit adds
    none (one geometry flip = exactly one recompile);
  * **RSS / device-memory plateau** — a resource snapshot after every
    sweep point: the wave driver's double-buffered assembly must not
    scale host memory with lane count.

XLA fixes its host device count at first backend init, so the sweep
re-execs itself into a clean child process with JAX_PLATFORMS=cpu and
the forced device count (same hazard note as
__graft_entry__.dryrun_multichip); the parent only relays output.

Prints one JSON report line and exits 1 when any invariant fails.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Optional, Sequence

from ..utils.fsio import atomic_write_text
from ..utils.log import get_logger


def _reexec_child(args, argv: Sequence[str]) -> int:
    """Re-run this tool in a subprocess whose XLA host-device count is
    forced BEFORE any backend exists (nothing in this process — env,
    jax config, initialized backends — is mutated)."""
    import re

    from ..utils.runner import shell

    env = dict(os.environ)
    env["_PC_MESH_REPORT_CHILD"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        env.get("XLA_FLAGS", ""),
    ).strip()
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={args.devices}"
    ).strip()
    proc = shell(
        [sys.executable, "-m", "processing_chain_tpu.cli",
         "tools", "mesh-report", *argv],
        check=False, timeout=1800, env=env,
    )
    # the child's report (JSON + progress) belongs on OUR streams
    sys.stdout.write(proc.stdout)
    sys.stderr.write(proc.stderr)
    return proc.returncode


def _run_lanes(mesh, lengths, dh, dw, journal_dir, *, ten_bit=False,
               chunk=8, sh=36, sw=64):
    """One sweep point: `lengths[i]` frames of synthetic YUV per lane,
    through run_bucket with the journal attached to `journal_dir`.
    Returns (aggregate, elapsed_s, emitted_frames)."""
    import numpy as np

    from ..parallel import meshobs, p03_batch

    rng = np.random.default_rng(0x18)
    outs: list[list] = [[] for _ in lengths]
    lanes = []
    for i, n in enumerate(lengths):
        yuv = [
            rng.integers(0, 255, size=(n, sh, sw), dtype=np.uint8),
            rng.integers(0, 255, size=(n, sh // 2, sw // 2), dtype=np.uint8),
            rng.integers(0, 255, size=(n, sh // 2, sw // 2), dtype=np.uint8),
        ]
        lanes.append(p03_batch.Lane(
            chunks=iter([yuv]), emit=outs[i].append,
            n_frames_hint=n, name=f"lane{i:02d}",
        ))
    meshobs.attach_journal(journal_dir, replica="sweep")
    t0 = time.perf_counter()
    p03_batch.run_bucket(
        lanes, mesh, dh, dw, "bicubic", (2, 2), ten_bit, chunk=chunk,
        bucket=p03_batch.bucket_label(dh, dw, ten_bit, sh, sw),
    )
    elapsed = time.perf_counter() - t0
    meshobs.detach_journal()
    emitted = sum(
        sum(blk[0].shape[0] for blk in out) for out in outs
    )
    return meshobs.aggregate(journal_dir), elapsed, emitted


def _check_point(tag: str, agg: dict, want_valid: int,
                 failures: list) -> None:
    tot = agg["totals"]
    if agg["invariant_violations"]:
        failures.append(
            f"{tag}: {agg['invariant_violations']} wave record(s) broke "
            "valid+pad == dispatched")
    if tot["valid"] != want_valid:
        failures.append(
            f"{tag}: journal counts {tot['valid']} valid slots, the "
            f"corpus has {want_valid} frames")
    padded = tot["pad_tail"] + tot["pad_exhausted"] + tot["pad_mesh"]
    if tot["valid"] + padded != tot["dispatched"]:
        failures.append(
            f"{tag}: totals {tot['valid']}+{padded} != "
            f"{tot['dispatched']} dispatched")


def _cmd_sweep(args, argv: Sequence[str]) -> int:
    log = get_logger()
    if os.environ.get("_PC_MESH_REPORT_CHILD") != "1":
        return _reexec_child(args, argv)

    import jax

    from .. import telemetry as tm
    from ..parallel import meshobs
    from ..parallel.mesh import make_mesh
    from ..telemetry import profiling

    tm.enable()
    journal_root = args.journal or tempfile.mkdtemp(prefix="mesh-report-")
    devices = jax.devices("cpu")[:args.devices]
    if len(devices) != args.devices:
        log.error("mesh-report: need %d devices, have %d (child env "
                  "did not take)", args.devices, len(devices))
        return 1
    time_parallel = 2 if args.devices % 2 == 0 else 1
    mesh = make_mesh(devices, time_parallel=time_parallel)
    n_pvs = mesh.shape["pvs"]
    t_step = max(1, 8 // mesh.shape["time"]) * mesh.shape["time"]
    report: dict = {
        "devices": args.devices,
        "mesh": dict(mesh.shape),
        "t_step": t_step,
        "journal_root": journal_root,
    }
    failures: list[str] = []

    # ---- throughput vs lane count: same bucket, 1x/2x/4x mesh width --
    # warmup dispatch first: the sweep points must all ride the SAME
    # compiled step, or point 1 silently carries the XLA compile
    _run_lanes(mesh, [t_step] * n_pvs, 72, 128,
               os.path.join(journal_root, "warmup"), chunk=t_step)
    scaling = []
    for mult in (1, 2, 4):
        lanes_n = n_pvs * mult
        lengths = [args.frames] * lanes_n
        jdir = os.path.join(journal_root, f"scale_{lanes_n:03d}")
        agg, elapsed, emitted = _run_lanes(
            mesh, lengths, 72, 128, jdir, chunk=t_step)
        _check_point(f"scale x{mult}", agg, sum(lengths), failures)
        if emitted != sum(lengths):
            failures.append(
                f"scale x{mult}: {emitted} frames emitted, "
                f"{sum(lengths)} decoded")
        scaling.append({
            "lanes": lanes_n,
            "frames": sum(lengths),
            "waves": agg["totals"]["waves"],
            "seconds": round(elapsed, 4),
            "frames_per_s": round(sum(lengths) / elapsed, 2),
            "waste_fraction": agg["totals"]["waste_fraction"],
        })
        sample = profiling.sample_resources()
        devmem = sample.get("device_memory", {})
        scaling[-1]["rss_bytes"] = sample.get("rss_bytes")
        scaling[-1]["device_bytes_in_use"] = devmem.get("bytes_in_use")
    report["scaling"] = scaling
    rss = [p["rss_bytes"] for p in scaling if p["rss_bytes"]]
    if len(rss) >= 2 and rss[0]:
        # the wave driver double-buffers ONE wave regardless of lane
        # count — host memory must plateau, not scale with lanes
        report["rss_plateau_ratio"] = round(rss[-1] / rss[0], 3)
        if report["rss_plateau_ratio"] > 3.0:
            failures.append(
                f"RSS grew {report['rss_plateau_ratio']}x from "
                f"{scaling[0]['lanes']} to {scaling[-1]['lanes']} lanes "
                "— the wave buffers are not plateauing")

    # ---- waste vs bucket spread: uniform vs ragged lengths -----------
    uniform = [t_step] * n_pvs
    ragged = [t_step if i % 2 else max(1, t_step // 4)
              for i in range(n_pvs)]
    frag = {}
    for tag, lengths in (("uniform", uniform), ("ragged", ragged)):
        jdir = os.path.join(journal_root, f"frag_{tag}")
        agg, _, _ = _run_lanes(mesh, lengths, 72, 128, jdir, chunk=t_step)
        _check_point(f"frag {tag}", agg, sum(lengths), failures)
        tot = agg["totals"]
        frag[tag] = {
            "lengths": lengths,
            "waste_fraction": tot["waste_fraction"],
            "pad_tail": tot["pad_tail"],
            "pad_exhausted": tot["pad_exhausted"],
            "pad_mesh": tot["pad_mesh"],
        }
    report["fragmentation"] = frag
    if frag["uniform"]["waste_fraction"] != 0.0:
        failures.append(
            "t_step-aligned uniform lanes padded "
            f"{frag['uniform']['waste_fraction']:.2%} — nothing should "
            "pad when every lane fills its blocks")
    if frag["ragged"]["waste_fraction"] <= frag["uniform"]["waste_fraction"]:
        failures.append(
            "ragged lanes show no more waste than uniform ones — the "
            "pad accounting is not seeing the spread")

    # ---- compile ledger: 3 geometries, then revisit the first. All
    # three are FRESH in this process (the sweeps above used 72x128):
    # the compile detector is process-global, so a geometry the sweep
    # already compiled would correctly land its ledger entry THERE.
    ledger_dir = os.path.join(journal_root, "compiles")
    geometries = [
        dict(dh=80, dw=144, ten_bit=False),
        dict(dh=90, dw=160, ten_bit=False),
        dict(dh=80, dw=144, ten_bit=True),
    ]
    for geo in geometries + [geometries[0]]:  # the revisit
        agg, _, _ = _run_lanes(
            mesh, [t_step] * n_pvs, geo["dh"], geo["dw"], ledger_dir,
            ten_bit=geo["ten_bit"], chunk=t_step)
    recompiles = agg["totals"]["recompiles"]
    report["compile_ledger"] = {
        "distinct_geometries": len(geometries),
        "dispatch_rounds": len(geometries) + 1,
        "recompiles": recompiles,
        "buckets": {b: e["recompiles"] for b, e in agg["buckets"].items()},
    }
    if recompiles != len(geometries):
        failures.append(
            f"{recompiles} recompile(s) over {len(geometries)} distinct "
            f"geometries + 1 revisit — one geometry flip must cost "
            "exactly one recompile")

    # ---- the journal itself: cheap stats + metric cross-check --------
    stats = meshobs.journal_stats(ledger_dir)
    report["ledger_journal"] = stats
    if not stats["waves"]:
        failures.append("the compile-ledger journal holds no wave "
                        "records")
    waste = profiling.mesh_waste_from_metrics(tm.REGISTRY.snapshot())
    report["metrics_waste_fraction"] = waste
    if waste is None:
        failures.append("chain_mesh_wave_slots_total carries no series "
                        "— the metrics side of the recorder is dark")

    report["failures"] = failures
    report["ok"] = not failures
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    if failures:
        for f in failures:
            log.error("mesh-report sweep: %s", f)
        return 1
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(argv) if argv is not None else sys.argv[1:]
    parser = argparse.ArgumentParser(
        prog="tools mesh-report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_sweep = sub.add_parser(
        "sweep", help="mesh-occupancy scaling sweep on a virtual CPU mesh")
    p_sweep.add_argument("--devices", type=int, default=8,
                         help="virtual CPU device count for the mesh")
    p_sweep.add_argument("--frames", type=int, default=8,
                         help="frames per lane in the throughput sweep")
    p_sweep.add_argument("--out", default=None,
                         help="write the JSON report here too")
    p_sweep.add_argument("--journal", default=None,
                         help="journal root (default: fresh temp dir)")
    args = parser.parse_args(argv)
    return _cmd_sweep(args, argv)


if __name__ == "__main__":
    raise SystemExit(main())
