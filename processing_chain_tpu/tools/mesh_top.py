"""mesh-top: a refreshing terminal view of device-mesh wave occupancy.

The operator face of the device-plane flight recorder
(parallel/meshobs.py): per geometry bucket, how many wave-steps have
dispatched, how their frame-slots split into valid work vs the three
padding kinds (tail repeat, exhausted lanes riding the wave, batch-axis
mesh padding), the running waste fraction, and the compile ledger
(recompiles + compile-inclusive seconds).

    python -m processing_chain_tpu tools mesh-top http://host:8788
    python -m processing_chain_tpu tools mesh-top RUN_DIR/meshobs_<stamp>
    python -m processing_chain_tpu tools mesh-top SERVE_ROOT --once

A URL reads a live process's /status "mesh" section (in-memory
aggregates since process start); a directory reads the wave journal on
disk — works against a dead or remote-copied run, and additionally
shows the lane→wave schedule the journal preserves. A serve root is
accepted directly (its `meshobs/` journal dir is used). `--once`
renders one frame for scripts/CI.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Optional, Sequence

from .chain_top import StatusSourceError, fetch_status


def load_mesh(source: str) -> dict:
    """The per-bucket aggregate from a /status URL or a journal dir.
    Returns {"buckets": {...}, "totals"?, "schedule"?, "source": ...};
    raises StatusSourceError when the source has no mesh data."""
    if source.startswith(("http://", "https://")):
        status = fetch_status(source)
        mesh = status.get("mesh")
        if not mesh:
            raise StatusSourceError(
                f"{source}: no mesh section (no wave has dispatched in "
                "that process yet)"
            )
        return {"buckets": mesh.get("buckets", {}), "source": source,
                "journal": mesh.get("journal")}
    from ..parallel import meshobs

    root = source
    # a serve root is accepted directly: its meshobs/ dir is the journal
    if os.path.isdir(meshobs.mesh_dir(root)):
        root = meshobs.mesh_dir(root)
    agg = meshobs.aggregate(root)
    if not agg["buckets"]:
        raise StatusSourceError(f"no wave journal records under {root}")
    return {"buckets": agg["buckets"], "totals": agg["totals"],
            "schedule": agg["schedule"],
            "invariant_violations": agg["invariant_violations"],
            "source": root}


def _occupancy_bar(agg: dict, width: int = 24) -> str:
    """valid/pad split as a bar: '#' valid, 't' tail, 'x' exhausted,
    '.' mesh padding."""
    dispatched = agg.get("dispatched", 0)
    if not dispatched:
        return "[" + "?" * width + "]"
    cells = []
    for kind, mark in (("valid", "#"), ("pad_tail", "t"),
                       ("pad_exhausted", "x"), ("pad_mesh", ".")):
        cells.append([mark, agg.get(kind, 0) * width / dispatched])
    # largest-remainder rounding so the bar is always exactly `width`
    floors = [int(c[1]) for c in cells]
    rem = width - sum(floors)
    order = sorted(range(4), key=lambda i: -(cells[i][1] - floors[i]))
    for i in order[:rem]:
        floors[i] += 1
    return "[" + "".join(m * n for (m, _), n in zip(cells, floors)) + "]"


def render(view: dict, note: str = "") -> str:
    """One full frame (plain text; the loop clears the screen)."""
    lines: list[str] = []
    head = f"mesh-top — {view.get('source', '?')}"
    if note:
        head += f"  [{note}]"
    lines.append(head)
    violations = view.get("invariant_violations")
    if violations:
        lines.append(f"  !! {violations} wave record(s) broke "
                     "valid+pad==dispatched (driver accounting bug)")
    lines.append("")
    lines.append("buckets (# valid, t tail-pad, x exhausted-lane, "
                 ". mesh-pad):")
    buckets = view.get("buckets", {})
    if not buckets:
        lines.append("  (no waves dispatched)")
    for name in sorted(buckets):
        agg = buckets[name]
        waste = agg.get("waste_fraction", 0.0)
        lines.append(
            f"  {name:<28} {_occupancy_bar(agg)} "
            f"waste {waste * 100:5.1f}%  waves {agg.get('waves', 0):>5}  "
            f"slots {agg.get('valid', 0)}+{agg.get('pad_tail', 0)}t"
            f"+{agg.get('pad_exhausted', 0)}x+{agg.get('pad_mesh', 0)}. "
            f" step {agg.get('step_s', 0.0):.2f}s"
        )
        if agg.get("recompiles"):
            lines.append(
                f"  {'':<28} compiles {agg['recompiles']} "
                f"({agg.get('compile_s', 0.0):.2f}s compile-inclusive)"
            )
    totals = view.get("totals")
    if totals and len(buckets) > 1:
        lines.append(
            f"  {'TOTAL':<28} {_occupancy_bar(totals)} "
            f"waste {totals.get('waste_fraction', 0.0) * 100:5.1f}%  "
            f"waves {totals.get('waves', 0):>5}  "
            f"compiles {totals.get('recompiles', 0)}"
        )
    schedule = view.get("schedule")
    if schedule:
        lines.append("")
        lines.append("lane→wave schedule (journal, block-0 records):")
        for name in sorted(schedule):
            for entry in schedule[name]:
                lanes = entry.get("lanes", [])
                shown = ", ".join(str(ln) for ln in lanes[:6])
                if len(lanes) > 6:
                    shown += f", … +{len(lanes) - 6}"
                lines.append(
                    f"  {name} wave {entry.get('wave', '?')}: {shown}")
    if view.get("journal"):
        lines.append("")
        lines.append(f"journal: {view['journal']}")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools mesh-top",
        description="device-mesh wave occupancy / waste / compile-ledger "
                    "view (parallel/meshobs.py, docs/PERF.md)",
    )
    parser.add_argument(
        "source",
        help="live /status URL, a meshobs journal directory, or a serve "
             "root containing one",
    )
    parser.add_argument("-i", "--interval", default=2.0, type=float,
                        help="refresh period in seconds")
    parser.add_argument("--once", action="store_true",
                        help="render one frame and exit (scripts/CI)")
    args = parser.parse_args(list(argv) if argv is not None else None)

    if args.once:
        print(render(load_mesh(args.source)), end="")
        return 0
    last_frame = None
    try:
        while True:
            note = ""
            try:
                frame = render(load_mesh(args.source))
                last_frame = frame
            except StatusSourceError as exc:
                if last_frame is None:
                    raise
                note = f"stale: {exc}"
                frame = last_frame.rstrip("\n") + f"\n[{note}]\n"
            sys.stdout.write("\033[2J\033[H" + frame)
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    raise SystemExit(main())
