"""Design plots for database YAML configs.

Parity targets:
  * `plot_long` — reference util/plot_config_long.py:145-296: one row per
    HRC, a rectangle per segment colored by frame height, grey bars for
    stall events, plus design-rule warnings (first chunk ≥ 5 s, last chunk
    ≥ 10 s for long videos, chunk durations divisible by the segment
    duration).
  * `plot_short` — reference util/plot_config_short.py:62-154: frame-height
    vs bitrate scatter on sqrt/log-scaled axes, optionally one plot per
    codec (`-codec-wise`).

Both operate on the raw YAML (no SRC probing required) so they can be run on
a design file before any media exists; `plot_short` also accepts an already
parsed TestConfig. Warnings are returned as structured records (and logged)
instead of bare prints, so the checks are unit-testable.
"""

from __future__ import annotations

import argparse
import math
import os
from typing import Any, Optional, Sequence

import yaml

from ..utils.log import get_logger

#: frame-height bands and their colors (reference plot_config_long.py:106-121)
HEIGHT_BANDS = (240, 360, 480, 540, 720, 1080, 1440, 2160)
BAND_COLORS = (
    "#800000", "#e6194b", "#f58231", "#ffe119",
    "#a6d96a", "#3cb44b", "#4393c3", "#2166ac",
)

_PLOT_PARAM = {
    "stall_height": 0.03,
    "v_offset": 0.2,
    "v_height_max": 0.5,
    "v_res_max": 2160,
    "label_offset": 0.025,
}

_STALL_IDS = ("buffering", "stall", "freeze")


def height_color(height: float) -> str:
    """Color for a frame height: first band ≥ height."""
    for band, color in zip(HEIGHT_BANDS, BAND_COLORS):
        if band >= height:
            return color
    return BAND_COLORS[-1]


def event_list_duration(event_list: Sequence[Sequence[Any]]) -> float:
    return float(sum(e[1] for e in event_list))


def design_warnings(
    hrc_id: str,
    event_list: Sequence[Sequence[Any]],
    video_duration: float,
    segment_duration: float = 0.0,
) -> list[str]:
    """Design-rule checks on one HRC's event list (reference
    plot_config_long.py:164-215). Returns human-readable warning strings."""
    warnings: list[str] = []
    media = [e for e in event_list if e[0] not in _STALL_IDS and e[1] != 0]
    if not media:
        return warnings
    if float(media[0][1]) < 5.0:
        warnings.append(f"HRC {hrc_id}: first chunk duration < 5 seconds")
    last = float(media[-1][1])
    if (last < 10.0 and video_duration > 60) or last < 5.0:
        warnings.append(f"HRC {hrc_id}: last chunk duration < 10 seconds")
    if segment_duration > 0:
        for event_id, duration in media:
            if (float(duration) / segment_duration) % 1 >= 1e-4:
                warnings.append(
                    f"HRC {hrc_id}: chunk {event_id} duration {duration} is "
                    f"not a multiple of segment duration {segment_duration:g}"
                )
    return warnings


def _load_config_data(config: Any) -> dict:
    """Accept a YAML path, a dict, or a parsed TestConfig."""
    if isinstance(config, str):
        with open(config) as f:
            return yaml.safe_load(f)
    if isinstance(config, dict):
        return config
    return config.data  # TestConfig


def plot_long(config: Any, out_file: Optional[str] = None) -> list[str]:
    """Render the HRC timeline SVG; returns all design warnings."""
    import matplotlib

    matplotlib.use("svg")
    from matplotlib.patches import Rectangle
    import matplotlib.pyplot as plt

    data = _load_config_data(config)
    ql_list = data["qualityLevelList"]
    hrc_list = data["hrcList"]
    segment_dur = float(data.get("segmentDuration", 1))
    video_duration = min(event_list_duration(h["eventList"]) for h in hrc_list.values())

    log = get_logger()
    all_warnings: list[str] = []

    fig = plt.figure(figsize=(min(video_duration / 6, 35), max(2, len(hrc_list))))
    ax = fig.add_subplot(111)
    labels: list[str] = []
    max_duration = 0.0

    for i, hrc_id in enumerate(sorted(hrc_list.keys())):
        event_list = hrc_list[hrc_id]["eventList"]
        hrc_seg_dur = float(hrc_list[hrc_id].get("segmentDuration", segment_dur))
        max_duration = max(max_duration, event_list_duration(event_list))
        y_offset = len(hrc_list) - i - 1

        warnings = design_warnings(hrc_id, event_list, video_duration, hrc_seg_dur)
        for w in warnings:
            log.warning("%s", w)
        all_warnings.extend(warnings)

        t = 0.0
        for event_id, duration in event_list:
            duration = float(duration)
            if duration == 0:
                continue
            if event_id in _STALL_IDS:
                ax.add_patch(Rectangle(
                    (t, y_offset + _PLOT_PARAM["v_offset"]), duration,
                    _PLOT_PARAM["stall_height"], fc="grey",
                ))
                t += duration
                continue
            ql = ql_list[event_id]
            height = ql["height"] * _PLOT_PARAM["v_height_max"] / _PLOT_PARAM["v_res_max"]
            color = height_color(ql["height"])
            # full segment rects, then the remainder — t always advances by
            # exactly `duration` so stall bars and the duration line stay
            # aligned even for chunks not divisible by the segment duration
            remaining = duration
            while remaining > 1e-9:
                width = min(hrc_seg_dur, remaining)
                ax.add_patch(Rectangle(
                    (t, y_offset + _PLOT_PARAM["v_offset"]), width, height,
                    fc=color, ec="grey",
                ))
                t += width
                remaining -= width
        labels.append(hrc_id)

    ax.set_yticks(
        [len(hrc_list) - i - 1 + _PLOT_PARAM["v_offset"] for i in range(len(labels))]
    )
    ax.set_yticklabels(labels, fontsize="x-small")
    ax.set_xlabel("time in seconds")
    ax.set_ylim([-0.1, len(hrc_list) + 1])
    ax.set_xlim([0, max_duration * 1.05])
    ax.plot([video_duration, video_duration], ax.get_ylim(), "-k", alpha=0.3)
    title = data.get("databaseId", "")
    if isinstance(config, str):
        title += " : " + os.path.basename(config)
    ax.set_title(title)

    from matplotlib.patches import Patch

    ax.legend(
        handles=[Patch(color=height_color(h), label=str(h)) for h in HEIGHT_BANDS],
        fontsize="x-small",
    )

    if out_file is None:
        base = os.path.splitext(config)[0] if isinstance(config, str) else "config"
        out_file = base + ".svg"
    fig.savefig(out_file)
    plt.close(fig)
    log.info("wrote %s", out_file)
    return all_warnings


def _first_media_quality(data: dict, hrc_id: str) -> Optional[tuple[str, dict]]:
    """(quality-level id, quality-level dict) of the HRC's first media event."""
    for event_id, _dur in data["hrcList"][hrc_id]["eventList"]:
        if event_id not in _STALL_IDS:
            return event_id, data["qualityLevelList"][event_id]
    return None


def plot_short(
    config: Any, out_file: Optional[str] = None, codec_wise: bool = False
) -> list[str]:
    """Height-vs-bitrate design scatter; returns the written file paths."""
    import matplotlib

    matplotlib.use("svg")
    import matplotlib.pyplot as plt
    import numpy as np

    data = _load_config_data(config)
    log = get_logger()
    if out_file is not None:
        base = os.path.splitext(out_file)[0]
    elif isinstance(config, str):
        base = os.path.splitext(config)[0]
    else:
        base = "config"

    warned_levels: set = set()

    def first_bitrate(ql_id: str, ql: dict) -> Optional[float]:
        # CRF/QP-coded quality levels have no videoBitrate; the reference
        # hard-KeyErrors on them (test_config.py:1481 via plot_config_
        # short.py:94) — here they are skipped, warned once per level
        if "videoBitrate" not in ql:
            if ql_id not in warned_levels:
                warned_levels.add(ql_id)
                log.warning(
                    "quality level %s has no videoBitrate (CRF/QP-coded), "
                    "skipping in bitrate plot", ql_id,
                )
            return None
        return float(str(ql["videoBitrate"]).split("/")[0])

    written: list[str] = []
    if codec_wise:
        codecs = ("vp9", "h264", "h265")
        by_codec: dict[str, tuple[list, list]] = {c: ([], []) for c in codecs}
        for hrc_id in data["hrcList"]:
            found = _first_media_quality(data, hrc_id)
            if found is None:
                continue
            ql_id, ql = found
            codec = ql.get("videoCodec", "h264")
            if codec not in by_codec:
                log.warning("unexpected video codec %s, ignoring", codec)
                continue
            rate = first_bitrate(ql_id, ql)
            if rate is None:
                continue
            by_codec[codec][0].append(ql["height"])
            by_codec[codec][1].append(rate)
        for codec in codecs:
            heights, bitrates = by_codec[codec]
            fig = plt.figure(figsize=(10, 10))
            ax = fig.add_subplot(111)
            ax.set_xticks([120, 240, 360, 480, 720, 1080, 2160])
            ax.scatter(heights, bitrates)
            ax.set_xlabel("frame height")
            ax.set_ylabel("bitrate in kbit/s")
            ax.grid(True)
            ax.set_title(codec)
            path = f"{base}_datarate-resolution_plot_{codec}.svg"
            fig.savefig(path)
            plt.close(fig)
            written.append(path)
            log.info("wrote %s", path)
        return written

    # single scatter on sqrt(height) / log(bitrate) axes (reference :62-100)
    fig = plt.figure(figsize=(10, 10))
    ax = fig.add_subplot(111)
    x_t = np.array([120, 240, 360, 480, 720, 1080, 2160])
    y_t = np.array([10.0 ** i for i in range(2, 6)])
    ax.set_xticks(np.sqrt(x_t))
    ax.set_xticklabels(x_t)
    ax.set_yticks(np.log(y_t))
    ax.set_yticklabels([int(y) for y in y_t])
    ax.set_xlim([math.sqrt(x_t[0]), math.sqrt(x_t[-1])])
    ax.set_ylim([math.log(y_t[0]), math.log(y_t[-1])])
    for hrc_id in data["hrcList"]:
        found = _first_media_quality(data, hrc_id)
        if found is None:
            continue
        ql_id, ql = found
        rate = first_bitrate(ql_id, ql)
        if rate is None:
            continue
        ax.scatter([math.sqrt(ql["height"])], [math.log(rate)], color="red")
    ax.set_xlabel("frame height")
    ax.set_ylabel("bitrate in kbit/s")
    path = out_file or base + ".svg"
    fig.savefig(path)
    plt.close(fig)
    log.info("wrote %s", path)
    return [path]


def build_parser(parser: Optional[argparse.ArgumentParser] = None) -> argparse.ArgumentParser:
    p = parser or argparse.ArgumentParser("plots", description="Database design plots")
    p.add_argument("config", help="database YAML file")
    p.add_argument("--kind", choices=("long", "short"), default="long",
                   help="timeline (long) or bitrate/resolution scatter (short)")
    p.add_argument("--codec-wise", action="store_true",
                   help="short only: one scatter per codec")
    p.add_argument("-o", "--output", default=None, help="output SVG path")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.kind == "long":
        plot_long(args.config, args.output)
    else:
        plot_short(args.config, args.output, codec_wise=args.codec_wise)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
