"""`tools priors`: operator surface for codec-prior extraction.

    tools priors extract -i SRC... [--store DIR] [--force] [--json]
    tools priors show <clip | clip.priors.npz>

`extract` streams MV/QP/frame-type coding metadata out of each input's
existing bitstream (docs/PRIORS.md), writes the `.priors.npz` sidecar
next to it, and commits it to the artifact store when one is configured
— a warm re-run plans ZERO extraction jobs (the CI `priors-smoke` gate).
`show` prints a sidecar digest: frame-type histogram, QP stats, MV
coverage and the derived temporal features.
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Optional, Sequence

from ..store import runtime as store_runtime
from ..utils.log import get_logger


def _extract(args) -> int:
    from .. import priors

    store_runtime.configure_from_args(args)
    out = {
        "files": 0, "extracted": 0, "cache_hits": 0,
        "frames": 0, "mvs": 0, "sidecars": [],
    }
    for src in args.input:
        data, hit = priors.ensure_priors(src, force=args.force,
                                         threads=args.threads)
        out["files"] += 1
        out["cache_hits" if hit else "extracted"] += 1
        out["frames"] += data.n_frames
        out["mvs"] += data.n_mvs
        out["sidecars"].append(priors.sidecar_path(src))
        if not args.as_json:
            s = data.summary()
            get_logger().info(
                "%s: %d frames (%s), %d MVs, qp_mean=%s -> %s",
                os.path.basename(src), s["frames"],
                f"I{s['i_frames']}/P{s['p_frames']}/B{s['b_frames']}",
                s["mvs"], s["qp_mean"], priors.sidecar_path(src),
            )
    if args.as_json:
        print(json.dumps(out))
    else:
        get_logger().info(
            "priors: %d files, %d extracted, %d warm hits",
            out["files"], out["extracted"], out["cache_hits"],
        )
    return 0


def _show(args) -> int:
    from .. import priors
    from ..priors import features

    store_runtime.configure_from_args(args)
    path = args.file
    if path.endswith(priors.SIDECAR_SUFFIX):
        data = priors.load_priors(path)
    else:
        # ensure_priors, not a bare extract: a repeat `show` on the same
        # clip is a sidecar/store hit instead of another full decode
        data, _ = priors.ensure_priors(path)
    doc = data.summary()
    feats = features.temporal_features(data)
    mv_sel = feats["mv_count"] > 0
    doc["features"] = {
        "mean_mag": round(float(feats["mean_mag"][mv_sel].mean()), 4)
        if mv_sel.any() else None,
        "p95_mag": round(float(feats["p95_mag"][mv_sel].mean()), 4)
        if mv_sel.any() else None,
        "divergence": round(float(feats["divergence"][mv_sel].mean()), 4)
        if mv_sel.any() else None,
        "intra_fraction": round(float(feats["intra_fraction"].mean()), 4),
    }
    print(json.dumps(doc, indent=1))
    return 0


def build_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    p = parser or argparse.ArgumentParser(
        "priors", description="Extract/inspect codec priors (docs/PRIORS.md)"
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ext = sub.add_parser("extract", help="extract sidecars (store-cached)")
    ext.add_argument("-i", "--input", required=True, nargs="+",
                     help="input media files")
    ext.add_argument("-f", "--force", action="store_true",
                     help="re-extract even when cached")
    ext.add_argument("--threads", type=int, default=0,
                     help="decoder threads (0 = auto)")
    ext.add_argument("--store", default=None, metavar="DIR",
                     help="artifact store root (default: PC_STORE_DIR)")
    ext.add_argument("--no-store", action="store_true",
                     help="disable the artifact store")
    ext.add_argument("--json", action="store_true", dest="as_json",
                     help="machine-readable summary on stdout")
    ext.set_defaults(fn=_extract)
    show = sub.add_parser("show", help="print a sidecar digest")
    show.add_argument("file", help="a clip or its .priors.npz sidecar")
    show.add_argument("--store", default=None, metavar="DIR",
                      help="artifact store root (default: PC_STORE_DIR)")
    show.add_argument("--no-store", action="store_true",
                      help="disable the artifact store")
    show.set_defaults(fn=_show)
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
