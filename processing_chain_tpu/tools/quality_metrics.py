"""Full-reference quality metrics tool: per-frame PSNR / SSIM / SI / TI of a
PVS's AVPVS against its SRC, computed on device.

Fills the role of the libvmaf build the reference carries but never invokes
(reference Dockerfile:38-43, install_ffmpeg.sh:61 — `--enable-libvmaf`
compiled into ffmpeg, no chain code calls it): pixel-model features over the
AVPVS artifacts (BASELINE.json config 4). Where vmaf is a CPU filter over
decoded frames, here both clips stream through the decode-prefetch pipeline
and every metric is a vmapped device kernel (ops/metrics, ops/siti).

Output: `<sideInfo>/<pvs_id>.metrics.csv` with one row per AVPVS frame:
frame, psnr_y, psnr_u, psnr_v, ssim_y, si, ti. Identical frames give
100 dB PSNR (ops/metrics clamps instead of emitting inf, so the CSV stays
finite and averageable). 10-bit planes are normalized to the 8-bit scale
before comparison, so mixed-depth AVPVS-vs-SRC pairs score correctly.

CLI: `python -m processing_chain_tpu tools metrics -c DB/DB.yaml
[--filter-pvs …] [-p N] [-f]`.
"""

from __future__ import annotations

import argparse
import functools
import os
from typing import Iterator, Optional, Sequence

import numpy as np

from ..config import TestConfig
from ..config.domain import Pvs
from ..engine import prefetch as pf
from ..io import medialib
from ..io.video import VideoReader
from ..ops import metrics as metrics_ops
from ..ops import resize as resize_ops
from ..ops import siti as siti_ops
from ..utils import tracing
from ..utils.log import get_logger

CHUNK = 32


@functools.lru_cache(maxsize=4)
def _metrics_mesh_step(devs: tuple):
    """(mesh, jitted sharded step), cached per device set: rebuilding the
    shard_map closure per chunk would retrace+recompile every CHUNK
    frames. Metrics are frame-local (no halo), so time_parallel stays 1 —
    a (pvs=N, time=1) mesh is pure frame parallelism."""
    from ..parallel import make_batch_metrics_step, make_mesh

    mesh = make_mesh(list(devs))
    return mesh, make_batch_metrics_step(mesh)


def _metric_frames(ry, dy, ru, du, rv, dv, with_ssim: bool = True):
    """Per-frame PSNR(Y/U/V) + SSIM(Y) of one chunk — on a multi-device
    mesh the frame axis is sharded through parallel.make_batch_metrics_step
    (frames are independent, so the mesh acts as pure frame parallelism
    for this tool; BASELINE config 4); single device runs the vmapped
    kernels directly."""
    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    t = ry.shape[0]
    if len(devs) > 1 and t >= len(devs):
        from ..parallel.mesh import batch_sharding

        mesh, step = _metrics_mesh_step(tuple(devs))
        b = mesh.shape["pvs"]
        pad = (-t) % b

        def shard(p):
            if pad:
                p = jnp.concatenate([p, jnp.repeat(p[-1:], pad, axis=0)])
            p = p.reshape((b, (t + pad) // b) + p.shape[1:])
            return jax.device_put(p, batch_sharding(mesh))

        # Y (the expensive plane: SSIM windows) rides the mesh; chroma
        # PSNR is cheap and frame-local, computed alongside. (The mesh
        # step computes SSIM fused with PSNR regardless of with_ssim; the
        # flag only spares the single-device path.)
        psnr_y, ssim_y = step(shard(ry), shard(dy))
        return {
            "psnr_y": np.asarray(psnr_y).reshape(-1)[:t],
            "ssim_y": np.asarray(ssim_y).reshape(-1)[:t],
            "psnr_u": np.asarray(metrics_ops.psnr_frames(ru, du)),
            "psnr_v": np.asarray(metrics_ops.psnr_frames(rv, dv)),
        }
    out = {
        "psnr_y": np.asarray(metrics_ops.psnr_frames(ry, dy)),
        "psnr_u": np.asarray(metrics_ops.psnr_frames(ru, du)),
        "psnr_v": np.asarray(metrics_ops.psnr_frames(rv, dv)),
    }
    if with_ssim:
        out["ssim_y"] = np.asarray(metrics_ops.ssim_frames(ry, dy))
    return out


def _src_index_map(pvs, rate: float, src_fps: float):
    """out_index(k): SRC frame index aligned to AVPVS output frame k.

    Without buffering (or with frame-freeze HRCs, whose AVPVS keeps the
    original length) the AVPVS timeline IS the SRC timeline. With stall
    events, apply_stalling inserted round(d*rate) frames per event, so the
    played media time of output k comes from the same StallPlan the
    renderer used: during a stall the SRC holds the last played frame —
    the honest full-reference comparison for a frozen/spinner period."""
    has_buffering = getattr(pvs, "has_buffering", lambda: False)()
    has_freeze = getattr(pvs, "has_framefreeze", lambda: False)()
    if not has_buffering or has_freeze:
        return lambda k: int(np.floor(k / rate * src_fps + 0.5))

    from ..ops import overlay as ov

    events = pvs.get_buff_events_media_time()
    # played-frame count from the ACTUAL rendered file, exactly as the
    # renderer saw it: n_played = avpvs frames − inserted stall frames
    # (apply_stalling built its plan from the wo_buffer frame count, so a
    # duration-based estimate can drift by a frame on fps-converted PVSes)
    avpvs_path = pvs.get_avpvs_file_path()
    vstreams = [
        s for s in medialib.probe(avpvs_path)["streams"]
        if s["codec_type"] == "video"
    ]
    n_avpvs = int(vstreams[0].get("nb_frames") or 0) if vstreams else 0
    if n_avpvs <= 0:
        n_avpvs = len(medialib.scan_packets(avpvs_path, "video")["size"])
    n_stall = sum(int(round(float(e[1]) * rate)) for e in events)
    plan = ov.plan_stalling(max(n_avpvs - n_stall, 1), rate, events)
    src_idx = plan.src_idx  # played-frame index per output frame

    def out_index(k: int) -> int:
        j = src_idx[min(k, len(src_idx) - 1)]
        return int(np.floor(j / rate * src_fps + 0.5))

    return out_index


def _paired_chunks(
    deg: VideoReader, ref: VideoReader, out_index, chunk: int = CHUNK
) -> Iterator[tuple[list[np.ndarray], list[np.ndarray]]]:
    """Yield ((deg_y, deg_u, deg_v), (ref_y, ref_u, ref_v)) chunk pairs on
    the AVPVS timeline: SRC frame for output k is out_index(k) (monotonic
    → single streaming decode of both clips)."""
    deg_it = pf.iter_plane_chunks(deg, chunk)
    # n_out unknown up front (follow the AVPVS stream); gather the SRC
    # lazily and stop when the AVPVS side ends
    ref_it = pf.stream_monotonic_gather(
        ref,
        out_index,
        10**9,  # effectively unbounded; the AVPVS side stops us
        chunk,
    )
    for deg_chunk in deg_it:
        ref_chunk = next(ref_it, None)
        if ref_chunk is None:
            break
        n = min(deg_chunk[0].shape[0], ref_chunk[0].shape[0])
        yield (
            [p[:n] for p in deg_chunk],
            [p[:n] for p in ref_chunk],
        )


@functools.lru_cache(maxsize=1)
def _vif_windows() -> tuple:
    """Normalized 1-D Gaussian windows per VIF scale (N = 17/9/5/3,
    sd = N/5 — the pixel-domain VIF constants, Sheikh & Bovik 2006 /
    VMAF's vif feature). Window construction shared with SSIM's
    (ops/metrics._gaussian_kernel)."""
    return tuple(
        np.asarray(
            metrics_ops._gaussian_kernel(n, n / 5.0), np.float32
        )
        for n in (17, 9, 5, 3)
    )


def _conv_valid(x, w):
    """Separable VALID 2-D convolution of [T, H, W] frames with a 1-D
    window (symmetric, so convolution == correlation)."""
    import jax

    k = w.shape[0]
    nchw = ("NCHW", "OIHW", "NCHW")
    y = jax.lax.conv_general_dilated(
        x[:, None], w.reshape(1, 1, k, 1), (1, 1), "VALID",
        dimension_numbers=nchw,
    )
    y = jax.lax.conv_general_dilated(
        y, w.reshape(1, 1, 1, k), (1, 1), "VALID", dimension_numbers=nchw,
    )
    return y[:, 0]


@functools.lru_cache(maxsize=1)
def _vif_impl():
    """Module-cached jitted VIF body: per-call jit would re-trace and
    recompile the 4-scale conv pipeline every CHUNK frames (the hazard
    _metrics_mesh_step documents)."""
    import jax
    import jax.numpy as jnp

    wins = _vif_windows()  # built OUTSIDE the trace (concrete constants)

    @jax.jit
    def impl(r, d):
        sigma_nsq = 2.0
        eps = 1e-10
        num = jnp.zeros(r.shape[0], jnp.float32)
        den = jnp.zeros(r.shape[0], jnp.float32)
        for scale, w_np in enumerate(wins, start=1):
            w = jnp.asarray(w_np)
            if scale > 1:
                r = _conv_valid(r, w)[:, ::2, ::2]
                d = _conv_valid(d, w)[:, ::2, ::2]
            mu1 = _conv_valid(r, w)
            mu2 = _conv_valid(d, w)
            mu1_sq, mu2_sq, mu1_mu2 = mu1 * mu1, mu2 * mu2, mu1 * mu2
            sigma1_sq = _conv_valid(r * r, w) - mu1_sq
            sigma2_sq = _conv_valid(d * d, w) - mu2_sq
            sigma12 = _conv_valid(r * d, w) - mu1_mu2
            sigma1_sq = jnp.maximum(sigma1_sq, 0.0)
            sigma2_sq = jnp.maximum(sigma2_sq, 0.0)

            g = sigma12 / (sigma1_sq + eps)
            sv_sq = sigma2_sq - g * sigma12
            # reference implementation's edge fixups (vifp_mscale)
            g = jnp.where(sigma1_sq < eps, 0.0, g)
            sv_sq = jnp.where(sigma1_sq < eps, sigma2_sq, sv_sq)
            sigma1_sq = jnp.where(sigma1_sq < eps, 0.0, sigma1_sq)
            g = jnp.where(sigma2_sq < eps, 0.0, g)
            sv_sq = jnp.where(sigma2_sq < eps, 0.0, sv_sq)
            sv_sq = jnp.where(g < 0.0, sigma2_sq, sv_sq)
            g = jnp.maximum(g, 0.0)
            sv_sq = jnp.maximum(sv_sq, eps)

            num = num + jnp.sum(
                jnp.log10(1.0 + g * g * sigma1_sq / (sv_sq + sigma_nsq)),
                axis=(1, 2),
            )
            den = den + jnp.sum(
                jnp.log10(1.0 + sigma1_sq / sigma_nsq), axis=(1, 2)
            )
        return num / jnp.maximum(den, eps)

    return impl


def _vif_frames(ref, deg):
    """Per-frame pixel-domain VIF (vifp multi-scale) of [T, H, W] luma on
    the 8-bit scale — the VMAF-family fidelity feature the reference's
    libvmaf build would supply if anything invoked it. Frames must be
    >= 41 px per side for the 4-scale pyramid (VALID convs + ::2
    decimation per scale).

    NOTE: device kernel placed in this tool (not ops/metrics) so it can
    land while ops/ is frozen by the live-bench code-hash guard
    (BENCH_LIVE.json); migrate next to msssim_frames at the next safe
    ops/ change."""
    return _vif_impl()(ref, deg)


def compute_pvs_metrics(
    pvs: Pvs, force: bool = False, out_dir: Optional[str] = None,
    use_sidecar: bool = True, msssim: bool = False, vif: bool = False,
) -> Optional[str]:
    """Write `<pvs_id>.metrics.csv`; returns the path (None if skipped).

    When the p03 device pass left a per-frame SI/TI sidecar next to the
    AVPVS (models/avpvs.SiTiAccumulator — the north star's "consume
    device-side feature tensors instead of reparsing files"), those
    columns are reused instead of recomputed; PSNR/SSIM always need the
    SRC comparison and are computed regardless. A buffered PVS's final
    AVPVS has no sidecar (the sidecar describes the pre-stall render), so
    it computes everything — path-keyed lookup handles that naturally."""
    import jax.numpy as jnp
    import pandas as pd

    tc = pvs.test_config
    avpvs_path = pvs.get_avpvs_file_path()
    if not os.path.isfile(avpvs_path):
        raise medialib.MediaError(
            f"AVPVS for {pvs.pvs_id} does not exist — run p03 first: {avpvs_path}"
        )
    out_dir = out_dir or tc.get_side_information_path()
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, pvs.pvs_id + ".metrics.csv")
    if os.path.isfile(out_path) and not force:
        get_logger().warning(
            "file %s already exists, not overwriting. Use -f/--force to "
            "force overwriting", out_path,
        )
        return None

    from ..models.avpvs import siti_sidecar_path

    sidecar = None
    if use_sidecar:
        sc_path = siti_sidecar_path(avpvs_path)
        if os.path.isfile(sc_path):
            try:
                sidecar = np.atleast_1d(
                    np.genfromtxt(sc_path, delimiter=",", names=True)
                )
            except ValueError:
                get_logger().warning(
                    "%s: unreadable SI/TI sidecar; recomputing features "
                    "inline", pvs.pvs_id,
                )
            else:
                # validate BEFORE the expensive pass: sidecar rows must
                # cover the AVPVS's frames (cheap packet scan — FFV1 is
                # intra-only, one packet per frame). The paired metrics
                # table may be SHORTER (SRC ends first); sidecar[:n]
                # aligns exactly in that case.
                n_deg = len(medialib.scan_packets(avpvs_path, "video")["size"])
                if len(sidecar) != n_deg:
                    get_logger().warning(
                        "%s: SI/TI sidecar has %d rows for %d AVPVS "
                        "frames; recomputing features inline",
                        pvs.pvs_id, len(sidecar), n_deg,
                    )
                    sidecar = None
                else:
                    get_logger().debug(
                        "reusing device features from %s", sc_path
                    )

    # declarative column order so it is stable across flag combinations
    # (msssim_y always before vif_y, both between ssim_y and si)
    cols = (
        ["psnr_y", "psnr_u", "psnr_v", "ssim_y"]
        + (["msssim_y"] if msssim else [])
        + (["vif_y"] if vif else [])
        + ["si", "ti"]
    )
    rows = {k: [] for k in cols}
    prev_last = None  # last deg luma of the previous chunk (TI continuity)
    with tracing.span(f"metrics {pvs.pvs_id}"), VideoReader(
        avpvs_path
    ) as deg_reader, VideoReader(pvs.src.file_path) as ref_reader:
        dh, dw = deg_reader.height, deg_reader.width
        # 10-bit planes decode as uint16 in 0..1023: bring both clips onto
        # the 8-bit scale so peak=255 PSNR and SSIM constants are correct
        # for every depth pairing
        deg_scale = 0.25 if deg_reader.dtype == np.uint16 else 1.0
        ref_scale = 0.25 if ref_reader.dtype == np.uint16 else 1.0
        out_index = _src_index_map(pvs, deg_reader.fps, ref_reader.fps)
        with pf.Prefetcher(
            _paired_chunks(deg_reader, ref_reader, out_index), depth=2
        ) as pre:
            for deg_chunk, ref_chunk in pre:
                dy = jnp.asarray(deg_chunk[0]).astype(jnp.float32) * deg_scale
                du = jnp.asarray(deg_chunk[1]).astype(jnp.float32) * deg_scale
                dv = jnp.asarray(deg_chunk[2]).astype(jnp.float32) * deg_scale
                # SRC on the AVPVS grid (device resize when dims differ)
                ry = resize_ops.resize_frames(
                    jnp.asarray(ref_chunk[0]).astype(jnp.float32) * ref_scale,
                    dh, dw, "bicubic",
                )
                ru = resize_ops.resize_frames(
                    jnp.asarray(ref_chunk[1]).astype(jnp.float32) * ref_scale,
                    du.shape[-2], du.shape[-1], "bicubic",
                )
                rv = resize_ops.resize_frames(
                    jnp.asarray(ref_chunk[2]).astype(jnp.float32) * ref_scale,
                    dv.shape[-2], dv.shape[-1], "bicubic",
                )

                chunk_metrics = _metric_frames(
                    ry, dy, ru, du, rv, dv,
                    with_ssim=not msssim,
                )
                if msssim:
                    # opt-in (--msssim): frame-local, no mesh plumbing.
                    # The combined kernel also yields plain SSIM from its
                    # scale-1 pass, so the full-res filtering runs once.
                    ms, s1 = metrics_ops.msssim_ssim_frames(ry, dy)
                    chunk_metrics["msssim_y"] = np.asarray(ms)
                    chunk_metrics.setdefault("ssim_y", np.asarray(s1))
                if vif:
                    chunk_metrics["vif_y"] = np.asarray(_vif_frames(ry, dy))
                for k, vals in chunk_metrics.items():
                    rows[k].append(vals)
                if sidecar is None:
                    rows["si"].append(np.asarray(siti_ops.si_frames(dy)))
                    ti, prev_last = siti_ops.ti_frames_continued(dy, prev_last)
                    rows["ti"].append(np.asarray(ti))

    if sidecar is not None:
        n_paired = sum(len(r) for r in rows["psnr_y"])
        # SI/TI are stds of linear functions of the luma: the sidecar's
        # container-depth values scale exactly by deg_scale onto the
        # 8-bit scale the metrics table uses
        rows["si"] = [sidecar["si"][:n_paired] * deg_scale]
        rows["ti"] = [sidecar["ti"][:n_paired] * deg_scale]

    table = {k: np.concatenate(v) if v else np.empty(0) for k, v in rows.items()}
    n = len(table["psnr_y"])
    df = pd.DataFrame({"frame": np.arange(n), **table})
    df.to_csv(out_path, index=False, float_format="%.5f")
    get_logger().info("wrote %s (%d frames)", out_path, n)
    return out_path


def run(
    config_path: str,
    filter_pvses: Optional[str] = None,
    force: bool = False,
    prober=None,
    msssim: bool = False,
    vif: bool = False,
) -> list[str]:
    tc = TestConfig(config_path, filter_pvses=filter_pvses, prober=prober)
    written = []
    for pvs in tc.pvses.values():
        path = compute_pvs_metrics(pvs, force=force, msssim=msssim, vif=vif)
        if path:
            written.append(path)
    return written


def build_parser(
    parser: Optional[argparse.ArgumentParser] = None,
) -> argparse.ArgumentParser:
    parser = parser or argparse.ArgumentParser(
        description="per-frame PSNR/SSIM/SI/TI of AVPVS files vs their SRC"
    )
    parser.add_argument("-c", "--test-config", required=True)
    parser.add_argument("-f", "--force", action="store_true")
    parser.add_argument("--filter-pvs", help="only these PVS-IDs ('|'-separated)")
    parser.add_argument(
        "--msssim", action="store_true",
        help="add a per-frame multi-scale SSIM column (frames must be "
        ">=176 px per side for the 5-scale pyramid)",
    )
    parser.add_argument(
        "--vif", action="store_true",
        help="add a per-frame pixel-domain VIF column (the VMAF-family "
        "fidelity feature; frames must be >=41 px per side for the "
        "4-scale pyramid)",
    )
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    run(args.test_config, filter_pvses=args.filter_pvs, force=args.force,
        msssim=args.msssim, vif=args.vif)
    return 0
