"""`tools queue-crashcheck` — crash-consistency proof for the serve queue.

The DurableQueue's whole promise is that a daemon can die at ANY disk
boundary and a restart reaches a sane state (docs/SERVE.md). This
harness makes that promise exhaustive instead of anecdotal: it runs a
scripted queue workload that exercises every transition of the declared
state machine (serve/queue.py STATES/TRANSITIONS), counts the
`fsio.atomic_write_json` boundaries it crosses, then replays the
workload once per boundary × crash mode:

  * ``before`` — the process dies with the write NOT on disk (the
    os.replace never happened);
  * ``after``  — the process dies the instant the write landed (nothing
    after the replace executed).

Each injected death abandons the in-memory queue (exactly what SIGKILL
does), reopens a fresh ``DurableQueue`` on the same root, and asserts
the recovered world:

  * every record's state is a DECLARED state, and never ``running`` —
    recovery must requeue interrupted executions, not strand them;
  * no ``.inprogress`` sentinel survives recovery;
  * the in-memory queued index matches the records' states exactly;
  * the queue still DRAINS: claiming and completing everything queued
    leaves every record terminal (no stuck work).

Exit 0 with a one-line JSON summary on success; exit 1 listing every
violated fault point otherwise. ``--render-table`` prints the markdown
transition table docs/SERVE.md embeds (the single declared source).

The pytest lane (tests/test_queue_crashcheck.py) runs the same harness
in-process; the CI ``queue-crashcheck`` step gates serve merges on it.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from typing import Optional, Sequence

from ..serve import queue as queue_mod
from ..serve.queue import STATES, TERMINAL, TRANSITIONS, DurableQueue
from ..utils.log import get_logger


class _InjectedCrash(BaseException):
    """Simulated process death: BaseException so no handler in the
    queue's own code can swallow it (mirroring what SIGKILL 'catches')."""


class _FaultyWriter:
    """Wraps atomic_write_json: counts boundaries, dies at one of them."""

    def __init__(self, real, fault_at: Optional[int] = None,
                 mode: str = "before") -> None:
        self.real = real
        self.fault_at = fault_at
        self.mode = mode
        self.count = 0

    def __call__(self, path, obj, **kw):
        self.count += 1
        if self.fault_at is not None and self.count == self.fault_at:
            if self.mode == "before":
                raise _InjectedCrash(f"died before write #{self.count}")
            self.real(path, obj, **kw)
            raise _InjectedCrash(f"died after write #{self.count}")
        self.real(path, obj, **kw)


def _unit(n: int) -> dict:
    return {"database": "DB", "src": f"SRC{n:03d}", "hrc": "HRC000",
            "params": {}, "pvs_id": f"DB_SRC{n:03d}_HRC000"}


def _scenario(q: DurableQueue) -> None:
    """Exercise every declared edge: enqueue/attach, claim, complete,
    retry-requeue, terminal fail, failed re-arm, done re-arm (eviction),
    permanent-failure quarantine + operator re-arm, an expired-lease
    steal with the loser's settle fenced, and a final drain. Helper
    replica handles are CLOSED in the finally (an injected crash kills
    the whole process — their in-process liveness must die with it)."""
    peer = DurableQueue(q.root, replica=f"peer-{os.path.basename(q.root)}",
                        lease_s=0.05)
    try:
        r1, _ = q.enqueue("p1", {"op": "t", "n": 1}, _unit(1), "t0",
                          "normal", "req-a", "o1.bin")
        r2, _ = q.enqueue("p2", {"op": "t", "n": 2}, _unit(2), "t0",
                          "normal", "req-a", "o2.bin")
        r3, _ = q.enqueue("p3", {"op": "t", "n": 3}, _unit(3), "t1",
                          "high", "req-b", "o3.bin")
        q.enqueue("p1", {"op": "t", "n": 1}, _unit(1), "t2", "normal",
                  "req-c", "o1.bin")                        # attach
        q.claim([r1.job_id, r2.job_id])                     # queued -> running
        q.complete(r1.job_id)                               # running -> done
        q.fail(r2.job_id, "boom", requeue=True)             # running -> queued
        q.claim([r2.job_id])
        q.fail(r2.job_id, "boom again", requeue=False)      # running -> failed
        q.enqueue("p2", {"op": "t", "n": 2}, _unit(2), "t0", "normal",
                  "req-d", "o2.bin")                        # failed -> queued
        q.rearm(r1.job_id)                                  # done -> queued
        # permanent-failure taxonomy: quarantine, then operator re-arm
        r4, _ = q.enqueue("p4", {"op": "t", "n": 4}, _unit(4), "t0",
                          "normal", "req-e", "o4.bin")
        q.claim([r4.job_id])
        q.quarantine(r4.job_id, "bad params")       # running -> quarantined
        q.rearm(r4.job_id)                          # quarantined -> queued
        # lease fencing: the peer claims r4, its lease expires (0.05 s,
        # no heartbeat), q steals it back, and the peer's settle is
        # REFUSED by the epoch fence
        peer.poll()
        assert peer.claim([r4.job_id]), "peer could not claim r4"
        time.sleep(0.12)                            # outlive the lease
        stolen = q.poll()["stolen"]                 # running -> queued (steal)
        assert stolen >= 1, "expired lease was not stolen"
        fenced = peer.complete(r4.job_id)
        assert fenced is None, "fenced settle was accepted"
        # poison-SRC quarantine: a QUEUED record carrying a poisoned
        # content digest is swept through the declared poison edge
        # (fault-injecting the registry write + the swept persist),
        # then the operator re-arm unparks it for the drain
        digest = "d" * 64
        r5, _ = q.enqueue("p5", {"op": "t", "n": 5}, _unit(5), "t0",
                          "normal", "req-f", "o5.bin", src_digest=digest)
        swept = q.poison_src(digest, src="SRC005",
                             error="hostile bytes",
                             by_job=r5.job_id)  # queued -> quarantined
        assert any(r.job_id == r5.job_id for r in swept), \
            "poison sweep missed the queued record carrying the digest"
        q.rearm_src(digest)                     # quarantined -> queued
        # drain whatever is queued now
        queued = [r.job_id for r in q.queued_snapshot()]
        for rec in q.claim(queued):
            q.complete(rec.job_id)
        # r3 may still be queued if the drain claimed it already —
        # complete anything left so the baseline run ends terminal
        for rec in q.claim([r3.job_id]):
            q.complete(rec.job_id)
    finally:
        peer.close()


def _seed_interrupted_root(root: str) -> None:
    """A root as a SIGKILLed daemon leaves it: one record persisted as
    'running' with its lease down — recovery must requeue it (the
    recovery-path atomic writes are fault-injected when DurableQueue
    opens this root). close() without settling is the faithful kill:
    the process's liveness dies, the on-disk record/lease stay."""
    q = DurableQueue(root)
    rec, _ = q.enqueue("pr", {"op": "t", "n": 9}, _unit(9), "t0", "normal",
                       "req-r", "o9.bin")
    q.claim([rec.job_id])
    q.close()


def _check_recovered(root: str, violations: list, where: str) -> None:
    q = DurableQueue(root)
    try:
        with q._lock:
            records = dict(q._jobs)
            queued_idx = set(q._queued)
        for job_id, rec in records.items():
            if rec.state not in STATES:
                violations.append(
                    f"{where}: {job_id} recovered into undeclared state "
                    f"{rec.state!r}")
            if rec.state == "running":
                # every owner in these roots is dead (closed), so a
                # running record after recovery is stranded — a LIVE
                # peer's lease is the only legitimate keeper
                violations.append(
                    f"{where}: {job_id} stranded in 'running' after "
                    "recovery")
            if os.path.isfile(q._sentinel_path(job_id)):
                violations.append(
                    f"{where}: {job_id} lease survived recovery")
            if (rec.state == "queued") != (job_id in queued_idx):
                violations.append(
                    f"{where}: {job_id} state {rec.state!r} disagrees "
                    "with the queued index")
        # the recovered queue must still drain to terminal states
        for _ in range(len(records) + 1):
            claimable = [r.job_id for r in q.queued_snapshot()]
            if not claimable:
                break
            for rec in q.claim(claimable):
                q.complete(rec.job_id)
        with q._lock:
            stuck = [
                (job_id, rec.state) for job_id, rec in q._jobs.items()
                if rec.state not in TERMINAL
            ]
        if stuck:
            violations.append(f"{where}: records stuck after drain: {stuck}")
        # settle forensics: a terminal record's settled epoch must be
        # the epoch the settling owner actually held — an accepted
        # stale-epoch settle (a fenced zombie slipping through) shows
        # up as a mismatch here
        with q._lock:
            for job_id, rec in q._jobs.items():
                if rec.state in TERMINAL and \
                        rec.settled_epoch is not None and \
                        rec.settled_epoch != rec.epoch:
                    violations.append(
                        f"{where}: {job_id} settled under epoch "
                        f"{rec.settled_epoch} but owns epoch {rec.epoch} "
                        "— a fenced settle was accepted")
    finally:
        q.close()


def run_crashcheck(workdir: Optional[str] = None,
                   verbose: bool = False) -> dict:
    """Execute the full fault matrix; returns the summary dict."""
    log = get_logger()
    own_tmp = workdir is None
    base = workdir or tempfile.mkdtemp(prefix="queue-crashcheck-")
    real_writer = queue_mod.atomic_write_json
    violations: list[str] = []
    fault_points = {"scenario": 0, "recovery": 0}
    try:
        # -------- pass 0: count boundaries (no faults) ------------------
        counter = _FaultyWriter(real_writer)
        queue_mod.atomic_write_json = counter
        root = os.path.join(base, "count")
        q0 = DurableQueue(root)
        try:
            _scenario(q0)
        finally:
            q0.close()
        fault_points["scenario"] = counter.count

        rec_root = os.path.join(base, "rcount")
        _seed_interrupted_root(rec_root)
        rec_counter = _FaultyWriter(real_writer)
        queue_mod.atomic_write_json = rec_counter
        DurableQueue(rec_root).close()  # recovery pass only
        fault_points["recovery"] = rec_counter.count

        # -------- pass 1: scenario faults -------------------------------
        cases = 0
        for k in range(1, fault_points["scenario"] + 1):
            for mode in ("before", "after"):
                cases += 1
                root = os.path.join(base, f"s{k:03d}{mode[0]}")
                queue_mod.atomic_write_json = _FaultyWriter(
                    real_writer, fault_at=k, mode=mode)
                died = False
                qf = None
                try:
                    # construction is INSIDE the fault scope: opening a
                    # queue performs durable writes of its own (the
                    # replica-epoch bump), and a death there must be as
                    # recoverable as one mid-scenario
                    qf = DurableQueue(root)
                    _scenario(qf)
                except _InjectedCrash:
                    died = True
                finally:
                    # the injected death killed the whole process: its
                    # in-process liveness dies with it, the disk stays
                    if qf is not None:
                        qf.close()
                queue_mod.atomic_write_json = real_writer
                if not died:
                    violations.append(
                        f"scenario#{k}/{mode}: fault point never reached")
                    continue
                _check_recovered(root, violations, f"scenario#{k}/{mode}")
                if verbose:
                    log.info("queue-crashcheck: scenario#%d/%s ok", k, mode)

        # -------- pass 2: recovery-path faults --------------------------
        for k in range(1, fault_points["recovery"] + 1):
            for mode in ("before", "after"):
                cases += 1
                root = os.path.join(base, f"r{k:03d}{mode[0]}")
                _seed_interrupted_root(root)
                queue_mod.atomic_write_json = _FaultyWriter(
                    real_writer, fault_at=k, mode=mode)
                try:
                    DurableQueue(root).close()
                except _InjectedCrash:
                    pass
                queue_mod.atomic_write_json = real_writer
                # the daemon died AGAIN during recovery; the next restart
                # must still land every record in a declared, drainable
                # state
                _check_recovered(root, violations, f"recovery#{k}/{mode}")
                if verbose:
                    log.info("queue-crashcheck: recovery#%d/%s ok", k, mode)
    finally:
        queue_mod.atomic_write_json = real_writer
        if own_tmp:
            shutil.rmtree(base, ignore_errors=True)
    return {
        "fault_points": fault_points,
        "cases": cases,
        "transitions_declared": len(TRANSITIONS),
        "violations": violations,
        "ok": not violations,
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tools queue-crashcheck",
        description="fault-inject every serve-queue atomic-write boundary "
                    "and assert recovery reaches declared states only",
    )
    p.add_argument("--workdir", default=None,
                   help="keep fault roots here instead of a temp dir")
    p.add_argument("--render-table", action="store_true",
                   help="print the docs/SERVE.md transition table from "
                        "the declared source and exit")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(list(argv) if argv is not None else None)
    if args.render_table:
        from .chainlint.queue_transitions import load_transitions, render_table

        # parse the declaration (not the imported module): the trailing
        # comments on the TRANSITIONS entries ARE the meaning column
        print(render_table(*load_transitions(queue_mod.__file__)))
        return 0
    summary = run_crashcheck(workdir=args.workdir, verbose=args.verbose)
    print(json.dumps(summary))
    if not summary["ok"]:
        for v in summary["violations"]:
            print(f"queue-crashcheck: VIOLATION: {v}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
