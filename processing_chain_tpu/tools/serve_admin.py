"""`tools serve-admin` — operator surface for the serve quarantine.

The poison registry (docs/SERVE.md "Failure taxonomy", docs/
ROBUSTNESS.md "Quarantine & re-arm") quarantines hostile SRC uploads by
CONTENT DIGEST: one JSON entry per digest under `<root>/poison/`,
written when an execution settles with the `poison` failure kind, and
consulted at every enqueue so sibling plans fail fast fleet-wide. This
CLI is the operator's handle on it:

    python -m processing_chain_tpu tools serve-admin \
        --root DIR poison ls                # every registry entry
    python -m processing_chain_tpu tools serve-admin \
        --root DIR poison show DIGEST       # one entry, full forensics
    python -m processing_chain_tpu tools serve-admin \
        --root DIR poison rearm DIGEST      # drop entry, re-arm records

`rearm` drops the registry entry and re-arms every quarantined record
carrying the digest (fresh attempts budget) — the step after replacing
or repairing a convicted upload. If the bytes are still hostile, the
next execution re-convicts the digest; nothing is lost by re-arming.

All subcommands operate on the shared serve ROOT over the same durable
queue surface the replicas use (flock-serialized), so they are safe to
run against a live fleet.
"""

from __future__ import annotations

import argparse
import json
from typing import Optional, Sequence

from ..utils.log import get_logger


class _QueueHandle:
    """Scoped operator handle on the shared durable queue: opened like
    any replica (recovery + liveness claims), ALWAYS closed so the
    admin's transient identity never pins stale liveness."""

    def __init__(self, root: str) -> None:
        self._root = root

    def __enter__(self):
        import os

        from ..serve.queue import DurableQueue

        self._q = DurableQueue(os.path.join(self._root, "queue"),
                               replica="serve-admin")
        return self._q

    def __exit__(self, *exc) -> None:
        self._q.close()


def poison_ls(args) -> int:
    with _QueueHandle(args.root) as q:
        entries = q.poisoned_digests()
    print(json.dumps({"poisoned": entries, "count": len(entries)},
                     sort_keys=True))
    return 0


def poison_show(args) -> int:
    with _QueueHandle(args.root) as q:
        entry = q.src_poisoned(args.digest)
    if entry is None:
        get_logger().error("serve-admin: digest %s is not in the poison "
                           "registry", args.digest)
        return 1
    print(json.dumps(entry, sort_keys=True))
    return 0


def poison_rearm(args) -> int:
    with _QueueHandle(args.root) as q:
        result = q.rearm_src(args.digest)
    print(json.dumps(result, sort_keys=True))
    if not result["was_poisoned"]:
        get_logger().warning(
            "serve-admin: digest %s was not in the registry (re-armed "
            "%d stray quarantined record(s))", args.digest,
            len(result["rearmed"]))
    else:
        get_logger().info(
            "serve-admin: digest %s cleared; %d record(s) re-armed",
            args.digest, len(result["rearmed"]))
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tools serve-admin",
        description="operator surface for the serve poison quarantine "
                    "(docs/ROBUSTNESS.md)",
    )
    p.add_argument("--root", required=True,
                   help="the serve root shared by the replica fleet")
    sub = p.add_subparsers(dest="surface", required=True)
    poison = sub.add_parser("poison", help="the SRC-digest quarantine")
    psub = poison.add_subparsers(dest="action", required=True)
    psub.add_parser("ls", help="list every quarantined digest")
    show = psub.add_parser("show", help="one entry, full forensics")
    show.add_argument("digest")
    rearm = psub.add_parser("rearm",
                            help="drop the entry, re-arm its records")
    rearm.add_argument("digest")
    args = p.parse_args(argv)
    return {"ls": poison_ls, "show": poison_show,
            "rearm": poison_rearm}[args.action](args)


if __name__ == "__main__":
    raise SystemExit(main())
