"""`tools serve-chaos` — kill-the-replica proof for the serve fleet.

The multi-replica claims in docs/SERVE.md ("Running multiple
replicas") are statements about DEATH: leases fence ownership, peers
steal a dead replica's work, a zombie resumed after SIGSTOP cannot
settle what it lost, and none of it loses or duplicates work. This
harness makes those claims empirical: it spawns N REAL `chain-serve`
replica processes over ONE shared root (queue + store + requests),
drives an overlapping workload through them over HTTP, and — mid-wave —
SIGKILLs replicas (restarting each as a fresh generation), SIGSTOPs one
past its lease expiry (the zombie), and injects scripted execution
failures (the synthetic executor's `fail_times`/`poison` params:
transient disk-error stand-ins and permanent poison). Then it asserts
the invariants from disk, the store, and the survivors' /metrics:

  * every request reaches a terminal state (poisoned ones `failed`,
    everything else `done`) — no lost units;
  * every done unit's plan has exactly one verified artifact in the
    store (plan-hash identity keeps work exactly-once through any
    number of deaths);
  * every terminal queue record was settled under the epoch its owner
    actually held (`settledEpoch == epoch`) — an accepted fenced-zombie
    settle would break this — and no lease files survive;
  * with a zombie in the run: at least one lease was stolen
    (`chain_serve_lease_steals_total` over the survivors), proving the
    expiry/steal path actually fired;
  * quarantined records exist exactly for the poisoned plans;
  * warm-hit requests POSTed DURING the churn stay under the latency
    budget (default p50 < 50 ms) — replica death must not cost the
    warm path its milliseconds;
  * a voluntary drain/join cycle mid-churn (docs/SERVE.md "Draining a
    replica"): POST /v1/drain flips one replica to `draining` — its
    /healthz and serve-info must advertise it — and `{"resume": true}`
    returns it to rotation with /healthz back to `ok`;
  * with `--corrupt-corpus`: hostile-upload stand-ins (`poison_src`
    units) are convicted into the SRC-digest poison registry, queued
    siblings are swept without executing, a fresh request against a
    convicted digest parks at POST time, and the registry holds
    EXACTLY the injected digests (docs/ROBUSTNESS.md);
  * with `--throughput-floor N`: done-units/s measured over the whole
    churn window must stay at or above N (ROADMAP item 3: replica
    death and poison churn must not starve the settle path).

Prints one JSON report line (the `SERVE_CHAOS_*.json` artifact
committed with the PR) and exits nonzero on any violated invariant.
`--self-test` proves the harness can fail: it runs a small clean pass,
then tampers with the on-disk state (a stale settled epoch, a
resurrected 'active' request, a deleted store object) and demands the
checker report every seeded violation.

    python -m processing_chain_tpu tools serve-chaos
        [--replicas 3] [--kills 2] [--stops 1] [--lease-s 1.5]
        [--clients 6] [--srcs 8] [--hrcs 5] [--overlap 0.5]
        [--work-ms 80] [--workers 2] [--wave-width 4]
        [--warm-probes 15] [--warm-budget-ms 50]
        [--no-inject] [--timeout-s 180] [--out FILE] [--root DIR]
        [--self-test]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from ..utils.fsio import atomic_write_json, atomic_write_text
from ..utils.log import get_logger

_SHARED_GEOMETRY = [64, 36]

#: /metrics counters summed over the surviving replicas for the report
_SCRAPED = (
    "chain_serve_lease_steals_total",
    "chain_serve_fenced_settles_total",
    "chain_serve_quarantined_total",
    "chain_serve_claim_reverts_total",
    "chain_serve_poisoned_total",
)

def _synthetic_digest(src: str, database: str = "P2STR01") -> str:
    """The SyntheticExecutor's SRC content digest for one corpus SRC —
    ONE source of truth (serve/executors.py src_digest), so the gate's
    registry expectations can never drift from the executor's identity."""
    from ..serve.executors import SyntheticExecutor

    return SyntheticExecutor().src_digest(
        {"database": database, "src": src})


#: the --corrupt-corpus workload: hostile-upload stand-ins (the
#: synthetic executor's `poison_src` param — every unit settles with
#: the `poison` kind, quarantining the SRC's synthetic content digest
#: fleet-wide, docs/ROBUSTNESS.md)
_CORRUPT_SRCS = ("SRC950", "SRC951")


# ------------------------------------------------------------ replicas


class _Replica:
    """One chain-serve daemon process of the fleet."""

    def __init__(self, index: int, generation: int, proc, info: dict,
                 log_path: str) -> None:
        self.index = index
        self.generation = generation
        self.proc = proc
        self.info = info
        self.log_path = log_path

    @property
    def url(self) -> str:
        return self.info["url"]

    def alive(self) -> bool:
        return self.proc.poll() is None


def _spawn_replica(root: str, index: int, generation: int,
                   args) -> _Replica:
    """Start one replica over the shared root and wait for /healthz."""
    info_path = os.path.join(root, f"replica-{index}-g{generation}.json")
    log_path = os.path.join(root, "logs",
                            f"replica-{index}-g{generation}.log")
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    argv = [
        sys.executable, "-m", "processing_chain_tpu", "tools",
        "chain-serve",
        "--root", root,
        "--port", "0",
        "--executor", "synthetic",
        "--workers", str(args.workers),
        "--wave-width", str(args.wave_width),
        "--max-attempts", str(args.max_attempts),
        "--lease-s", str(args.lease_s),
        "--poll-s", str(args.poll_s),
        "--replica-id", f"chaos-r{index}-g{generation}",
        "--info-file", info_path,
    ]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    log_f = open(log_path, "ab")
    try:
        # chainlint: disable=subprocess-hygiene (chaos replicas are long-running daemons the harness must SIGKILL/SIGSTOP mid-execution; runner.shell runs a child to completion and cannot deliver mid-flight signals)
        proc = subprocess.Popen(
            argv, stdout=log_f, stderr=log_f, env=env,
        )
    finally:
        log_f.close()  # the child owns the fd now
    deadline = time.monotonic() + 60.0
    info: Optional[dict] = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"replica {index} g{generation} died on startup "
                f"(rc {proc.returncode}); log: {log_path}"
            )
        try:
            with open(info_path) as f:
                info = json.load(f)
            with urllib.request.urlopen(info["url"] + "/healthz",
                                        timeout=2.0):
                break
        except (OSError, ValueError, KeyError):
            time.sleep(0.1)
    else:
        proc.kill()
        raise RuntimeError(
            f"replica {index} g{generation} never became healthy; "
            f"log: {log_path}"
        )
    return _Replica(index, generation, proc, info, log_path)


def _post_json(url: str, payload: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.load(resp)


def _scrape_metrics(replicas: list) -> dict:
    """Sum the _SCRAPED counters over every live replica's /metrics.
    Dead generations took their counters with them — the sums are a
    floor, which is the direction the gates need (steals observed ≥
    threshold)."""
    totals = {name: 0.0 for name in _SCRAPED}
    for rep in replicas:
        if not rep.alive():
            continue
        try:
            with urllib.request.urlopen(rep.url + "/metrics",
                                        timeout=5.0) as resp:
                text = resp.read().decode()
        except OSError:
            continue
        for line in text.splitlines():
            for name in _SCRAPED:
                if line.startswith(name + " ") or \
                        line.startswith(name + "{"):
                    try:
                        totals[name] += float(line.rsplit(None, 1)[-1])
                    except ValueError:
                        pass
    return {name: int(v) for name, v in totals.items()}


# ------------------------------------------------------------ workload


def _grid(client: int, n_srcs: int, n_hrcs: int, overlap: float) -> dict:
    """Client grids share a common core (the overlap fraction) plus a
    per-client disjoint tail — the serve-soak shape, so the fleet sees
    real cross-request singleflight while it is being killed."""
    shared = max(1, int(n_srcs * overlap))
    srcs = [f"SRC{100 + i:03d}" for i in range(shared)]
    srcs += [f"SRC{500 + client * 16 + i:03d}"
             for i in range(n_srcs - shared)]
    hrcs = [f"HRC{100 + i:03d}" for i in range(n_hrcs)]
    return {"srcs": srcs, "hrcs": hrcs}


def _seed_body(args) -> dict:
    """The warm-probe grid: the shared core, completed BEFORE the chaos
    so mid-churn probes are store hits by construction."""
    shared = max(1, int(args.srcs * args.overlap))
    return {
        "tenant": "seed", "priority": "interactive",
        "database": "P2STR01",
        "srcs": [f"SRC{100 + i:03d}" for i in range(shared)],
        "hrcs": [f"HRC{100 + i:03d}" for i in range(args.hrcs)],
        "params": {"geometry": _SHARED_GEOMETRY,
                   "size_bytes": args.size_bytes},
    }


def _load_requests(root: str) -> dict:
    """Every request doc on disk — the harness's ground truth (it
    outlives any replica)."""
    docs = {}
    req_dir = os.path.join(root, "requests")
    try:
        names = os.listdir(req_dir)
    except OSError:
        return docs
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(req_dir, name)) as f:
                doc = json.load(f)
            docs[doc["request"]] = doc
        except (OSError, ValueError, KeyError):
            continue
    return docs


def _load_records(root: str) -> dict:
    records = {}
    jobs_dir = os.path.join(root, "queue", "jobs")
    try:
        names = os.listdir(jobs_dir)
    except OSError:
        return records
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(jobs_dir, name)) as f:
                doc = json.load(f)
            records[doc["job"]] = doc
        except (OSError, ValueError, KeyError):
            continue
    return records


# ----------------------------------------------------------- invariants


def check_invariants(root: str, poisoned: set,
                     expect_failed: Optional[set] = None) -> list[str]:
    """The chaos contract, checked from durable state only (no live
    replica required): requests terminal with the right disposition,
    exactly one verified artifact per done plan, every terminal record
    settled under the epoch its owner held, no surviving leases,
    quarantine exactly for the poisoned plans — and TRACE COMPLETENESS:
    every terminal record's span chain is gapless (serve/spans.py),
    even for work whose owner was SIGKILLed mid-wave."""
    from ..serve import spans as serve_spans
    from ..store.store import ArtifactStore, StoreCorruption

    violations: list[str] = []
    expect_failed = expect_failed if expect_failed is not None else poisoned
    docs = _load_requests(root)
    records = _load_records(root)
    store = ArtifactStore(os.path.join(root, "store"))
    if not docs:
        violations.append("no request docs found — the run produced nothing")

    for req_id, doc in sorted(docs.items()):
        state = doc.get("state")
        if state == "active":
            violations.append(f"request {req_id} never reached a terminal "
                              "state")
            continue
        should_fail = req_id in expect_failed
        if should_fail and state != "failed":
            violations.append(f"poisoned request {req_id} ended {state!r}, "
                              "expected failed")
        if not should_fail and state != "done":
            violations.append(f"request {req_id} ended {state!r} "
                              f"(error: {doc.get('error')})")
        if state != "done":
            continue
        for pvs_id, unit in doc.get("units", {}).items():
            plan = unit["plan"]
            manifest = store.lookup(plan)
            if manifest is None:
                violations.append(
                    f"lost unit: {req_id}/{pvs_id} is 'done' but plan "
                    f"{plan[:12]}… has no store artifact")
                continue
            try:
                store.verify_object(manifest.object)
            except StoreCorruption as exc:
                violations.append(
                    f"corrupt artifact for {req_id}/{pvs_id} "
                    f"({plan[:12]}…): {exc}")

    jobs_dir = os.path.join(root, "queue", "jobs")
    quarantined_plans = set()
    for job_id, rec in sorted(records.items()):
        state = rec.get("state")
        if state not in ("done", "failed", "quarantined"):
            violations.append(
                f"record {job_id} left non-terminal: {state!r}")
        settled = rec.get("settledEpoch")
        if state in ("done", "failed", "quarantined") and \
                settled is not None and settled != rec.get("epoch"):
            violations.append(
                f"record {job_id} settled under epoch {settled} but owns "
                f"epoch {rec.get('epoch')} — a fenced settle was ACCEPTED")
        if state == "quarantined":
            quarantined_plans.add(rec.get("planHash"))
            if rec.get("planHash") not in poisoned:
                violations.append(
                    f"record {job_id} quarantined but its plan was never "
                    "poisoned")
        if os.path.isfile(os.path.join(jobs_dir,
                                       job_id + ".json.inprogress")):
            violations.append(f"record {job_id} still carries a lease "
                              "after the run")
    for plan in poisoned - quarantined_plans:
        violations.append(f"poisoned plan {plan[:12]}… was never "
                          "quarantined")
    # trace completeness: the span journal must fully explain every
    # terminal record across all the deaths the schedule delivered
    violations.extend(serve_spans.verify_completeness(root,
                                                      records=records))
    return violations


# ------------------------------------------------------------ the run


def _percentile(values: list, frac: float) -> float:
    from ..telemetry.fleet import percentile_exact

    return percentile_exact(values, frac)


def run_chaos(args, root: str) -> dict:
    """Execute the chaos schedule; returns the report dict."""
    log = get_logger()
    replicas: list[_Replica] = []
    report: dict = {
        "replicas": args.replicas, "kills": args.kills,
        "stops": args.stops, "lease_s": args.lease_s,
        "clients": args.clients, "srcs": args.srcs, "hrcs": args.hrcs,
        "overlap": args.overlap, "work_ms": args.work_ms,
        "workers": args.workers, "wave_width": args.wave_width,
        "max_attempts": args.max_attempts, "inject": args.inject,
        "root": root,
    }
    failures: list[str] = []
    poisoned_plans: set = set()
    try:
        for i in range(args.replicas):
            replicas.append(_spawn_replica(root, i, 0, args))
        log.info("serve-chaos: %d replicas up", len(replicas))

        def live() -> list:
            return [r for r in replicas if r.alive()]

        # ---- seed the warm core (the mid-churn probes' grid) ----------
        seed = _post_json(replicas[0].url + "/v1/requests",
                          _seed_body(args))
        deadline = time.monotonic() + args.timeout_s
        while time.monotonic() < deadline:
            doc = _load_requests(root).get(seed["request"], {})
            if doc.get("state") == "done":
                break
            time.sleep(0.1)
        else:
            raise RuntimeError("seed request never completed")

        # ---- the overlapping burst, round-robin over the fleet --------
        accepted: list = [None] * args.clients
        expect_failed: set = set()

        def _client(i: int) -> None:
            body = {
                "tenant": f"tenant{i % 3}",
                "priority": ("interactive", "normal", "bulk")[i % 3],
                "database": "P2STR01",
                **_grid(i, args.srcs, args.hrcs, args.overlap),
                "params": {"geometry": _SHARED_GEOMETRY,
                           "size_bytes": args.size_bytes,
                           "work_ms": args.work_ms},
            }
            url = replicas[i % len(replicas)].url
            accepted[i] = _post_json(url + "/v1/requests", body)

        threads = [threading.Thread(target=_client, args=(i,))
                   for i in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if args.inject:
            # transient injection: every unit fails once, then succeeds
            # (retry + backoff across whatever replicas survive)
            transient = _post_json(replicas[0].url + "/v1/requests", {
                "tenant": "faulty", "priority": "normal",
                "database": "P2STR01",
                "srcs": ["SRC900", "SRC901"], "hrcs": ["HRC100"],
                "params": {"geometry": [48, 28], "fail_times": 1,
                           "size_bytes": args.size_bytes},
            })
            # permanent poison: quarantined plans, failed request
            poison = _post_json(
                replicas[-1].url + "/v1/requests", {
                    "tenant": "toxic", "priority": "normal",
                    "database": "P2STR01",
                    "srcs": ["SRC910"], "hrcs": ["HRC100", "HRC101"],
                    "params": {"poison": True},
                })
            expect_failed.add(poison["request"])
            report["transient_request"] = transient["request"]
            report["poison_request"] = poison["request"]

        if args.corrupt_corpus:
            # the corrupt-upload workload (docs/ROBUSTNESS.md): two
            # hostile SRCs × two HRCs from one tenant, POSTed INTO the
            # churn — the first solo-wave conviction must quarantine
            # each SRC's content digest and sweep its queued siblings
            # without executing them
            corrupt = _post_json(replicas[0].url + "/v1/requests", {
                "tenant": "uploads", "priority": "normal",
                "database": "P2STR01",
                "srcs": list(_CORRUPT_SRCS),
                "hrcs": ["HRC100", "HRC101"],
                "params": {"poison_src": True, "geometry": [32, 18],
                           "size_bytes": args.size_bytes},
            })
            expect_failed.add(corrupt["request"])
            report["corrupt_request"] = corrupt["request"]

        # ---- throughput sampler: done units over the churn window ----
        thr_samples: list = []
        thr_stop = threading.Event()

        def _thr_sampler() -> None:
            while not thr_stop.is_set():
                done_now = sum(
                    1 for r in _load_records(root).values()
                    if r.get("state") == "done"
                )
                thr_samples.append((time.monotonic(), done_now))
                thr_stop.wait(0.25)

        thr_thread = threading.Thread(target=_thr_sampler, daemon=True)
        thr_thread.start()

        # ---- chaos schedule ------------------------------------------
        zombie: Optional[_Replica] = None
        resume_timer: Optional[threading.Timer] = None
        if args.stops > 0 and len(live()) > 1:
            time.sleep(args.stop_delay_s)
            zombie = live()[-1]
            zombie_pid = zombie.proc.pid
            os.kill(zombie_pid, signal.SIGSTOP)
            report["zombie"] = f"r{zombie.index}-g{zombie.generation}"
            log.info("serve-chaos: SIGSTOP replica %d (the zombie)",
                     zombie.index)

            def _resume() -> None:
                try:
                    os.kill(zombie_pid, signal.SIGCONT)
                    log.info("serve-chaos: SIGCONT the zombie")
                except OSError:
                    pass

            # resumed from a TIMER, not the main thread: a zombie
            # frozen inside a queue critical section holds the shared
            # flock, and a restarting replica then blocks in recovery
            # until the zombie continues — the resume must not wait on
            # anything that might wait on the zombie
            resume_timer = threading.Timer(args.stop_s, _resume)
            resume_timer.daemon = True
            resume_timer.start()

        kills_done = 0
        for k in range(args.kills):
            time.sleep(args.kill_delay_s)
            victims = [r for r in live() if r is not zombie]
            if not victims:
                break
            victim = victims[(k + 1) % len(victims)]
            victim.proc.kill()
            victim.proc.wait(timeout=30)
            kills_done += 1
            log.info("serve-chaos: SIGKILL replica %d g%d",
                     victim.index, victim.generation)
            time.sleep(args.restart_delay_s)
            replicas.append(
                _spawn_replica(root, victim.index,
                               victim.generation + 1, args))

        # ---- warm probes DURING the churn ----------------------------
        warm_ms: list = []
        probe_body = _seed_body(args)
        for _ in range(args.warm_probes):
            target = [r for r in live() if r is not zombie][0]
            t0 = time.perf_counter()
            probe = _post_json(target.url + "/v1/requests", probe_body,
                               timeout=10.0)
            warm_ms.append(round((time.perf_counter() - t0) * 1e3, 3))
            if probe.get("state") != "done" or \
                    not probe.get("latency_ms"):
                failures.append(
                    f"warm probe {probe.get('request')} was not answered "
                    f"at POST time (state {probe.get('state')})")
            time.sleep(0.05)

        # ---- drain/join cycle: one replica bows out and rejoins ------
        # (docs/SERVE.md "Draining a replica"): POST /v1/drain flips
        # the replica to draining — /healthz and serve-info advertise
        # it, the scheduler stops claiming, peers absorb the queue —
        # then {"resume": true} puts it back in rotation. Run INSIDE
        # the churn so the fleet proves it survives a voluntary exit
        # on top of the involuntary ones.
        candidates = [r for r in live() if r is not zombie]
        if len(candidates) >= 2:
            drained = candidates[-1]
            drain_info: dict = {"replica":
                                f"r{drained.index}-g{drained.generation}"}
            _post_json(drained.url + "/v1/drain", {}, timeout=10.0)
            with urllib.request.urlopen(drained.url + "/healthz",
                                        timeout=5.0) as resp:
                health = json.load(resp)
            drain_info["healthz_draining"] = health.get("status")
            if health.get("status") != "draining":
                failures.append(
                    f"drained replica's /healthz reports "
                    f"{health.get('status')!r}, expected 'draining'")
            info_path = os.path.join(
                root, f"replica-{drained.index}-"
                      f"g{drained.generation}.json")
            try:
                with open(info_path) as f:
                    drain_info["info_state"] = json.load(f).get("state")
            except (OSError, ValueError):
                drain_info["info_state"] = None
            if drain_info["info_state"] != "draining":
                failures.append(
                    "drained replica's serve-info never flipped to "
                    f"'draining' (saw {drain_info['info_state']!r})")
            # the fleet keeps settling while one member sits out
            time.sleep(max(0.5, args.poll_s))
            _post_json(drained.url + "/v1/drain", {"resume": True},
                       timeout=10.0)
            with urllib.request.urlopen(drained.url + "/healthz",
                                        timeout=5.0) as resp:
                health = json.load(resp)
            drain_info["healthz_resumed"] = health.get("status")
            if health.get("status") != "ok":
                failures.append(
                    f"resumed replica's /healthz reports "
                    f"{health.get('status')!r}, expected 'ok'")
            report["drain_cycle"] = drain_info

        # ---- fail-fast: a fresh tenant hits a poisoned digest --------
        if args.corrupt_corpus:
            # wait for the first conviction to land in the registry
            # (it needs a solo-wave verdict, which the jittered backoff
            # delivers), then a NEW plan (fresh HRC) against the same
            # SRC must park at enqueue — quarantined with zero
            # executions — instead of burning its own attempts budget
            digest0 = _synthetic_digest(_CORRUPT_SRCS[0])
            registry0 = os.path.join(root, "queue", "poison",
                                     digest0 + ".json")
            deadline = time.monotonic() + args.timeout_s
            while time.monotonic() < deadline and \
                    not os.path.isfile(registry0):
                time.sleep(0.2)
            if not os.path.isfile(registry0):
                failures.append(
                    f"corrupt-corpus: digest of {_CORRUPT_SRCS[0]} never "
                    "reached the poison registry")
            failfast = _post_json(
                [r for r in live() if r is not zombie][0].url
                + "/v1/requests", {
                    "tenant": "other", "priority": "normal",
                    "database": "P2STR01",
                    "srcs": [_CORRUPT_SRCS[0]], "hrcs": ["HRC103"],
                    "params": {"poison_src": True, "geometry": [32, 18],
                               "size_bytes": args.size_bytes},
                })
            expect_failed.add(failfast["request"])
            report["corrupt_failfast_request"] = failfast["request"]

        # ---- zombie resume: its settles must be fenced, not accepted -
        if resume_timer is not None:
            resume_timer.join()

        # ---- wait for every request to reach a terminal state --------
        deadline = time.monotonic() + args.timeout_s
        pending: list = []
        while time.monotonic() < deadline:
            docs = _load_requests(root)
            pending = [r for r, d in docs.items()
                       if d.get("state") == "active"]
            if not pending:
                records = _load_records(root)
                busy = [j for j, r in records.items()
                        if r.get("state") in ("queued", "running")]
                if not busy:
                    break
            time.sleep(0.25)
        else:
            failures.append(f"timeout: still unsettled after "
                            f"{args.timeout_s}s: requests {pending[:5]}")

        # ---- throughput floor during churn (ROADMAP item 3) ----------
        thr_stop.set()
        thr_thread.join(timeout=10.0)
        churn_units_per_s: Optional[float] = None
        if len(thr_samples) >= 2:
            (t_a, n_a), (t_b, n_b) = thr_samples[0], thr_samples[-1]
            if t_b > t_a:
                churn_units_per_s = round((n_b - n_a) / (t_b - t_a), 3)
        report["churn_throughput_units_per_s"] = churn_units_per_s
        if args.throughput_floor > 0:
            if churn_units_per_s is None:
                failures.append("throughput floor: too few samples to "
                                "measure churn throughput")
            elif churn_units_per_s < args.throughput_floor:
                failures.append(
                    f"churn throughput {churn_units_per_s:.2f} units/s "
                    f"under the {args.throughput_floor:g} units/s floor "
                    "— replica death/poison churn is starving the "
                    "settle path")

        # poisoned plan hashes, for the quarantine invariant
        docs = _load_requests(root)
        for req_id in expect_failed:
            for unit in docs.get(req_id, {}).get("units", {}).values():
                poisoned_plans.add(unit["plan"])

        # fleet view captured WHILE survivors are still serving — the
        # per-(tenant × priority) SLO histograms merged over the fleet
        # as they stood during/after the churn (FLEET_OBS artifact)
        try:
            from ..telemetry import fleet as fleet_mod

            fleet_doc = fleet_mod.fleet_view(root)
        except Exception as exc:  # noqa: BLE001 - the view must not sink the run
            fleet_doc = {"error": repr(exc)}
            failures.append(f"fleet view failed to build: {exc!r}")
        report["fleet"] = {
            "alive": fleet_doc.get("alive"),
            "replicas": len(fleet_doc.get("replicas", [])),
            "spans": fleet_doc.get("spans"),
            "slo_flows": sum(
                len(p) for t in fleet_doc.get("slo", {}).values()
                for p in t.values()
            ),
        }
        if args.fleet_out:
            atomic_write_json(args.fleet_out, fleet_doc)
        if not fleet_doc.get("slo"):
            failures.append("fleet view carries no SLO histograms — "
                            "the phase metrics never recorded")

        counters = _scrape_metrics(live())
        report["counters"] = counters
        report["kills_done"] = kills_done
        report["warm_request_ms"] = {
            "probes": len(warm_ms),
            "min": min(warm_ms) if warm_ms else None,
            "p50": _percentile(warm_ms, 0.50) if warm_ms else None,
            "p90": _percentile(warm_ms, 0.90) if warm_ms else None,
            "max": max(warm_ms) if warm_ms else None,
        }
        units_total = sum(len(d.get("units", {})) for d in docs.values())
        unique_plans = {u["plan"] for d in docs.values()
                        for u in d.get("units", {}).values()}
        report["requests"] = len(docs)
        report["units_total"] = units_total
        report["unique_plans"] = len(unique_plans)

        # ---- corrupt-corpus invariants (docs/ROBUSTNESS.md) ----------
        if args.corrupt_corpus:
            records = _load_records(root)
            expected_digests = {
                _synthetic_digest(src) for src in _CORRUPT_SRCS
            }
            registry = set()
            poison_dir = os.path.join(root, "queue", "poison")
            try:
                registry = {n[:-5] for n in os.listdir(poison_dir)
                            if n.endswith(".json")}
            except OSError:
                pass
            for digest in expected_digests - registry:
                failures.append(f"corrupt-corpus: digest {digest[:12]}… "
                                "missing from the poison registry")
            for digest in registry - expected_digests:
                failures.append(f"corrupt-corpus: digest {digest[:12]}… "
                                "quarantined but never injected")
            poison_recs = [r for r in records.values()
                           if r.get("errorKind") == "poison"]
            if not poison_recs:
                failures.append("corrupt-corpus: no record settled with "
                                "the poison kind")
            for rec in poison_recs:
                if rec.get("state") != "quarantined":
                    failures.append(
                        f"corrupt-corpus: poison record {rec.get('job')} "
                        f"ended {rec.get('state')!r}, expected "
                        "quarantined")
            swept = [r for r in poison_recs
                     if r.get("state") == "quarantined"
                     and not r.get("attempts")]
            if not swept:
                failures.append(
                    "corrupt-corpus: no sibling was swept without "
                    "executing (attempts == 0) — digest fail-fast never "
                    "fired")
            report["corrupt_corpus"] = {
                "digests": len(registry),
                "poison_records": len(poison_recs),
                "swept_without_executing": len(swept),
            }

        # ---- invariants ----------------------------------------------
        failures.extend(check_invariants(root, poisoned_plans,
                                         expect_failed=expect_failed))
        if kills_done < args.kills:
            failures.append(f"only {kills_done}/{args.kills} kills were "
                            "delivered (fleet too small?)")
        # the /metrics scrape is a floor over the replicas still alive
        # at capture time — a stealer killed LATER in the schedule took
        # its counter with it. The durable span journal records every
        # steal fleet-wide, so it is the authoritative count.
        steals_observed = max(
            counters["chain_serve_lease_steals_total"],
            (report.get("fleet", {}).get("spans", {}) or {})
            .get("by_phase", {}).get("steal", 0),
        )
        if args.stops > 0 and steals_observed < 1:
            failures.append(
                "SIGSTOP zombie produced no lease steal — the run proved "
                "nothing about fencing (lower --lease-s or raise "
                "--work-ms/--stop-s)")
        if warm_ms and args.warm_budget_ms > 0 and \
                _percentile(warm_ms, 0.50) > args.warm_budget_ms:
            failures.append(
                f"warm p50 {_percentile(warm_ms, 0.50):.1f} ms over the "
                f"{args.warm_budget_ms:.0f} ms budget under churn")
    finally:
        for rep in replicas:
            if rep.alive():
                try:
                    os.kill(rep.proc.pid, signal.SIGCONT)
                except OSError:
                    pass
                rep.proc.terminate()
        for rep in replicas:
            if rep.proc.poll() is None:
                try:
                    rep.proc.wait(timeout=20)
                except Exception:  # noqa: BLE001 - last resort on a wedged child
                    rep.proc.kill()
    report["failures"] = failures
    report["ok"] = not failures
    return report


# ----------------------------------------------------------- self-test


def run_self_test(args, root: str) -> int:
    """Prove the invariant checker can FAIL (the repo's standing
    gate-must-be-able-to-fire discipline): run a small clean pass, then
    seed three distinct corruptions into the durable state and demand
    the checker reports each class."""
    log = get_logger()
    args.replicas, args.kills, args.stops = 1, 0, 0
    args.clients, args.srcs, args.hrcs = 2, 2, 2
    args.inject = False
    args.corrupt_corpus = False
    args.throughput_floor = 0.0
    args.warm_probes = 2
    args.work_ms = 5
    report = run_chaos(args, root)
    if not report["ok"]:
        log.error("serve-chaos self-test: clean pass FAILED: %s",
                  report["failures"])
        return 1
    records = _load_records(root)
    done = [r for r in records.values() if r.get("state") == "done"]
    docs = _load_requests(root)
    some_req = sorted(docs)[0]
    jobs_dir = os.path.join(root, "queue", "jobs")
    # 1) a fenced settle "accepted": settled epoch behind the record's
    rec = done[0]
    rec["settledEpoch"] = int(rec.get("epoch", 1)) - 1
    atomic_write_json(os.path.join(jobs_dir, rec["job"] + ".json"), rec)
    # 2) a request resurrected to 'active' (never-terminal class)
    doc = docs[some_req]
    doc["state"] = "active"
    atomic_write_json(os.path.join(root, "requests", some_req + ".json"),
                      doc)
    # 3) a lost artifact: delete a store object whose plan a STILL-done
    # request (not the one resurrected above) depends on
    from ..store.store import ArtifactStore

    store = ArtifactStore(os.path.join(root, "store"))
    victim_plan = None
    for req_id, d in sorted(docs.items()):
        if req_id == some_req or d.get("state") != "done":
            continue
        for unit in d.get("units", {}).values():
            if store.lookup(unit["plan"]) is not None:
                victim_plan = unit["plan"]
                break
        if victim_plan:
            break
    if victim_plan is None:
        log.error("serve-chaos self-test: no deletable artifact found")
        return 1
    manifest = store.lookup(victim_plan)
    os.unlink(store.object_path(manifest.object["sha256"]))
    # 4) a trace gap: strip one done job's claim spans from every
    # journal — its terminal record is then unexplained (an ownership
    # epoch no span introduced), which the completeness check must flag
    gap_job = done[-1]["job"]
    spans_dir = os.path.join(root, "queue", "spans")
    for name in os.listdir(spans_dir):
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(spans_dir, name)
        with open(path) as f:
            lines = f.readlines()
        kept = []
        for line in lines:
            try:
                span = json.loads(line)
            except ValueError:
                kept.append(line)
                continue
            if span.get("job") == gap_job and span.get("phase") == "claim":
                continue
            kept.append(line)
        # chainlint: disable=atomic-write (self-test tamper harness: deliberately corrupting the journal the checker must then flag)
        with open(path, "w") as f:
            f.writelines(kept)
    violations = check_invariants(root, set())
    classes = {
        "fenced": any("fenced settle was ACCEPTED" in v
                      for v in violations),
        "active": any("never reached a terminal" in v
                      for v in violations),
        "artifact": any(("no store artifact" in v or
                         "corrupt artifact" in v) for v in violations),
        "trace": any(("chain has a gap" in v or
                      "no spans at all" in v) for v in violations),
    }
    print(json.dumps({"self_test": True, "violations": violations,
                      "classes": classes}))
    if all(classes.values()):
        log.info("serve-chaos self-test OK: all %d seeded corruption "
                 "classes detected", len(classes))
        return 0
    log.error("serve-chaos self-test: checker MISSED seeded corruption: "
              "%s", classes)
    return 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="tools serve-chaos",
        description="multi-replica kill/steal/fence proof harness "
                    "(docs/SERVE.md)",
    )
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--kills", type=int, default=2,
                   help="replicas to SIGKILL mid-run (each restarted)")
    p.add_argument("--stops", type=int, default=1,
                   help="1 = SIGSTOP one replica past its lease (zombie)")
    p.add_argument("--lease-s", type=float, default=1.5)
    p.add_argument("--poll-s", type=float, default=0.3,
                   help="replica maintenance tick (steal latency)")
    p.add_argument("--clients", type=int, default=6)
    p.add_argument("--srcs", type=int, default=8)
    p.add_argument("--hrcs", type=int, default=5)
    p.add_argument("--overlap", type=float, default=0.5)
    p.add_argument("--work-ms", type=float, default=80.0)
    p.add_argument("--size-bytes", type=int, default=2048)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--wave-width", type=int, default=4)
    p.add_argument("--max-attempts", type=int, default=3)
    p.add_argument("--stop-delay-s", type=float, default=0.6,
                   help="burst-to-SIGSTOP delay (zombie must hold claims)")
    p.add_argument("--stop-s", type=float, default=5.0,
                   help="how long the zombie stays stopped (> lease-s)")
    p.add_argument("--kill-delay-s", type=float, default=0.5)
    p.add_argument("--restart-delay-s", type=float, default=0.8)
    p.add_argument("--warm-probes", type=int, default=15)
    p.add_argument("--warm-budget-ms", type=float, default=50.0,
                   help="p50 gate for warm POSTs during churn (0 = off)")
    p.add_argument("--no-inject", dest="inject", action="store_false",
                   help="skip the transient/poison fault-injection "
                        "requests")
    p.add_argument("--corrupt-corpus", action="store_true",
                   help="drive the hostile-upload workload through the "
                        "churn: poison-SRC units whose content digests "
                        "must quarantine fleet-wide with fail-fast "
                        "sweeps (docs/ROBUSTNESS.md)")
    p.add_argument("--throughput-floor", type=float, default=0.0,
                   help="minimum done-units/s over the churn window "
                        "(0 = report only; ROADMAP item 3 gate)")
    p.add_argument("--timeout-s", type=float, default=180.0)
    p.add_argument("--out", default=None,
                   help="also write the JSON report here")
    p.add_argument("--fleet-out", default=None,
                   help="write the merged fleet view (replicas + SLO "
                        "histograms captured during churn) here")
    p.add_argument("--root", default=None,
                   help="shared fleet root (default: a fresh temp dir)")
    p.add_argument("--self-test", action="store_true",
                   help="prove the invariant checker can fail")
    args = p.parse_args(list(argv) if argv is not None else None)

    root = os.path.abspath(args.root or
                           tempfile.mkdtemp(prefix="chain-serve-chaos-"))
    os.makedirs(root, exist_ok=True)
    if args.self_test:
        return run_self_test(args, root)
    report = run_chaos(args, root)
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    log = get_logger()
    if report["failures"]:
        for f in report["failures"]:
            log.error("serve-chaos: %s", f)
        return 1
    log.info(
        "serve-chaos: OK — %d requests / %d units / %d plans through "
        "%d kills + %d stop(s); %d lease steal(s), %d fenced settle(s), "
        "warm p50 %s ms",
        report["requests"], report["units_total"], report["unique_plans"],
        report["kills_done"], args.stops,
        report["counters"]["chain_serve_lease_steals_total"],
        report["counters"]["chain_serve_fenced_settles_total"],
        report["warm_request_ms"]["p50"],
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
