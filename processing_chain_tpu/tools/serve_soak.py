"""`tools serve-soak` — the dedup/fairness/latency proof harness.

Runs an in-process chain-serve service, fires N concurrent synthetic
clients whose SRC×HRC grids deliberately OVERLAP, waits for every
request to finish, then asserts the serving economics the design
promises (ROADMAP open item #2, docs/SERVE.md):

  * zero duplicate executions — `chain_jobs_planned_total{runner=serve}`
    must equal the number of UNIQUE plan hashes across all requests;
  * every request completes;
  * a warm re-run of the same grids answers in milliseconds
    (measured, reported, and gated against --warm-budget-ms).

`--executor chain` runs the soak over a REAL synthetic corpus: the
harness renders SRC videos of deliberately mixed complexity, writes a
database YAML around them, and the overlapping clients drive the full
p01–p04 stages through the production executor — every artifact family
lands in the store, still with zero duplicate executions.

`--pack-bench` instead benches the scheduler's packing POLICY:
cost-aware wave packing (balance predicted seconds, serve/cost.py) vs
count-based packing on an adversarially-ordered mixed-complexity queue,
reporting per-wave predicted-seconds spread and per-unit e2e tail for
both (the committed `COST_PACK_*.json` band).

The report also breaks the cold pass's latency into the SLO phases
the fleet layer grades (docs/TELEMETRY.md "Fleet observability"):
p50/p95/p99 of queue-wait (enqueue→claim) and execution (claim→settle)
from the span journal's exact timestamps, plus request end-to-end —
so a soak regression says WHICH phase moved, not just that warm p50
did.

Prints one JSON report line (the `SERVE_SOAK_*.json` artifact committed
with the PR) and exits nonzero on any violated invariant.

    python -m processing_chain_tpu tools serve-soak
        [--clients 8] [--srcs 6] [--hrcs 4] [--overlap 0.5]
        [--executor synthetic|wave|chain] [--workers 4] [--wave-width 4]
        [--warm-budget-ms 1000] [--out FILE] [--root DIR]
        [--pack-bench] [--wave-budget-s S]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import threading
import time
from typing import Optional, Sequence

from .. import telemetry as tm
from ..utils.fsio import atomic_write_text
from ..utils.log import get_logger


def _grid(client: int, n_srcs: int, n_hrcs: int, overlap: float) -> dict:
    """Client grids share a common core (the overlap fraction) and add a
    per-client disjoint tail — the 'million users requesting overlapping
    SRC×HRC grids' shape, miniaturized."""
    shared = max(1, int(n_srcs * overlap))
    srcs = [f"SRC{100 + i:03d}" for i in range(shared)]
    srcs += [f"SRC{500 + client * 16 + i:03d}"
             for i in range(n_srcs - shared)]
    hrcs = [f"HRC{100 + i:03d}" for i in range(n_hrcs)]
    return {"srcs": srcs, "hrcs": hrcs}


def _percentiles_ms(values: list) -> Optional[dict]:
    """{p50, p95, p99} in milliseconds (exact order statistics — the
    soak has every observation, no bucket estimate needed)."""
    from ..telemetry.fleet import percentile_exact

    if not values:
        return None
    return {"p50": round(percentile_exact(values, 0.50) * 1e3, 3),
            "p95": round(percentile_exact(values, 0.95) * 1e3, 3),
            "p99": round(percentile_exact(values, 0.99) * 1e3, 3),
            "n": len(values)}


def phase_latencies(root: str, e2e_s: list) -> dict:
    """Per-phase latency percentiles from the span journal (queue-wait
    and execution ride the claim/settle spans) + the caller's request
    end-to-end samples."""
    from ..serve import spans as serve_spans

    journal = serve_spans.read_journals(
        os.path.join(root, "queue", "spans"))
    queue_wait = [s["queue_wait_s"] for s in journal
                  if s.get("phase") == "claim"
                  and s.get("queue_wait_s") is not None]
    execution = [s["exec_s"] for s in journal
                 if s.get("phase") == "complete"
                 and s.get("exec_s") is not None and not s.get("warm")]
    return {
        "queue_wait_ms": _percentiles_ms(queue_wait),
        "execution_ms": _percentiles_ms(execution),
        "e2e_ms": _percentiles_ms(e2e_s),
    }


def _planned_serve_jobs() -> int:
    metric = tm.REGISTRY.snapshot().get("chain_jobs_planned_total")
    if not metric:
        return 0
    return int(sum(
        s.get("value", 0) for s in metric["series"]
        if s.get("labels", {}).get("runner") == "serve"
    ))


# ------------------------------------------------------ chain corpus


def make_chain_corpus(root: str, n_srcs: int, n_hrcs: int) -> dict:
    """A REAL synthetic corpus for the production executor: `n_srcs`
    tiny SRC videos of deliberately MIXED complexity (spatial detail ×
    motion speed × noise all vary per SRC, so the priors cost model has
    something to rank) and an `n_hrcs`-rung bitrate ladder around them.
    Returns {"config", "srcs", "hrcs"}."""
    import numpy as np

    from ..io import VideoWriter

    db_id = "P2SXM77"
    db_dir = os.path.join(root, "corpus", db_id)
    os.makedirs(os.path.join(db_dir, "srcVid"), exist_ok=True)
    w, h, n, fps = 160, 90, 48, 24
    rng = np.random.default_rng(7)
    srcs = [f"SRC{i:03d}" for i in range(n_srcs)]
    for i, src in enumerate(srcs):
        path = os.path.join(db_dir, "srcVid", src + ".avi")
        detail = 5 + 18 * i          # spatial frequency ramps per SRC
        speed = 1 + 3 * i            # motion ramps per SRC
        noise = 3.0 * i              # coding complexity ramps per SRC
        with VideoWriter(path, "ffv1", w, h, "yuv420p", (fps, 1)) as wr:
            xx, yy = np.meshgrid(np.arange(w), np.arange(h))
            for f in range(n):
                y = (np.sin((xx + speed * f) / max(1, 30 - detail))
                     + np.cos((yy + f) / 17)) * 50 + 120
                if noise:
                    y = y + rng.normal(0.0, noise, y.shape)
                y = np.clip(y, 0, 255).astype(np.uint8)
                u = np.full((h // 2, w // 2), 128, np.uint8)
                v = np.full((h // 2, w // 2), 118, np.uint8)
                wr.write(y, u, v)
    hrcs = [f"HRC{i:03d}" for i in range(n_hrcs)]
    qls = "\n".join(
        f"  Q{i}: {{index: {i}, videoCodec: h264, "
        f"videoBitrate: {150 * (i + 1)}, width: {w}, height: {h}, "
        f"fps: {fps}}}"
        for i in range(n_hrcs)
    )
    hrc_rows = "\n".join(
        f"  {hrc}: {{videoCodingId: VC01, eventList: [[Q{i}, 2]]}}"
        for i, hrc in enumerate(hrcs)
    )
    pvs_rows = "\n".join(
        f"  - {db_id}_{src}_{hrc}" for src in srcs for hrc in hrcs
    )
    config = os.path.join(db_dir, db_id + ".yaml")
    atomic_write_text(config, (
        f"databaseId: {db_id}\n"
        "syntaxVersion: 6\n"
        "type: short\n"
        f"qualityLevelList:\n{qls}\n"
        "codingList:\n"
        "  VC01: {type: video, encoder: libx264, passes: 1, "
        "iFrameInterval: 1, preset: ultrafast}\n"
        "srcList:\n"
        + "\n".join(f"  {s}: {s}.avi" for s in srcs) + "\n"
        f"hrcList:\n{hrc_rows}\n"
        f"pvsList:\n{pvs_rows}\n"
        "postProcessingList:\n"
        f"  - {{type: pc, displayWidth: {w}, displayHeight: {h}, "
        f"codingWidth: {w}, codingHeight: {h}, displayFrameRate: {fps}}}\n"
    ))
    return {"config": config, "database": db_id, "srcs": srcs,
            "hrcs": hrcs}


def _corpus_grid(client: int, corpus: dict, overlap: float) -> dict:
    """Overlapping per-client subsets of the REAL corpus grid (the
    chain-mode sibling of `_grid`): a shared core plus a rotating
    tail."""
    srcs, hrcs = corpus["srcs"], corpus["hrcs"]
    shared = max(1, int(len(srcs) * overlap))
    picked = list(srcs[:shared])
    for k in range(len(srcs) - shared):
        picked.append(srcs[(shared + client + k) % len(srcs)])
    return {"srcs": sorted(set(picked)), "hrcs": list(hrcs)}


# ------------------------------------------------------- pack bench


def pack_bench(args) -> int:
    """Cost-aware vs count-based wave packing on an adversarially
    ordered mixed-complexity queue: a burst of light units followed by
    a burst of heavy ones (the order a bursty tenant actually
    produces). Count-based packing groups the heavies into a few
    monolithic all-heavy waves whose coarse granularity straggles the
    end of the drain; cost-aware packing splits them into ~budget-sized
    waves that spread across workers. Reports, per policy: per-wave
    predicted-seconds spread (CV + max) and per-unit e2e latency
    percentiles. Exit 1 unless cost-aware improves BOTH — the committed
    `COST_PACK_*.json` band."""
    from ..serve import cost as serve_cost
    from ..serve.api import Unit
    from ..serve.executors import SyntheticExecutor
    from ..serve.queue import DurableQueue
    from ..serve.scheduler import Scheduler
    from ..store import keys

    log = get_logger()
    tm.enable()
    root = args.root or tempfile.mkdtemp(prefix="chain-pack-bench-")
    heavy_ms, light_ms = 200, 10
    n_heavy, n_light = 12, 36
    executor = SyntheticExecutor()

    def predict(work_ms: int) -> float:
        return serve_cost.predict_unit_cost(executor, {
            "params": {"work_ms": work_ms, "size_bytes": 1024},
        })

    budget = args.wave_budget_s or (
        predict(heavy_ms) + 3 * predict(light_ms) + 0.005
    )
    report: dict = {
        "bench": "pack",
        "heavy_ms": heavy_ms, "light_ms": light_ms,
        "n_heavy": n_heavy, "n_light": n_light,
        "workers": args.workers, "wave_width": args.wave_width,
        "wave_budget_s": round(budget, 4),
        "modes": {},
    }
    failures: list[str] = []
    work = [light_ms] * n_light + [heavy_ms] * n_heavy
    for mode in ("count", "cost"):
        mroot = os.path.join(root, mode)
        queue = DurableQueue(os.path.join(mroot, "queue"))
        try:
            for i, work_ms in enumerate(work):
                unit = Unit(database="P2STR01", src=f"SRC{100 + i:03d}",
                            hrc="HRC100",
                            params={"geometry": [64, 36],
                                    "work_ms": work_ms,
                                    "size_bytes": 1024})
                plan = executor.plan(unit)
                record_unit = {
                    "database": unit.database, "src": unit.src,
                    "hrc": unit.hrc, "params": unit.params,
                    "pvs_id": unit.pvs_id,
                }
                queue.enqueue(
                    keys.plan_hash(plan), plan, record_unit, "acme",
                    "normal", f"req-{i}", f"u{i}.bin",
                    cost_s=serve_cost.predict_unit_cost(
                        executor, record_unit),
                )
            events_before = len(tm.EVENTS.records())
            sched = Scheduler(
                queue, executor, os.path.join(mroot, "artifacts"),
                workers=args.workers, wave_width=args.wave_width,
                wave_budget_s=budget if mode == "cost" else None,
            )
            t0 = time.perf_counter()
            sched.start()
            drained = sched.wait_idle(timeout=180.0)
            wall_s = time.perf_counter() - t0
            sched.stop()
            if not drained:
                failures.append(f"{mode}: queue never drained")
                continue
            wave_pred = [
                e.get("predicted_s", 0.0)
                for e in tm.EVENTS.records()[events_before:]
                if e.get("event") == "serve_wave"
            ]
            records = [queue.record(j) for j in _record_ids(queue)]
            e2e = [
                max(0.0, r.done_at - r.enqueued_at)
                for r in records
                if r is not None and r.state == "done" and r.done_at
            ]
            mean = sum(wave_pred) / max(1, len(wave_pred))
            var = sum((x - mean) ** 2 for x in wave_pred) \
                / max(1, len(wave_pred))
            from ..telemetry.fleet import percentile_exact

            results = {
                "waves": len(wave_pred),
                "wave_pred_mean_s": round(mean, 4),
                "wave_pred_max_s": round(max(wave_pred), 4)
                if wave_pred else None,
                "wave_pred_cv": round((var ** 0.5) / mean, 4)
                if mean else None,
                "units_done": len(e2e),
                "e2e_p50_s": round(percentile_exact(e2e, 0.50), 4)
                if e2e else None,
                "e2e_p95_s": round(percentile_exact(e2e, 0.95), 4)
                if e2e else None,
                "wall_s": round(wall_s, 3),
            }
            report["modes"][mode] = results
            if len(e2e) != len(work):
                failures.append(
                    f"{mode}: {len(e2e)}/{len(work)} units completed")
        finally:
            queue.close()
    count_m, cost_m = report["modes"].get("count"), \
        report["modes"].get("cost")
    if count_m and cost_m and None not in (
            count_m["e2e_p95_s"], cost_m["e2e_p95_s"],
            count_m["wave_pred_cv"], cost_m["wave_pred_cv"]):
        report["improvement"] = {
            "wave_pred_cv": round(
                count_m["wave_pred_cv"] / max(1e-9, cost_m["wave_pred_cv"]),
                3) if cost_m["wave_pred_cv"] else None,
            "e2e_p95": round(
                count_m["e2e_p95_s"] / max(1e-9, cost_m["e2e_p95_s"]), 3),
        }
        if cost_m["wave_pred_cv"] >= count_m["wave_pred_cv"]:
            failures.append(
                "cost-aware packing did not reduce per-wave "
                f"predicted-seconds spread (cv {cost_m['wave_pred_cv']} "
                f"vs {count_m['wave_pred_cv']})")
        if cost_m["e2e_p95_s"] >= count_m["e2e_p95_s"]:
            failures.append(
                "cost-aware packing did not improve the e2e tail "
                f"(p95 {cost_m['e2e_p95_s']}s vs "
                f"{count_m['e2e_p95_s']}s)")
    else:
        # a mode that completed nothing already appended its failure;
        # the comparison is meaningless without both sides' numbers
        failures.append("pack comparison skipped: a mode has no "
                        "completed units")
    report["failures"] = failures
    report["ok"] = not failures
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    if failures:
        for f in failures:
            log.error("pack-bench: %s", f)
        return 1
    log.info("pack-bench: OK — wave-spread cv %s -> %s, e2e p95 %ss -> %ss",
             count_m["wave_pred_cv"], cost_m["wave_pred_cv"],
             count_m["e2e_p95_s"], cost_m["e2e_p95_s"])
    return 0


def _record_ids(queue) -> list[str]:
    with queue._lock:
        return list(queue._jobs)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="tools serve-soak")
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--srcs", type=int, default=6)
    parser.add_argument("--hrcs", type=int, default=4)
    parser.add_argument("--overlap", type=float, default=0.5,
                        help="fraction of each grid shared across clients")
    parser.add_argument("--executor", default="synthetic")
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--wave-width", type=int, default=4)
    parser.add_argument("--warm-budget-ms", type=float, default=1000.0,
                        help="warm-hit request latency gate (per request)")
    parser.add_argument("--out", default=None,
                        help="also write the JSON report here")
    parser.add_argument("--root", default=None,
                        help="serve root (default: a fresh temp dir)")
    parser.add_argument("--wave-budget-s", type=float, default=None,
                        help="cost-aware packing budget (predicted "
                             "seconds per wave; serve/cost.py)")
    parser.add_argument("--calibrate-out", default=None, metavar="FILE",
                        help="after the soak, fit the per-host cost-"
                             "prediction scale from the ledger's "
                             "observed/predicted ratios and write the "
                             "fitted coefficients JSON to FILE "
                             "(serve/cost.py calibration)")
    parser.add_argument("--pack-bench", action="store_true",
                        help="bench cost-aware vs count-based wave "
                             "packing instead of running the soak")
    args = parser.parse_args(list(argv) if argv is not None else None)

    if args.pack_bench:
        return pack_bench(args)

    from ..serve.service import ChainServeService

    log = get_logger()
    root = args.root or tempfile.mkdtemp(prefix="chain-serve-soak-")
    corpus: Optional[dict] = None
    if args.executor == "chain":
        # a real synthetic corpus: mixed-complexity SRCs + a bitrate
        # ladder, driven through the full p01-p04 stages
        corpus = make_chain_corpus(root, args.srcs, args.hrcs)
    service = ChainServeService(
        root=root, port=0, executor=args.executor,
        workers=args.workers, wave_width=args.wave_width,
        wave_budget_s=args.wave_budget_s,
    ).start()
    report: dict = {"clients": args.clients, "srcs": args.srcs,
                    "hrcs": args.hrcs, "overlap": args.overlap,
                    "executor": args.executor, "workers": args.workers,
                    "wave_width": args.wave_width, "root": root}
    failures: list[str] = []
    try:
        planned_before = _planned_serve_jobs()
        tenants = [f"tenant{i % 3}" for i in range(args.clients)]
        results: list[Optional[dict]] = [None] * args.clients
        geometry = [64, 36]

        def _body(i: int, priority: str) -> dict:
            if corpus is not None:
                return {
                    "tenant": tenants[i],
                    "priority": priority,
                    "database": corpus["database"],
                    **_corpus_grid(i, corpus, args.overlap),
                    "params": {"config": corpus["config"]},
                }
            return {
                "tenant": tenants[i],
                "priority": priority,
                "database": "P2STR01",
                **_grid(i, args.srcs, args.hrcs, args.overlap),
                "params": {"geometry": geometry, "size_bytes": 2048},
            }

        def _client(i: int) -> None:
            results[i] = service.submit(
                _body(i, ("interactive", "normal", "bulk")[i % 3])
            )

        t0 = time.perf_counter()
        threads = [threading.Thread(target=_client, args=(i,))
                   for i in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        req_ids = [r["request"] for r in results if r]
        wait_s = 600.0 if corpus is not None else 120.0
        states = {rid: service.wait_request(rid, timeout=wait_s)
                  for rid in req_ids}
        cold_wall_s = time.perf_counter() - t0
        incomplete = sorted(r for r, s in states.items() if s != "done")
        if incomplete:
            failures.append(f"requests never completed: {incomplete}")

        # dedup invariant: executions == unique plans
        unique_plans = set()
        for rid in req_ids:
            doc = service.request_status(rid)
            unique_plans.update(u["plan"] for u in doc["units"].values())
        planned = _planned_serve_jobs() - planned_before
        report.update(
            requests=len(req_ids),
            units_total=sum(
                len(service.request_status(rid)["units"]) for rid in req_ids
            ),
            unique_plans=len(unique_plans),
            jobs_planned=planned,
            cold_wall_s=round(cold_wall_s, 3),
        )
        if planned != len(unique_plans):
            failures.append(
                f"duplicate executions: {planned} jobs planned for "
                f"{len(unique_plans)} unique plans"
            )

        if corpus is not None and req_ids:
            # all four stages really ran: every unit's manifest names a
            # verified store object per artifact family (every request
            # walked — the grids overlap, but each must resolve)
            families_missing: set = {"segments", "metadata", "avpvs",
                                     "cpvs"}
            units_to_verify: dict = {}
            for rid in req_ids:
                doc = service.request_status(rid)
                for unit in (doc or {}).get("units", {}).values():
                    units_to_verify[unit["plan"]] = unit
            for unit in units_to_verify.values():
                manifest = service.store.lookup(unit["plan"])
                if manifest is None:
                    failures.append(
                        f"unit manifest {unit['plan']} not in the store")
                    continue
                with open(service.store.object_path(
                        manifest.object["sha256"])) as f:
                    artifacts = json.load(f)["artifacts"]
                for family, entry in artifacts.items():
                    entries = entry if isinstance(entry, list) else [entry]
                    for one in entries:
                        m = service.store.lookup(one["plan"])
                        if m is None:
                            failures.append(
                                f"{family} artifact {one['name']} not "
                                "in the store")
                            continue
                        service.store.verify_object(m.object)
                        families_missing.discard(family)
            if families_missing:
                failures.append(
                    f"artifact families never produced: "
                    f"{sorted(families_missing)}")
            report["artifact_families"] = sorted(
                {"segments", "metadata", "avpvs", "cpvs"}
                - families_missing)
            report["cost"] = service.cost_ledger.report()

        # per-phase latency percentiles (queue-wait vs execution vs
        # end-to-end), from the span journal's exact timestamps
        e2e_s = []
        for rid in req_ids:
            doc = service.request_status(rid)
            if doc and doc.get("latency_ms") is not None:
                e2e_s.append(doc["latency_ms"] / 1e3)
        report["latency_phases"] = phase_latencies(root, e2e_s)
        if not report["latency_phases"]["queue_wait_ms"]:
            failures.append("span journal recorded no claim spans — "
                            "phase latency accounting is broken")

        # warm pass: same grids again — store hits, millisecond latency
        warm_latencies = []
        for i in range(args.clients):
            body = _body(i, "interactive")
            t1 = time.perf_counter()
            accepted = service.submit(body)
            state = service.wait_request(accepted["request"], timeout=30.0)
            warm_ms = (time.perf_counter() - t1) * 1e3
            warm_latencies.append(round(warm_ms, 3))
            if state != "done":
                failures.append(
                    f"warm request {accepted['request']} state {state}"
                )
            if not accepted.get("latency_ms"):
                failures.append(
                    f"warm request {accepted['request']} was not answered "
                    "at submit time (latency_ms missing)"
                )
        planned_after_warm = _planned_serve_jobs() - planned_before
        if planned_after_warm != planned:
            failures.append(
                f"warm pass executed {planned_after_warm - planned} job(s); "
                "expected 0"
            )
        warm_sorted = sorted(warm_latencies)
        report.update(
            warm_request_ms={
                "min": warm_sorted[0],
                "p50": warm_sorted[len(warm_sorted) // 2],
                "max": warm_sorted[-1],
            },
            warm_jobs_planned=planned_after_warm - planned,
        )
        if warm_sorted[-1] > args.warm_budget_ms:
            failures.append(
                f"warm request latency {warm_sorted[-1]:.1f} ms over the "
                f"{args.warm_budget_ms:.0f} ms budget"
            )
        if args.calibrate_out:
            # fit from whatever the soak observed (a soak is a
            # deliberate sample, so no minimum-ring gate) and report
            # the full auditable document next to the model error
            from ..serve import cost as serve_cost

            fitted = serve_cost.fit_scale(
                service.cost_ledger.ratios(), min_samples=1
            )
            cal_doc = {
                "fitted": fitted,
                "applied_base_scale": serve_cost.calibration_scale(),
                "model_error": service.cost_ledger.report()["model_error"],
                "host_cpu_count": os.cpu_count(),
            }
            atomic_write_text(
                args.calibrate_out,
                json.dumps(cal_doc, sort_keys=True) + "\n",
            )
            report["calibration"] = cal_doc
            if fitted is None:
                failures.append(
                    "--calibrate-out: no observed/predicted ratios to "
                    "fit from (did any unit execute?)"
                )
    finally:
        service.stop()
    report["failures"] = failures
    report["ok"] = not failures
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    if failures:
        for f in failures:
            log.error("serve-soak: %s", f)
        return 1
    log.info(
        "serve-soak: OK — %d requests, %d unique plans, %d executions, "
        "warm p50 %.1f ms",
        report["requests"], report["unique_plans"], report["jobs_planned"],
        report["warm_request_ms"]["p50"],
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
