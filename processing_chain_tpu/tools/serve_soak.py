"""`tools serve-soak` — the dedup/fairness/latency proof harness.

Runs an in-process chain-serve service, fires N concurrent synthetic
clients whose SRC×HRC grids deliberately OVERLAP, waits for every
request to finish, then asserts the serving economics the design
promises (ROADMAP open item #2, docs/SERVE.md):

  * zero duplicate executions — `chain_jobs_planned_total{runner=serve}`
    must equal the number of UNIQUE plan hashes across all requests;
  * every request completes;
  * a warm re-run of the same grids answers in milliseconds
    (measured, reported, and gated against --warm-budget-ms).

The report also breaks the cold pass's latency into the SLO phases
the fleet layer grades (docs/TELEMETRY.md "Fleet observability"):
p50/p95/p99 of queue-wait (enqueue→claim) and execution (claim→settle)
from the span journal's exact timestamps, plus request end-to-end —
so a soak regression says WHICH phase moved, not just that warm p50
did.

Prints one JSON report line (the `SERVE_SOAK_*.json` artifact committed
with the PR) and exits nonzero on any violated invariant.

    python -m processing_chain_tpu tools serve-soak
        [--clients 8] [--srcs 6] [--hrcs 4] [--overlap 0.5]
        [--executor synthetic] [--workers 4] [--wave-width 4]
        [--warm-budget-ms 1000] [--out FILE] [--root DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import tempfile
import threading
import time
from typing import Optional, Sequence

from .. import telemetry as tm
from ..utils.fsio import atomic_write_text
from ..utils.log import get_logger


def _grid(client: int, n_srcs: int, n_hrcs: int, overlap: float) -> dict:
    """Client grids share a common core (the overlap fraction) and add a
    per-client disjoint tail — the 'million users requesting overlapping
    SRC×HRC grids' shape, miniaturized."""
    shared = max(1, int(n_srcs * overlap))
    srcs = [f"SRC{100 + i:03d}" for i in range(shared)]
    srcs += [f"SRC{500 + client * 16 + i:03d}"
             for i in range(n_srcs - shared)]
    hrcs = [f"HRC{100 + i:03d}" for i in range(n_hrcs)]
    return {"srcs": srcs, "hrcs": hrcs}


def _percentiles_ms(values: list) -> Optional[dict]:
    """{p50, p95, p99} in milliseconds (exact order statistics — the
    soak has every observation, no bucket estimate needed)."""
    from ..telemetry.fleet import percentile_exact

    if not values:
        return None
    return {"p50": round(percentile_exact(values, 0.50) * 1e3, 3),
            "p95": round(percentile_exact(values, 0.95) * 1e3, 3),
            "p99": round(percentile_exact(values, 0.99) * 1e3, 3),
            "n": len(values)}


def phase_latencies(root: str, e2e_s: list) -> dict:
    """Per-phase latency percentiles from the span journal (queue-wait
    and execution ride the claim/settle spans) + the caller's request
    end-to-end samples."""
    from ..serve import spans as serve_spans

    journal = serve_spans.read_journals(
        os.path.join(root, "queue", "spans"))
    queue_wait = [s["queue_wait_s"] for s in journal
                  if s.get("phase") == "claim"
                  and s.get("queue_wait_s") is not None]
    execution = [s["exec_s"] for s in journal
                 if s.get("phase") == "complete"
                 and s.get("exec_s") is not None and not s.get("warm")]
    return {
        "queue_wait_ms": _percentiles_ms(queue_wait),
        "execution_ms": _percentiles_ms(execution),
        "e2e_ms": _percentiles_ms(e2e_s),
    }


def _planned_serve_jobs() -> int:
    metric = tm.REGISTRY.snapshot().get("chain_jobs_planned_total")
    if not metric:
        return 0
    return int(sum(
        s.get("value", 0) for s in metric["series"]
        if s.get("labels", {}).get("runner") == "serve"
    ))


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="tools serve-soak")
    parser.add_argument("--clients", type=int, default=8)
    parser.add_argument("--srcs", type=int, default=6)
    parser.add_argument("--hrcs", type=int, default=4)
    parser.add_argument("--overlap", type=float, default=0.5,
                        help="fraction of each grid shared across clients")
    parser.add_argument("--executor", default="synthetic")
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--wave-width", type=int, default=4)
    parser.add_argument("--warm-budget-ms", type=float, default=1000.0,
                        help="warm-hit request latency gate (per request)")
    parser.add_argument("--out", default=None,
                        help="also write the JSON report here")
    parser.add_argument("--root", default=None,
                        help="serve root (default: a fresh temp dir)")
    args = parser.parse_args(list(argv) if argv is not None else None)

    from ..serve.service import ChainServeService

    log = get_logger()
    root = args.root or tempfile.mkdtemp(prefix="chain-serve-soak-")
    service = ChainServeService(
        root=root, port=0, executor=args.executor,
        workers=args.workers, wave_width=args.wave_width,
    ).start()
    report: dict = {"clients": args.clients, "srcs": args.srcs,
                    "hrcs": args.hrcs, "overlap": args.overlap,
                    "executor": args.executor, "workers": args.workers,
                    "wave_width": args.wave_width, "root": root}
    failures: list[str] = []
    try:
        planned_before = _planned_serve_jobs()
        tenants = [f"tenant{i % 3}" for i in range(args.clients)]
        results: list[Optional[dict]] = [None] * args.clients
        geometry = [64, 36]

        def _client(i: int) -> None:
            body = {
                "tenant": tenants[i],
                "priority": ("interactive", "normal", "bulk")[i % 3],
                "database": "P2STR01",
                **_grid(i, args.srcs, args.hrcs, args.overlap),
                "params": {"geometry": geometry, "size_bytes": 2048},
            }
            results[i] = service.submit(body)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=_client, args=(i,))
                   for i in range(args.clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        req_ids = [r["request"] for r in results if r]
        states = {rid: service.wait_request(rid, timeout=120.0)
                  for rid in req_ids}
        cold_wall_s = time.perf_counter() - t0
        incomplete = sorted(r for r, s in states.items() if s != "done")
        if incomplete:
            failures.append(f"requests never completed: {incomplete}")

        # dedup invariant: executions == unique plans
        unique_plans = set()
        for rid in req_ids:
            doc = service.request_status(rid)
            unique_plans.update(u["plan"] for u in doc["units"].values())
        planned = _planned_serve_jobs() - planned_before
        report.update(
            requests=len(req_ids),
            units_total=sum(
                len(service.request_status(rid)["units"]) for rid in req_ids
            ),
            unique_plans=len(unique_plans),
            jobs_planned=planned,
            cold_wall_s=round(cold_wall_s, 3),
        )
        if planned != len(unique_plans):
            failures.append(
                f"duplicate executions: {planned} jobs planned for "
                f"{len(unique_plans)} unique plans"
            )

        # per-phase latency percentiles (queue-wait vs execution vs
        # end-to-end), from the span journal's exact timestamps
        e2e_s = []
        for rid in req_ids:
            doc = service.request_status(rid)
            if doc and doc.get("latency_ms") is not None:
                e2e_s.append(doc["latency_ms"] / 1e3)
        report["latency_phases"] = phase_latencies(root, e2e_s)
        if not report["latency_phases"]["queue_wait_ms"]:
            failures.append("span journal recorded no claim spans — "
                            "phase latency accounting is broken")

        # warm pass: same grids again — store hits, millisecond latency
        warm_latencies = []
        for i in range(args.clients):
            body = {
                "tenant": tenants[i], "priority": "interactive",
                "database": "P2STR01",
                **_grid(i, args.srcs, args.hrcs, args.overlap),
                "params": {"geometry": geometry, "size_bytes": 2048},
            }
            t1 = time.perf_counter()
            accepted = service.submit(body)
            state = service.wait_request(accepted["request"], timeout=30.0)
            warm_ms = (time.perf_counter() - t1) * 1e3
            warm_latencies.append(round(warm_ms, 3))
            if state != "done":
                failures.append(
                    f"warm request {accepted['request']} state {state}"
                )
            if not accepted.get("latency_ms"):
                failures.append(
                    f"warm request {accepted['request']} was not answered "
                    "at submit time (latency_ms missing)"
                )
        planned_after_warm = _planned_serve_jobs() - planned_before
        if planned_after_warm != planned:
            failures.append(
                f"warm pass executed {planned_after_warm - planned} job(s); "
                "expected 0"
            )
        warm_sorted = sorted(warm_latencies)
        report.update(
            warm_request_ms={
                "min": warm_sorted[0],
                "p50": warm_sorted[len(warm_sorted) // 2],
                "max": warm_sorted[-1],
            },
            warm_jobs_planned=planned_after_warm - planned,
        )
        if warm_sorted[-1] > args.warm_budget_ms:
            failures.append(
                f"warm request latency {warm_sorted[-1]:.1f} ms over the "
                f"{args.warm_budget_ms:.0f} ms budget"
            )
    finally:
        service.stop()
    report["failures"] = failures
    report["ok"] = not failures
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    if failures:
        for f in failures:
            log.error("serve-soak: %s", f)
        return 1
    log.info(
        "serve-soak: OK — %d requests, %d unique plans, %d executions, "
        "warm p50 %.1f ms",
        report["requests"], report["unique_plans"], report["jobs_planned"],
        report["warm_request_ms"]["p50"],
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
