"""SRC corpus analysis: md5 + .yaml probe sidecars.

Parity target: reference util/SRC_analysis.py:17-211. For every SRC video it
(1) writes or verifies an `<src>.md5` sidecar and (2) writes an `<src>.yaml`
info sidecar bundling probed stream info, exact stream sizes, and the md5.
The .yaml sidecars are the probe cache the config layer consumes during YAML
parsing (reference ffmpeg.py:604-632 / io/probe.py here), so running this
tool ahead of a chain run removes all probe work from the critical path.

Differences from the reference (deliberate):
  * probing goes through the native libav boundary (io.medialib), not
    ffprobe subprocesses;
  * md5 hashing fans out over a thread pool (hashlib releases the GIL on
    large buffers) instead of a fork pool;
  * results are returned as structured records, and the md5 summary file is
    written with one line per file (the reference's dump_log writes the
    pooled list without separators when concurrency > 1).
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import io as _io
import os
from dataclasses import dataclass
from typing import Optional, Sequence

from ..io import probe as probe_mod
from ..utils.fsio import atomic_write_text
from ..utils.log import get_logger
from ..utils.runner import ParallelRunner

VIDEO_EXTENSIONS = ("mp4", "avi", "mov", "mkv", "y4m")


def md5sum(path: str, chunk_size: int = _io.DEFAULT_BUFFER_SIZE) -> str:
    """Streaming md5 of a file (reference util/SRC_analysis.py:33-43)."""
    digest = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_size), b""):
            digest.update(chunk)
    return digest.hexdigest()


def read_md5_sidecar(sidecar_path: str) -> Optional[str]:
    """First token of the first line — accepts both bare digests and
    `md5sum` CLI format `<digest>  <name>` (reference :87-91)."""
    if not os.path.isfile(sidecar_path):
        return None
    with open(sidecar_path) as f:
        line = f.readline().strip()
    return line.split(" ")[0] if line else None


@dataclass
class Md5Result:
    file: str
    digest: str
    status: str  # "ok" | "BAD" | "written"

    def summary(self) -> str:
        base = os.path.basename(self.file)
        if self.status == "ok":
            return f"ok    -- File: {base} has a correct md5sum"
        if self.status == "BAD":
            return f"BAD!! -- File: {base} has an erroneous md5sum"
        return f"md5sum file written for file: {base}"


def check_or_write_md5(video_path: str) -> Md5Result:
    """Verify the .md5 sidecar if present, else compute and write it
    (reference sum_file, util/SRC_analysis.py:83-104)."""
    sidecar = os.path.abspath(video_path) + ".md5"
    existing = read_md5_sidecar(sidecar)
    current = md5sum(video_path)
    if existing is not None:
        status = "ok" if existing == current else "BAD"
        return Md5Result(video_path, current, status)
    atomic_write_text(
        sidecar, f"{current} {os.path.basename(video_path)}\n")
    return Md5Result(video_path, current, "written")


def src_siti_summary(video_path: str, chunk: int = 64) -> dict:
    """Device-computed SI/TI summary of a SRC (mean/max/p95 over frames):
    the "SRC_analysis consumes device-side feature tensors" leg of the
    north star (BASELINE.json). Streams the decode in CHUNK batches
    through the same ops.siti kernels as the p03 sidecars (fused Pallas on
    TPU); O(chunk) memory for any SRC length. Values are on the 8-BIT
    scale regardless of container depth (10-bit luma is normalized like
    tools/quality_metrics does), so summaries compare across SRC depths."""
    import jax.numpy as jnp
    import numpy as np

    from ..engine import prefetch as pf
    from ..io.video import VideoReader
    from ..ops import siti as siti_ops

    si_parts, ti_parts = [], []
    prev = None
    with VideoReader(video_path) as reader:
        # SI/TI are stds of linear functions of the luma: computing at
        # container depth and scaling the RESULTS by 0.25 equals scaling
        # the 10-bit planes first
        depth_scale = 0.25 if reader.dtype == np.uint16 else 1.0
        for planes in pf.iter_plane_chunks(reader, chunk):
            y = jnp.asarray(planes[0])
            si_parts.append(siti_ops.si_frames(y))
            ti, prev = siti_ops.ti_frames_continued(y, prev)
            ti_parts.append(ti)
    si = np.concatenate([np.asarray(s) for s in si_parts]) * depth_scale
    ti = np.concatenate([np.asarray(t) for t in ti_parts]) * depth_scale
    return {
        "si_mean": round(float(si.mean()), 4),
        "si_max": round(float(si.max()), 4),
        "si_p95": round(float(np.percentile(si, 95)), 4),
        "ti_mean": round(float(ti.mean()), 4),
        "ti_max": round(float(ti.max()), 4),
        "ti_p95": round(float(np.percentile(ti, 95)), 4),
    }


def analyse_src(video_path: str, with_siti: bool = False) -> str:
    """Write the `<src>.yaml` info sidecar and return its path (reference
    analyse_src, util/SRC_analysis.py:119-147). The sidecar schema
    {md5sum, get_stream_size: {v, a}, get_src_info} is the contract with
    io/probe.LibavProber.src_info's cache reader; `with_siti` adds a
    `siti` block of device-computed P.910 features (an extension — the
    reference has no SRC feature pass)."""
    sidecar = video_path + ".yaml"
    # LibavProber writes the full sidecar (info + stream sizes) itself; we
    # then stamp the md5 from the .md5 sidecar if one exists.
    if os.path.isfile(sidecar):
        os.remove(sidecar)
    prober = probe_mod.LibavProber()
    prober.src_info(video_path, sidecar_path=sidecar)

    md5_path = video_path + ".md5"
    md5 = read_md5_sidecar(md5_path) or md5sum(video_path)

    import yaml

    with open(sidecar) as f:
        data = yaml.safe_load(f)
    data["md5sum"] = md5
    if with_siti:
        data["siti"] = src_siti_summary(video_path)
    atomic_write_text(
        sidecar, yaml.safe_dump(data, default_flow_style=False))
    return sidecar


def backfill_siti(video_path: str) -> str:
    """Merge a SI/TI block into an existing, otherwise-intact sidecar —
    one decode pass, no md5 re-hash, no re-probe."""
    import yaml

    sidecar = video_path + ".yaml"
    with open(sidecar) as f:
        data = yaml.safe_load(f) or {}
    data["siti"] = src_siti_summary(video_path)
    atomic_write_text(
        sidecar, yaml.safe_dump(data, default_flow_style=False))
    return sidecar


def collect_video_files(inputs: Sequence[str]) -> list[str]:
    """Expand files/directories into a sorted list of video files
    (reference :160-169)."""
    files: list[str] = []
    for entry in inputs:
        if os.path.isdir(entry):
            for ext in VIDEO_EXTENSIONS:
                files.extend(glob.glob(os.path.join(entry, f"*.{ext}")))
        elif os.path.isfile(entry):
            files.append(entry)
        else:
            get_logger().warning("%s is not a file or folder, skipping", entry)
    return sorted(files)


def run(
    inputs: Sequence[str],
    concurrency: int = 4,
    skip_md5: bool = False,
    skip_src: bool = False,
    force: bool = False,
    summary_path: Optional[str] = "./outsummary_md5.txt",
    with_siti: bool = False,
) -> dict:
    """Analyse all SRCs; returns {"md5": [Md5Result…], "sidecars": [path…]}."""
    log = get_logger()
    all_files = collect_video_files(inputs)
    backfill: list[str] = []
    if force:
        files = all_files
    else:
        files = []
        for f in all_files:
            sidecar = f + ".yaml"
            if not os.path.isfile(sidecar):
                files.append(f)
                continue
            if not with_siti:
                continue
            # --siti over previously analysed SRCs must add the feature
            # block, not silently no-op behind the existing-sidecar skip —
            # and an intact sidecar only needs the ONE decode pass merged
            # in, not a fresh md5 + re-probe
            import yaml

            try:
                data = yaml.safe_load(open(sidecar)) or {}
            except Exception:
                files.append(f)
                continue
            if "siti" not in data:
                backfill.append(f)
    log.info(
        "%d files will be processed%s", len(files),
        f" (+{len(backfill)} siti backfills)" if backfill else "",
    )

    out: dict = {"md5": [], "sidecars": []}
    if not skip_md5 and files:
        runner = ParallelRunner(max_parallel=concurrency, name="md5")
        for f in files:
            runner.add(check_or_write_md5, f, label=f)
        results = runner.run()
        out["md5"] = [results[f] for f in files]
        for r in out["md5"]:
            log.info("%s", r.summary())
        if summary_path:
            atomic_write_text(
                summary_path,
                "".join(r.summary() + "\n" for r in out["md5"]))

    if not skip_src and (files or backfill):
        runner = ParallelRunner(max_parallel=concurrency, name="src-info")
        for f in files:
            runner.add(analyse_src, f, with_siti, label=f)
        for f in backfill:
            runner.add(backfill_siti, f, label=f)
        results = runner.run()
        out["sidecars"] = [results[f] for f in files + backfill]
        for path in out["sidecars"]:
            log.info("wrote %s", path)
    return out


def build_parser(parser: Optional[argparse.ArgumentParser] = None) -> argparse.ArgumentParser:
    p = parser or argparse.ArgumentParser(
        "src-analysis", description="Create .md5 and .yaml sidecars for SRC videos"
    )
    p.add_argument("input", nargs="+", help="path to input file(s) or folder")
    p.add_argument("-p", "--concurrency", type=int, default=4,
                   help="number of parallel workers")
    p.add_argument("-m", "--skip-md5", action="store_true",
                   help="do not calculate or verify md5 sums")
    p.add_argument("-s", "--skip-src", action="store_true",
                   help="do not probe or write .yaml info sidecars")
    p.add_argument("-f", "--force-overwrite", action="store_true",
                   help="force overwrite of existing .yaml sidecars")
    p.add_argument("--siti", action="store_true",
                   help="add a device-computed SI/TI summary (P.910 "
                        "mean/max/p95) to each .yaml sidecar")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    run(
        args.input,
        concurrency=args.concurrency,
        skip_md5=args.skip_md5,
        skip_src=args.skip_src,
        force=args.force_overwrite,
        with_siti=args.siti,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
