"""Operator surface of the artifact store: `tools store <cmd>`.

    tools store ls       [--store DIR]             manifest inventory
    tools store verify   [--store DIR] [--deep] [--drop]
    tools store gc       [--store DIR] [--max-bytes N] [--dry-run]
                         [--tmp-max-age S] [--min-object-age S]
    tools store pin      [--store DIR] HASH [--label TEXT]
    tools store unpin    [--store DIR] HASH
    tools store tier     [--store DIR] ls
    tools store tier     [--store DIR] promote|demote HASH

The store root resolves like the pipeline's: --store DIR, else
PC_STORE_DIR; the placement spec (hot/warm/cold tiers, docs/STORE.md
"Tier hierarchy") resolves from --tiers SPEC, else PC_STORE_TIERS. `verify` deep-checks every manifest's objects and exits 1
when corruption is found (counted in chain_store_corrupt_total); with
--drop, corrupt manifests are removed so the next pipeline run rebuilds
exactly those artifacts. `gc` is store.gc.collect with a human report —
the same run-report ergonomics as `tools run-report` (docs/STORE.md).
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Optional, Sequence

from ..store import gc as store_gc
from ..store import heat as store_heat
from ..store.store import ArtifactStore, StoreCorruption
from ..utils.log import get_logger


def _human_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def _parse_bytes(text: str) -> int:
    """'500M', '2G', '1024' → bytes."""
    text = text.strip().upper()
    mult = 1
    for suffix, m in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30),
                      ("T", 1 << 40)):
        if text.endswith(suffix) or text.endswith(suffix + "B"):
            text = text[: -1 - text.endswith(suffix + "B")]
            mult = m
            break
    return int(float(text) * mult)


def _open_store(root: Optional[str],
                tiers: Optional[str] = None) -> ArtifactStore:
    root = root or os.environ.get("PC_STORE_DIR") or ""
    if not root:
        raise ValueError(
            "no store root: pass --store DIR or set PC_STORE_DIR"
        )
    if not os.path.isdir(root):
        # admin never creates a store (the pipeline does): a mistyped
        # root must error, not mkdir an empty tree and report a false
        # "verified 0 ok" all-clear
        raise ValueError(f"store root {root} does not exist")
    # plan-exempt: (names WHERE artifact bytes are placed, never what they contain)
    tiers = tiers or os.environ.get("PC_STORE_TIERS") or None
    return ArtifactStore(root, tier_spec=tiers)


def _cmd_ls(store: ArtifactStore) -> int:
    pins = store.pins()
    rows = []
    for m in store.iter_manifests():
        age_s = max(0.0, time.time() - m.created_at) if m.created_at else 0.0
        size = m.object.get("size", 0)
        size += sum(d.get("size", 0) for d in m.sidecars.values())
        size += sum(d.get("size", 0) for d in m.extras.values())
        rows.append((
            m.plan_hash[:12],
            _human_bytes(size),
            f"{age_s / 3600:.1f}h",
            "pin" if m.plan_hash in pins else "",
            "adopted" if m.provenance.get("adopted") else "",
            m.producer,
        ))
    if not rows:
        print(f"{store.root}: empty store")
        return 0
    widths = [max(len(r[i]) for r in rows) for i in range(5)]
    for r in rows:
        print("  ".join(c.ljust(w) for c, w in zip(r[:5], widths)) + "  " + r[5])
    s = store.stats()
    print(
        f"-- {s['manifests']} manifest(s), {s['objects']} object(s), "
        f"{_human_bytes(s['bytes'])}, {s['pins']} pin(s)"
    )
    return 0


def _cmd_verify(store: ArtifactStore, deep: bool, drop: bool) -> int:
    ok = 0
    corrupt = []
    # unparseable manifest files first: lookup reports them as misses
    # (read paths must not mutate the store), so iter_manifests would
    # silently walk past them — verify is where they must surface
    for name in sorted(os.listdir(store.manifests_dir)):
        if not name.endswith(".json"):
            continue
        ph = name[:-5]
        if (store.lookup(ph) is None
                and os.path.isfile(store.manifest_path(ph))):
            corrupt.append((ph, None, "manifest unreadable/unparseable"))
    for m in store.iter_manifests():
        try:
            for digest in m.all_digests():
                store.verify_object(digest, deep=deep)
            ok += 1
        except StoreCorruption as exc:
            corrupt.append((m.plan_hash, m, str(exc)))
    for ph, m, why in corrupt:
        print(f"CORRUPT {ph[:12]} ({m.producer if m else '?'}): {why}")
        if drop:
            if m is not None:
                # bytes go with the manifest: a rebuild re-produces the
                # same digest and _ingest would dedupe onto the corrupt
                # object (unknowable for an unparseable manifest — its
                # orphaned objects fall to `gc`)
                store.drop_corrupt_objects(m)
            store._drop_manifest(ph)
    if corrupt and drop:
        print(
            f"dropped {len(corrupt)} corrupt manifest(s); the next "
            "pipeline run rebuilds exactly those artifacts (orphaned "
            "objects are swept by `tools store gc`)"
        )
    print(
        f"-- verified {ok} ok, {len(corrupt)} corrupt "
        f"({'deep' if deep else 'spot'} check)"
    )
    return 1 if corrupt else 0


def _cmd_gc(store: ArtifactStore, max_bytes: Optional[int], dry_run: bool,
            tmp_max_age: float, min_object_age: float) -> int:
    # a real (non-dry) pass journals its evictions so the serve fleet's
    # regret detector sees operator-driven evictions too — the CLI and
    # the pressure hook must not keep separate forensic truths
    heat = None if dry_run else store_heat.HeatLedger(
        store.root, replica="store-gc"
    )
    report = store_gc.collect(
        store, size_budget_bytes=max_bytes, dry_run=dry_run,
        tmp_max_age_s=tmp_max_age, min_object_age_s=min_object_age,
        heat=heat,
    )
    tag = "[dry-run] " if dry_run else ""
    print(f"{tag}tmp swept:        {report['tmp_removed']}")
    print(f"{tag}orphans removed:  {report['orphans_removed']} "
          f"({_human_bytes(report['orphan_bytes'])})")
    if report["demotions"]:
        print(f"{tag}demoted:          {len(report['demotions'])} "
              f"object(s) ({_human_bytes(report['demoted_bytes'])})")
        for d in report["demotions"]:
            print(f"{tag}  demote {d['object'][:12]}  "
                  f"{d['from_tier']} -> {d['to_tier']}  "
                  f"{d.get('reads', 0)} recorded read(s)  "
                  f"{_human_bytes(d['bytes'])}")
    print(f"{tag}manifests evicted:{len(report['evicted_manifests']):>2} "
          f"({_human_bytes(report['evicted_bytes'])})")
    # per-victim evidence: the SAME dicts the store_evict events and
    # the heat ledger's forensics journal carry (store/gc.py) — tier
    # included, so the render says which tier the bytes actually left
    for v in report["victims"]:
        if v["reason"] == "orphan":
            print(f"{tag}  orphan {v['object'][:12]}  "
                  f"tier {v.get('tier', 'hot')}  "
                  f"age {v['age_s'] / 3600:.1f}h  "
                  f"freed {_human_bytes(v['freed_bytes'])}")
        else:
            print(f"{tag}  evict {v['plan'][:12]}  over budget  "
                  f"from {v.get('tier', 'hot')}  "
                  f"last used {v['last_used_age_s'] / 3600:.1f}h ago  "
                  f"{v['reads']} recorded read(s)  "
                  f"freed {_human_bytes(v['freed_bytes'])}")
    print(f"{tag}kept:             {report['kept_manifests']} manifest(s), "
          f"{_human_bytes(report['kept_bytes'])}")
    print(f"{tag}freed:            {_human_bytes(report['bytes_freed'])} "
          f"({report['objects_evicted']} object(s)); "
          f"{report['pins_honored']} pin(s) honored")
    if heat is not None:
        heat.close()
    return 0


def _cmd_tier(store: ArtifactStore, action: str,
              ref: Optional[str]) -> int:
    """Placement inspection and manual moves (docs/STORE.md "Tier
    hierarchy"). `promote`/`demote` accept a plan hash (moves every
    object the manifest references) or a bare object sha256."""
    tiers = store.tiers
    if action == "ls":
        stats = tiers.tier_stats()
        for t in tiers.tiers:
            s = stats[t.name]
            budget = (_human_bytes(t.budget_bytes)
                      if t.budget_bytes else "-")
            print(f"{t.name:<8} {t.backend.kind:<7} "
                  f"{s['objects']:>6} object(s)  "
                  f"{_human_bytes(s['bytes']):>10}  budget {budget}")
        if not tiers.multi:
            print("-- single-tier store (no --tiers / PC_STORE_TIERS "
                  "spec in force)")
        return 0
    if not ref:
        raise ValueError(f"tier {action} needs a plan hash or object "
                         "sha256")
    manifest = store.lookup(ref)
    if manifest is not None:
        shas = [(d["sha256"], ref) for d in manifest.all_digests()]
    else:
        shas = [(ref, None)]
    # manual moves journal like automatic ones: the forensics trail
    # must not have operator-shaped holes
    heat = store_heat.HeatLedger(store.root, replica="store-admin")
    status = 0
    try:
        for sha, plan in shas:
            src = tiers.locate(sha)
            if src is None:
                print(f"absent  {sha[:12]}: in no tier")
                status = 1
                continue
            if action == "promote":
                evidence = tiers.promote(sha, plan=plan, heat=heat)
                if evidence is None:
                    print(f"noop    {sha[:12]}: already hot")
                    continue
            else:
                i = tiers.tiers.index(src)
                if i == len(tiers.tiers) - 1:
                    print(f"noop    {sha[:12]}: already in last tier "
                          f"({src.name})")
                    continue
                evidence = tiers.demote(sha, src, tiers.tiers[i + 1],
                                        plan=plan, heat=heat)
            print(f"{evidence['op']:<8}{evidence['object'][:12]}  "
                  f"{evidence['from_tier']} -> {evidence['to_tier']}  "
                  f"{_human_bytes(evidence['bytes'])}")
    finally:
        heat.close()
    return status


def main(argv: Optional[Sequence[str]] = None) -> int:
    # --store is accepted both before and after the subcommand (the
    # docs show the natural `tools store verify --store DIR` order).
    # SUPPRESS keeps an unset subparser occurrence from clobbering a
    # pre-subcommand value with its default.
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--store", default=argparse.SUPPRESS, metavar="DIR",
                        help="store root (default: PC_STORE_DIR)")
    common.add_argument("--tiers", default=argparse.SUPPRESS,
                        metavar="SPEC",
                        help="hot/warm/cold placement spec "
                        "(default: PC_STORE_TIERS; see docs/STORE.md)")
    parser = argparse.ArgumentParser(prog="tools store", description=__doc__,
                                     parents=[common])
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("ls", help="manifest inventory", parents=[common])
    p_verify = sub.add_parser("verify", help="integrity-check every object",
                              parents=[common])
    p_verify.add_argument("--deep", action="store_true",
                          help="full content digest for every object "
                          "(default: size + head/full spot check)")
    p_verify.add_argument("--drop", action="store_true",
                          help="remove corrupt manifests so the next run "
                          "rebuilds them")
    p_gc = sub.add_parser("gc", help="mark-and-sweep garbage collection",
                          parents=[common])
    p_gc.add_argument("--max-bytes", default=None, metavar="N",
                      help="LRU size budget (accepts K/M/G suffixes)")
    p_gc.add_argument("--dry-run", action="store_true")
    p_gc.add_argument("--tmp-max-age", default=3600.0, type=float,
                      metavar="S", help="sweep tmp/ entries older than S")
    p_gc.add_argument("--min-object-age", default=3600.0, type=float,
                      metavar="S", help="never sweep objects younger than S")
    p_pin = sub.add_parser("pin", help="exempt a plan hash from GC",
                           parents=[common])
    p_pin.add_argument("plan_hash")
    p_pin.add_argument("--label", default="")
    p_unpin = sub.add_parser("unpin", help="remove a pin", parents=[common])
    p_unpin.add_argument("plan_hash")
    p_tier = sub.add_parser("tier", help="tier placement: inspect and "
                            "move objects", parents=[common])
    p_tier.add_argument("action", choices=("ls", "promote", "demote"))
    p_tier.add_argument("ref", nargs="?", default=None,
                        help="plan hash or object sha256 "
                        "(promote/demote)")
    args = parser.parse_args(argv)

    store = _open_store(getattr(args, "store", None),
                        getattr(args, "tiers", None))
    if args.cmd == "ls":
        return _cmd_ls(store)
    if args.cmd == "verify":
        return _cmd_verify(store, deep=args.deep, drop=args.drop)
    if args.cmd == "gc":
        max_bytes = _parse_bytes(args.max_bytes) if args.max_bytes else None
        return _cmd_gc(store, max_bytes, args.dry_run, args.tmp_max_age,
                       args.min_object_age)
    if args.cmd == "tier":
        return _cmd_tier(store, args.action, args.ref)
    if args.cmd == "pin":
        store.pin(args.plan_hash, args.label)
        get_logger().info("pinned %s", args.plan_hash[:12])
        return 0
    store.unpin(args.plan_hash)
    get_logger().info("unpinned %s", args.plan_hash[:12])
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
