"""store-heat: the access-heat report and the read-heavy zipf soak.

Two subcommands over the heat ledger (store/heat.py — the read-path
flight recorder of the artifact plane):

    tools store-heat report SOURCE [--top N] [--json]
    tools store-heat soak   [--plans 12] [--reads 400] [--out FILE]
                            [--root DIR] [--budget-fraction 0.35]

`report` renders the fleet-merged ledger of one store (SOURCE is a
store root, or a serve root whose `store/` is the conventional layout):
totals, the 304 edge-hit ratio, per-replica sums, the top-N plans by
reads and by bytes, and the working-set curve — "X% of bytes serve Y%
of reads", the promotion/demotion signal ROADMAP item 2's tiering
needs.

`soak` is the measured acceptance harness (committed as
STORE_HEAT_r16.json): two in-process replicas over one store, a warm
build of mixed-size plans, a zipf-distributed read storm with
conditional GETs (nonzero 304 ratio on re-reads), then the regret
experiment — under an ADEQUATE budget nothing is evicted and regret is
zero; under a deliberately UNDERSIZED budget the GC evicts with
forensics and the soak's re-reads and one re-POST fire
`chain_store_eviction_regret_total` via both paths (read and rebuild).
Exit 1 when any invariant fails, serve-soak style.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import tempfile
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from ..store import heat as store_heat
from ..utils.fsio import atomic_write_text
from ..utils.log import get_logger


def _resolve_heat_dir(source: str) -> str:
    """SOURCE may be a store root (holding heat/) or a serve root
    (holding store/heat)."""
    direct = store_heat.heat_dir(source)
    if os.path.isdir(direct):
        return direct
    nested = store_heat.heat_dir(os.path.join(source, "store"))
    if os.path.isdir(nested):
        return nested
    return direct


def _curve_headline(curve: list) -> Optional[dict]:
    """The smallest hot-set prefix covering 90% of reads — the one
    sentence an operator sizes a cache tier from."""
    for point in curve:
        if point["reads_frac"] >= 0.9:
            return point
    return curve[-1] if curve else None


def _downsample(curve: list, points: int = 10) -> list:
    if len(curve) <= points:
        return curve
    step = len(curve) / points
    picked = [curve[min(len(curve) - 1, int(i * step))]
              for i in range(1, points)]
    picked.append(curve[-1])
    return picked


def _cmd_report(source: str, top: int, as_json: bool) -> int:
    root = _resolve_heat_dir(source)
    agg = store_heat.aggregate(root)
    curve = store_heat.working_set_curve(agg["per_plan"])
    totals = agg["totals"]
    ratio_304 = (totals["not_modified"] / totals["reads"]
                 if totals["reads"] else 0.0)
    if as_json:
        print(json.dumps({
            "heat_dir": root,
            "totals": totals,
            "ratio_304": round(ratio_304, 4),
            "by_replica": agg["by_replica"],
            "by_tier": agg["by_tier"],
            "working_set_curve": _downsample(curve),
        }, sort_keys=True))
        return 0
    if not totals["reads"] and not totals["evictions"]:
        print(f"{root}: no heat records")
        return 0
    print(f"heat ledger: {root}")
    print(f"reads: {totals['reads']} (full={totals['full']} "
          f"304={totals['not_modified']} range={totals['range']}, "
          f"304 ratio {ratio_304:.1%})  "
          f"bytes served: {totals['bytes'] / 1e6:.1f} MB")
    print(f"evictions: {totals['evictions']}  "
          f"regrets: {totals['regrets']}  "
          f"promotions: {totals['promotions']}  "
          f"demotions: {totals['demotions']}")
    for rep in sorted(agg["by_replica"]):
        entry = agg["by_replica"][rep]
        print(f"  replica {rep:<28} reads {entry['reads']:>6}  "
              f"bytes {entry['bytes'] / 1e6:9.1f} MB")
    if agg["by_tier"]:
        print("reads by tier (where the byte was found):")
        for tier in sorted(agg["by_tier"]):
            entry = agg["by_tier"][tier]
            frac = (entry["reads"] / totals["reads"]
                    if totals["reads"] else 0.0)
            print(f"  tier {tier:<8} reads {entry['reads']:>6} "
                  f"({frac:6.1%})  bytes {entry['bytes'] / 1e6:9.1f} MB")
    by_reads = sorted(agg["per_plan"].items(),
                      key=lambda kv: -kv[1]["reads"])[:top]
    if by_reads:
        print(f"top {len(by_reads)} plans by reads:")
        for plan, entry in by_reads:
            age = time.time() - entry["last_ts"] if entry["last_ts"] else 0
            tiers = "".join(
                f" {t}={n}" for t, n in sorted(entry["tiers"].items()))
            print(f"  {plan[:12]}  reads {entry['reads']:>5} "
                  f"(304 {entry['not_modified']})  "
                  f"{store_heat.plan_size(entry) / 1e6:7.2f} MB  "
                  f"last read {age / 60:.1f}m ago"
                  + (f"  tiers:{tiers}" if tiers else ""))
    by_bytes = sorted(agg["per_plan"].items(),
                      key=lambda kv: -kv[1]["bytes"])[:top]
    if by_bytes:
        print(f"top {len(by_bytes)} plans by bytes served:")
        for plan, entry in by_bytes:
            print(f"  {plan[:12]}  served {entry['bytes'] / 1e6:9.2f} MB "
                  f"over {entry['reads']} read(s)")
    headline = _curve_headline(curve)
    if headline:
        print(
            f"working set: {headline['plans']} plan(s) = "
            f"{headline['bytes_frac']:.0%} of bytes serve "
            f"{headline['reads_frac']:.0%} of reads"
        )
        for point in _downsample(curve):
            print(f"  {point['plans']:>4} plans: "
                  f"{point['bytes_frac']:7.1%} of bytes -> "
                  f"{point['reads_frac']:7.1%} of reads")
    return 0


# -------------------------------------------------------------- the soak


def _get(url: str, etag: Optional[str] = None,
         timeout: float = 30.0) -> tuple:
    """(status, etag, body_bytes, elapsed_s) for one artifact GET;
    urllib surfaces 304 as an HTTPError, which for this probe is just
    another answer."""
    req = urllib.request.Request(url)
    if etag:
        req.add_header("If-None-Match", etag)
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            return (resp.status, resp.headers.get("ETag"), len(body),
                    time.perf_counter() - t0)
    except urllib.error.HTTPError as exc:
        exc.read()
        return (exc.code, exc.headers.get("ETag"), 0,
                time.perf_counter() - t0)


def _zipf_rank(rng: random.Random, n: int) -> int:
    """A zipf(1)-distributed rank in [0, n): hot-head, long-tail — the
    read mix a content cache actually sees."""
    weights = [1.0 / (k + 1) for k in range(n)]
    return rng.choices(range(n), weights=weights, k=1)[0]


def _read_percentiles(urls: list, timeout_s: float = 5.0) -> dict:
    """p50/p99 per (phase × size class) from the replicas' merged
    /metrics histograms — the same estimate path the fleet view grades
    SLOs with (telemetry/fleet.py)."""
    from ..telemetry import fleet

    parsed = []
    for url in urls:
        try:
            with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                        timeout=timeout_s) as resp:
                text = resp.read().decode(errors="replace")
        except (urllib.error.URLError, TimeoutError, OSError):
            continue
        parsed.append(fleet.parse_histograms(
            text, fleet.READ_PHASE_METRICS.values()))
    merged = fleet.merge_histograms(parsed)
    out: dict = {}
    for (name, _), series in sorted(merged.items()):
        phase = next(p for p, m in fleet.READ_PHASE_METRICS.items()
                     if m == name)
        size_class = series["labels"].get("size_class", "?")
        cell = out.setdefault(phase, {}).setdefault(size_class, {})
        cell["n"] = series["count"]
        for frac in (0.50, 0.99):
            est = fleet.percentile_from_buckets(series["buckets"], frac)
            cell[f"p{int(frac * 100)}_s"] = \
                round(est, 6) if est is not None else None
    return out


def _cmd_soak(args) -> int:
    from ..serve.service import ChainServeService

    log = get_logger()
    root = args.root or tempfile.mkdtemp(prefix="chain-store-heat-")
    rng = random.Random(0xBEEF)
    # mixed size classes on purpose: the read SLO grades per size
    # class, so the soak must populate more than one row
    sizes = [4096 if i % 2 else (1 << 20) + 4096
             for i in range(args.plans)]
    replicas = [
        ChainServeService(
            root=root, port=0, executor="synthetic", workers=2,
            replica=f"heat{i}",
            info_path=os.path.join(root, f"serve-info-heat{i}.json"),
        ).start()
        for i in range(2)
    ]
    report: dict = {"plans": args.plans, "reads": args.reads,
                    "root": root, "replicas": 2}
    failures: list[str] = []
    try:
        # ---- warm phase: one plan per request, driven through replica 0
        req_ids = []
        for i in range(args.plans):
            req_ids.append(replicas[0].submit({
                "tenant": "soak",
                "priority": "normal",
                "database": "P2STR01",
                "srcs": [f"SRC{100 + i:03d}"],
                "hrcs": ["HRC100"],
                "params": {"geometry": [64, 36], "size_bytes": sizes[i],
                           "work_ms": 1.0},
            })["request"])
        plans: list[str] = []
        for rid in req_ids:
            if replicas[0].wait_request(rid, timeout=60.0) != "done":
                failures.append(f"warm request {rid} never completed")
                continue
            doc = replicas[0].request_status(rid)
            plans.extend(u["plan"] for u in doc["units"].values())
        if len(plans) != args.plans:
            failures.append(
                f"warm store holds {len(plans)}/{args.plans} plans")

        # ---- read storm: zipf-ranked, alternating replicas, with
        # conditional re-reads (every other revisit sends the ETag)
        etags: dict = {}
        seen: dict = {}
        by_status: dict = {}
        for r in range(args.reads):
            plan = plans[_zipf_rank(rng, len(plans))]
            svc = replicas[r % 2]
            url = f"{svc.server.url}/v1/artifacts/{plan}?tenant=soak"
            visits = seen.get(plan, 0)
            conditional = visits > 0 and visits % 2 == 1
            status, etag, _, _ = _get(
                url, etag=etags.get(plan) if conditional else None)
            seen[plan] = visits + 1
            if etag:
                etags[plan] = etag
            by_status[status] = by_status.get(status, 0) + 1
        report["reads_by_status"] = by_status
        if by_status.get(200, 0) == 0:
            failures.append("read storm produced no 200s")
        if by_status.get(304, 0) == 0:
            failures.append("conditional re-reads produced no 304s")
        if by_status.get(404, 0):
            failures.append(
                f"{by_status[404]} 404(s) before any eviction")

        # ---- adequate budget: nothing is over budget, regret stays 0
        heat_root = store_heat.heat_dir(replicas[0].store.root)
        agg = store_heat.aggregate(heat_root)
        totals = agg["totals"]
        replica_sum = {
            "reads": sum(e["reads"] for e in agg["by_replica"].values()),
            "bytes": sum(e["bytes"] for e in agg["by_replica"].values()),
        }
        if (totals["reads"] != replica_sum["reads"]
                or totals["bytes"] != replica_sum["bytes"]):
            failures.append(
                f"fleet-merged totals {totals} disagree with "
                f"per-replica sums {replica_sum}")
        report["ledger_totals"] = dict(totals)
        report["ledger_by_replica"] = agg["by_replica"]
        report["ratio_304"] = round(
            totals["not_modified"] / totals["reads"], 4) \
            if totals["reads"] else 0.0
        report["regret_adequate_budget"] = totals["regrets"]
        if totals["regrets"]:
            failures.append(
                f"{totals['regrets']} regret(s) under an adequate "
                "budget — must be zero")
        curve = store_heat.working_set_curve(agg["per_plan"])
        report["working_set_curve"] = _downsample(curve)
        headline = _curve_headline(curve)
        if headline:
            report["working_set_90pct_reads"] = headline

        # ---- undersized budget: force the pressure pass, demand
        # forensic evictions
        store_bytes = replicas[0].store.stats()["bytes"]
        budget = max(1, int(store_bytes * args.budget_fraction))
        report["store_bytes"] = store_bytes
        report["undersized_budget_bytes"] = budget
        replicas[0].pressure.budget_bytes = budget
        summary = replicas[0].pressure.maybe_collect(force=True)
        evicted = list((summary or {}).get("evicted_manifests", []))
        victims = list((summary or {}).get("victims", []))
        report["evicted"] = len(evicted)
        if not evicted:
            failures.append(
                f"undersized budget ({budget} of {store_bytes} bytes) "
                "evicted nothing")
        if len(victims) < len(evicted) or any(
                "last_used_age_s" not in v for v in victims
                if v.get("reason") == "over_budget"):
            failures.append("evictions missing per-victim evidence")

        # ---- regret, via read: replica 1 re-reads what replica 0's
        # pressure pass just evicted (cross-replica: the detector reads
        # the peer journal)
        for plan in evicted[:3]:
            status, _, _, _ = _get(
                f"{replicas[1].server.url}/v1/artifacts/{plan}"
                "?tenant=soak")
            if status != 404:
                failures.append(
                    f"evicted plan answered {status}, expected 404")
        # ---- regret, via rebuild: re-POST one evicted plan; the queue
        # remembers the completion, the store no longer holds it
        if evicted:
            i = plans.index(evicted[0]) if evicted[0] in plans else 0
            rid = replicas[0].submit({
                "tenant": "soak",
                "priority": "normal",
                "database": "P2STR01",
                "srcs": [f"SRC{100 + i:03d}"],
                "hrcs": ["HRC100"],
                "params": {"geometry": [64, 36],
                           "size_bytes": sizes[i], "work_ms": 1.0},
            })["request"]
            replicas[0].wait_request(rid, timeout=60.0)
        regrets = {"read": 0, "rebuild": 0}
        for record in store_heat.read_journals(heat_root):
            if record.get("kind") == "regret":
                via = record.get("via", "?")
                regrets[via] = regrets.get(via, 0) + 1
        report["regret_undersized_budget"] = regrets
        if evicted and not regrets["read"]:
            failures.append("re-reading evicted plans fired no read "
                            "regret")
        if evicted and not regrets["rebuild"]:
            failures.append("re-POSTing an evicted plan fired no "
                            "rebuild regret")

        # ---- read SLO percentiles from the /metrics histograms. Both
        # in-process replicas render the ONE process-wide registry, so
        # scraping one already covers the fleet — merging both would
        # double-count n (real fleets are one process per replica)
        report["read_latency"] = _read_percentiles(
            [replicas[0].server.url])
        if not report["read_latency"].get("read_ttfb_s"):
            failures.append("no read TTFB observations in /metrics")
    finally:
        for svc in replicas:
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 - report the soak, not the teardown
                log.warning("store-heat soak: replica stop failed",
                            exc_info=True)

    report["failures"] = failures
    report["ok"] = not failures
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    if failures:
        for f in failures:
            log.error("store-heat soak: %s", f)
        return 1
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools store-heat", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_report = sub.add_parser(
        "report", help="fleet-merged heat report of one store")
    p_report.add_argument(
        "source",
        help="store root (holding heat/) or serve root (store/heat)")
    p_report.add_argument("--top", type=int, default=10,
                          help="plans per top-N table")
    p_report.add_argument("--json", action="store_true",
                          help="machine-readable aggregate")
    p_soak = sub.add_parser(
        "soak", help="2-replica zipf read soak + regret experiment")
    p_soak.add_argument("--plans", type=int, default=12,
                        help="distinct plans to warm (mixed sizes)")
    p_soak.add_argument("--reads", type=int, default=400,
                        help="zipf-distributed GETs across the fleet")
    p_soak.add_argument("--budget-fraction", type=float, default=0.35,
                        help="undersized budget as a fraction of the "
                             "warm store's bytes")
    p_soak.add_argument("--out", default=None,
                        help="write the JSON report here too")
    p_soak.add_argument("--root", default=None,
                        help="serve root (default: fresh temp dir)")
    args = parser.parse_args(list(argv) if argv is not None else None)

    if args.cmd == "report":
        return _cmd_report(args.source, args.top, args.json)
    return _cmd_soak(args)


if __name__ == "__main__":
    raise SystemExit(main())
