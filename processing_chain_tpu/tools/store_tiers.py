"""store-tiers: the tiered-placement soak (STORE_TIERS_r17.json).

    tools store-tiers soak [--plans 12] [--reads 300] [--replicas 2]
                           [--out FILE] [--root DIR]

The measured acceptance harness for the hot/warm/cold placement layer
(store/tiers.py, docs/STORE.md "Tier hierarchy"): N in-process serve
replicas over ONE tiered store whose hot tier is deliberately
UNDERSIZED against the warm build, then

  * a forced pressure pass demotes the coldest objects down the
    hierarchy (demote before evict — with no total budget nothing may
    be evicted, and nothing ever regrets);
  * a zipf-distributed read storm falls through hot→warm→cold, counts
    per-tier hits in the heat ledger, and read-through-promotes the
    hot head back up;
  * the same probe set is timed with promotion DISABLED (every read
    streams from wherever the bytes sit) and again after the
    promotion storm (the hot head serves from the local fd path) —
    the p99 pair is the "what does the hot tier buy" headline;
  * ranged reads (RFC 9110 single-range) answer 206 and land in the
    ledger as their own read mode;
  * a final pressure pass squeezes the re-promoted head back under
    the hot budget, and every manifest must still integrity-verify
    from whichever tier holds its bytes.

Prints one JSON report line and exits 1 when any invariant fails
(zero evictions, zero regret, demotions observed, promotions
observed, hits in ≥2 tiers, 206s served, hot tier back under budget,
all manifests verify).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import tempfile
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence

from ..store import heat as store_heat
from ..utils.fsio import atomic_write_text
from ..utils.log import get_logger
from .store_heat import _zipf_rank


def _get(url: str, range_header: Optional[str] = None,
         timeout: float = 30.0) -> tuple:
    """(status, body_len, elapsed_s) for one artifact GET; 3xx/4xx
    surface as HTTPError, which for this probe is just another answer."""
    req = urllib.request.Request(url)
    if range_header:
        req.add_header("Range", range_header)
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            return resp.status, len(body), time.perf_counter() - t0
    except urllib.error.HTTPError as exc:
        exc.read()
        return exc.code, 0, time.perf_counter() - t0


def _p99_ms(values: list) -> Optional[float]:
    from ..telemetry.fleet import percentile_exact

    if not values:
        return None
    return round(percentile_exact(values, 0.99) * 1e3, 3)


def _cmd_soak(args) -> int:
    from ..serve.service import ChainServeService

    log = get_logger()
    root = args.root or tempfile.mkdtemp(prefix="chain-store-tiers-")
    rng = random.Random(0x71E2)
    # hot@1M against ~6 MB of mixed-size plans: the hot tier CANNOT
    # hold the build, so the demotion path must fire; warm@3M forces a
    # further spill into cold, populating all three rungs
    hot_budget = 1 << 20
    spec = (f"hot@{hot_budget},local={os.path.join(root, 'warm')}@3M,"
            f"object={os.path.join(root, 'cold')}")
    sizes = [4096 if i % 2 else (1 << 20) + 4096
             for i in range(args.plans)]
    replicas = [
        ChainServeService(
            root=root, port=0, executor="synthetic", workers=2,
            replica=f"tier{i}", store_tiers=spec,
            info_path=os.path.join(root, f"serve-info-tier{i}.json"),
        ).start()
        for i in range(args.replicas)
    ]
    store = replicas[0].store
    report: dict = {"plans": args.plans, "reads": args.reads,
                    "replicas": args.replicas, "root": root,
                    "tier_spec": spec, "hot_budget_bytes": hot_budget}
    failures: list[str] = []
    try:
        # ---- warm build: everything ingests hot -----------------------
        req_ids = [
            replicas[0].submit({
                "tenant": "soak", "priority": "normal",
                "database": "P2STR01",
                "srcs": [f"SRC{100 + i:03d}"], "hrcs": ["HRC100"],
                "params": {"geometry": [64, 36], "size_bytes": sizes[i],
                           "work_ms": 1.0},
            })["request"]
            for i in range(args.plans)
        ]
        plans: list[str] = []
        for rid in req_ids:
            if replicas[0].wait_request(rid, timeout=120.0) != "done":
                failures.append(f"warm request {rid} never completed")
                continue
            doc = replicas[0].request_status(rid)
            plans.extend(u["plan"] for u in doc["units"].values())
        report["tier_stats_warm"] = store.tiers.tier_stats()

        # ---- demotion under pressure: hot is over ITS budget, there
        # is no total budget — demote, never evict
        summary = replicas[0].pressure.maybe_collect(force=True) or {}
        report["demotions_initial"] = len(summary.get("demotions", []))
        report["tier_stats_demoted"] = store.tiers.tier_stats()
        if not summary.get("demotions"):
            failures.append("forced pressure pass over an undersized "
                            "hot tier demoted nothing")
        if summary.get("evicted_manifests"):
            failures.append(
                f"{len(summary['evicted_manifests'])} eviction(s) with "
                "no total budget — demote-before-evict is broken")
        # hot may legitimately be EMPTY here (objects bigger than the
        # whole hot budget demote entirely); what must hold is that the
        # spill crossed both lower rungs
        populated = {n for n, s in store.tiers.tier_stats().items()
                     if s["objects"]}
        missing = {"warm", "cold"} - populated
        if missing:
            failures.append(f"tier(s) {sorted(missing)} hold nothing "
                            "after demotion — the spill never got there")

        # ---- p99 WITHOUT the hot tier: promotion off, every read
        # streams from wherever its bytes sit (the demoted warm/cold
        # head included)
        for svc in replicas:
            svc.store.tiers.promote_on_read = False
        probe_set = plans[:: max(1, len(plans) // 8)]
        cold_ms: list[float] = []
        for _ in range(3):
            for plan in probe_set:
                svc = replicas[rng.randrange(len(replicas))]
                status, _, dt = _get(
                    f"{svc.server.url}/v1/artifacts/{plan}?tenant=soak")
                if status != 200:
                    failures.append(
                        f"unpromoted read answered {status}, expected 200")
                cold_ms.append(dt)
        report["p99_ms_without_hot"] = _p99_ms(cold_ms)

        # ---- the zipf storm, promotion on: the hot head climbs back --
        for svc in replicas:
            svc.store.tiers.promote_on_read = True
        by_status: dict = {}
        for r in range(args.reads):
            plan = plans[_zipf_rank(rng, len(plans))]
            svc = replicas[r % len(replicas)]
            status, _, _ = _get(
                f"{svc.server.url}/v1/artifacts/{plan}?tenant=soak")
            by_status[status] = by_status.get(status, 0) + 1
        report["storm_by_status"] = by_status
        if by_status.get(404, 0):
            failures.append(f"{by_status[404]} 404(s) in the storm — "
                            "placement lost an object")
        warm_ms: list[float] = []
        for _ in range(3):
            for plan in probe_set:
                svc = replicas[rng.randrange(len(replicas))]
                status, _, dt = _get(
                    f"{svc.server.url}/v1/artifacts/{plan}?tenant=soak")
                warm_ms.append(dt)
        report["p99_ms_with_hot"] = _p99_ms(warm_ms)
        report["tier_stats_storm"] = store.tiers.tier_stats()
        if not report["tier_stats_storm"]["hot"]["objects"]:
            failures.append("the storm left the hot tier empty — "
                            "read-through promotion moved nothing up")

        # ---- ranged reads: RFC 9110 single-range, own ledger mode ----
        ranged_206 = 0
        for plan in probe_set:
            svc = replicas[0]
            status, n, _ = _get(
                f"{svc.server.url}/v1/artifacts/{plan}?tenant=soak",
                range_header="bytes=0-1023")
            if status == 206 and n == 1024:
                ranged_206 += 1
            else:
                failures.append(f"ranged read answered {status} with "
                                f"{n} byte(s), expected 206/1024")
        report["ranged_reads"] = {"requested": len(probe_set),
                                  "status_206": ranged_206}

        # ---- final squeeze: the promoted head must fit hot again -----
        summary = replicas[0].pressure.maybe_collect(force=True) or {}
        report["demotions_final"] = len(summary.get("demotions", []))
        report["tier_stats_final"] = store.tiers.tier_stats()
        hot_bytes = report["tier_stats_final"]["hot"]["bytes"]
        if hot_bytes > hot_budget:
            failures.append(f"hot tier holds {hot_bytes} bytes after "
                            f"the final pass, over its {hot_budget} "
                            "budget")

        # ---- the ledger's verdict ------------------------------------
        heat_root = store_heat.heat_dir(store.root)
        agg = store_heat.aggregate(heat_root)
        totals = agg["totals"]
        report["ledger_totals"] = dict(totals)
        hits = {t: dict(e) for t, e in agg["by_tier"].items()}
        for entry in hits.values():
            entry["hit_ratio"] = (
                round(entry["reads"] / totals["reads"], 4)
                if totals["reads"] else 0.0)
        report["per_tier_hits"] = hits
        if totals["promotions"] == 0:
            failures.append("the storm promoted nothing — read-through "
                            "promotion never fired")
        if totals["demotions"] == 0:
            failures.append("ledger records no demotions")
        if totals["range"] == 0:
            failures.append("ranged reads left no range-mode ledger "
                            "records")
        if totals["evictions"] or totals["regrets"]:
            failures.append(
                f"{totals['evictions']} eviction(s) / "
                f"{totals['regrets']} regret(s) under an adequate total "
                "budget — both must be zero")
        if len([t for t, e in hits.items() if e["reads"]]) < 2:
            failures.append(f"reads hit only {sorted(hits)} — the "
                            "fall-through path never crossed a tier "
                            "boundary")

        # ---- integrity: every manifest verifies from whichever tier
        # holds its bytes now
        from ..store.store import StoreCorruption

        for plan in plans:
            manifest = store.lookup(plan)
            if manifest is None:
                failures.append(f"plan {plan[:12]}… lost its manifest")
                continue
            try:
                store.verify_object(manifest.object)
            except StoreCorruption as exc:
                failures.append(f"plan {plan[:12]}… fails verification "
                                f"after placement: {exc}")
    finally:
        for svc in replicas:
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 - report the soak, not the teardown
                log.warning("store-tiers soak: replica stop failed",
                            exc_info=True)

    report["failures"] = failures
    report["ok"] = not failures
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        atomic_write_text(args.out, line + "\n")
    if failures:
        for f in failures:
            log.error("store-tiers soak: %s", f)
        return 1
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tools store-tiers", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_soak = sub.add_parser(
        "soak", help="tiered-placement soak over an undersized hot tier")
    p_soak.add_argument("--plans", type=int, default=12,
                        help="distinct plans to warm (mixed sizes)")
    p_soak.add_argument("--reads", type=int, default=300,
                        help="zipf-distributed GETs across the fleet")
    p_soak.add_argument("--replicas", type=int, default=2,
                        help="in-process serve replicas over the store")
    p_soak.add_argument("--out", default=None,
                        help="write the JSON report here too")
    p_soak.add_argument("--root", default=None,
                        help="serve root (default: fresh temp dir)")
    args = parser.parse_args(list(argv) if argv is not None else None)
    return _cmd_soak(args)


if __name__ == "__main__":
    raise SystemExit(main())
